"""Cross-process span/event pipeline — Dapper-shaped observability for the
MapReduce control plane.

The reference's only observability is fmt.Printf progress lines
(SURVEY.md §5), and before this module the runtime was barely better off
across process boundaries: workers collected rich `Metrics` and per-scan
`engine.stats` that died with the process.  Here every task attempt emits
structured spans — read → kernel scan → confirm/stitch → shuffle → commit —
tagged with (job, task, attempt, worker) ids, plus instant events for
degrade/fallback transitions.  Workers buffer records in a bounded
`SpanBuffer` and flush them piggybacked on the existing Heartbeat /
TaskFinished RPCs (optional fields elided from the wire when empty, so old
peers interop); the coordinator persists everything as `events.jsonl` in
the work dir (`EventLog`) and estimates per-worker clock offsets from
heartbeat RTT midpoints (`ClockSync`) so spans from different hosts align.
`export_chrome_trace` renders the log as Chrome trace_event JSON
(Perfetto / TensorBoard-loadable) — one row per worker, a coordinator row
for scheduling decisions, engine sub-spans from per-scan telemetry.

Everything is a no-op unless a worker/coordinator switches the pipeline on
(JobConfig.spans or DGREP_SPANS=1): no ambient task context means `span` /
`instant` / `scan_record` return immediately, RPC payloads carry no extra
fields, and no file is ever written — the hot paths pay nothing in
production (the same contract as utils/trace.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from pathlib import Path

from distributed_grep_tpu.utils import event_audit, lockdep

_ENV_VAR = "DGREP_SPANS"

# Bounded buffering: a match-dense job can emit one scan record per chunk;
# past the cap records drop (counted, reported as a spans_dropped instant)
# rather than grow worker memory or RPC payloads without bound.
BUFFER_CAP = 4096
FLUSH_MAX = 512  # records per RPC piggyback — bounds heartbeat body size


def env_enabled() -> bool:
    """True when DGREP_SPANS switches the pipeline on process-wide."""
    return os.environ.get(_ENV_VAR, "") not in ("", "0")


def enabled(config_flag: bool = False) -> bool:
    """The effective on/off verdict: an explicit JobConfig.spans wins, the
    DGREP_SPANS env var forces on (operator override, like DGREP_TRACE_DIR)."""
    return bool(config_flag) or env_enabled()


class SpanBuffer:
    """Thread-safe bounded record buffer — one per worker loop.  Records are
    plain dicts (JSON-ready); `drain` hands out at most FLUSH_MAX per call
    so one RPC never ships an unbounded body."""

    def __init__(self, cap: int = BUFFER_CAP):
        self._lock = lockdep.make_lock("span-buffer")
        self._recs: list[dict] = []
        self.cap = cap
        self.dropped = 0
        # Tags applied to buffer-synthesized records (the spans_dropped
        # report) — emitted records carry their task_context tags already,
        # but the buffer itself needs to know at least (job, worker) so a
        # drop report renders on the owning worker's trace row, not the
        # coordinator's.  The owner updates this as ids become known.
        self.base_tags: dict = {}
        self.seq = 0  # batch counter (drain_batch) — the RPC dedup key

    def add(self, rec: dict) -> None:
        if event_audit.is_active() and rec.get("t") in ("span", "instant"):
            event_audit.record(rec["t"], rec.get("name"))
        with self._lock:
            if len(self._recs) >= self.cap:
                self.dropped += 1
                return
            self._recs.append(rec)

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)

    def drain(self, limit: int = FLUSH_MAX) -> list[dict]:
        """Remove and return up to `limit` buffered records.  A nonzero drop
        count is reported once (as a spans_dropped instant) when the buffer
        fully drains — silent truncation would read as full coverage."""
        with self._lock:
            return self._drain_locked(limit)

    def drain_batch(self, limit: int = FLUSH_MAX) -> tuple[int, list[dict]]:
        """drain() plus a per-buffer batch sequence number, allocated
        atomically with the drain — the RPC piggyback's dedup key: a
        transport-level retry reships the SAME (seq, batch), so the
        coordinator persists it once.  (-1, []) when nothing is buffered."""
        with self._lock:
            out = self._drain_locked(limit)
            if not out:
                return -1, out
            self.seq += 1
            return self.seq, out

    def _drain_locked(self, limit: int) -> list[dict]:
        out, self._recs = self._recs[:limit], self._recs[limit:]
        if self.dropped and not self._recs:
            out.append({
                **self.base_tags,
                "t": "instant", "name": "spans_dropped", "cat": "pipeline",
                "ts": time.time(), "args": {"count": self.dropped},
            })
            self.dropped = 0
        return out


# --------------------------------------------------------------- ambient ctx
# Thread-local task context: the worker loop opens it around each task
# attempt; code below it (engine scans, app hooks) emits without plumbing.
# Thread-local by design — worker slots share one process (and one app
# module), and each slot's attempt must tag its own records.
_tls = threading.local()


@contextmanager
def task_context(buffer: SpanBuffer, **tags):
    """Make `buffer` the current thread's span sink, tagging every record
    with `tags` (job/task/attempt/worker/kind).  Nests: the previous
    context is restored on exit."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = (buffer, tags)
    try:
        yield
    finally:
        _tls.ctx = prev


def active() -> bool:
    """True when the current thread is inside a task_context — the single
    gate every emitter checks, so disabled runs never build record dicts."""
    return getattr(_tls, "ctx", None) is not None


def _emit(rec: dict) -> None:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return
    buffer, tags = ctx
    buffer.add({**tags, **rec})


def complete(name: str, ts: float, dur: float, cat: str = "task",
             **args) -> None:
    """Emit an already-timed span (ts = wall-clock start, dur seconds)."""
    if not active():
        return
    rec: dict = {"t": "span", "name": name, "cat": cat,
                 "ts": ts, "dur": dur}
    if args:
        rec["args"] = args
    _emit(rec)


@contextmanager
def span(name: str, cat: str = "task", **args):
    """Timed region on the current task's row; no-op outside a context."""
    if not active():
        yield
        return
    t0 = time.time()
    try:
        yield
    finally:
        complete(name, t0, time.time() - t0, cat=cat, **args)


def instant(name: str, cat: str = "task", **args) -> None:
    """Point event (degrade/fallback transition); no-op outside a context."""
    if not active():
        return
    rec: dict = {"t": "instant", "name": name, "cat": cat, "ts": time.time()}
    if args:
        rec["args"] = args
    _emit(rec)


# Engine stats keys promoted into scan telemetry records when present
# (ops/engine.py / ops/device_scan.py populate them per scan).
_SCAN_STAT_KEYS = (
    "candidates", "confirm_seconds", "end_offsets",
    "feed_wait_seconds", "read_wait_seconds", "fdr_fallback",
)


def scan_record(mode: str, n_bytes: int, seconds: float,
                stats: dict | None = None, matches: int | None = None) -> None:
    """Per-scan engine telemetry: one span named scan:<mode> whose args are
    the structured form of `engine.stats` (candidates, confirm seconds,
    fallback flags).  The engine calls this after every scan(); it no-ops
    unless the scanning thread is inside a task_context."""
    if not active():
        return
    st = stats or {}
    args: dict = {
        "mode": mode,
        "bytes": int(n_bytes),
        # always present, both paths: the degraded-mode marker the
        # acceptance tests key on
        "device_fallback": bool(st.get("device_fallback", False)),
    }
    if matches is not None:
        args["matches"] = int(matches)
    for k in _SCAN_STAT_KEYS:
        if k in st:
            v = st[k]
            args[k] = round(v, 6) if isinstance(v, float) else v
    now = time.time()
    _emit({"t": "span", "name": f"scan:{mode}", "cat": "engine",
           "ts": now - seconds, "dur": seconds, "args": args})


def split_by_job(recs: list[dict], default: str = "") -> dict[str, list[dict]]:
    """Group span/event records by their 'job' tag, preserving order —
    the service daemon's per-job event routing (runtime/service.py): one
    drained worker batch may carry records from several jobs' attempts
    (the buffer flushes on whatever RPC goes next), and each group must
    land in ITS job's events.jsonl.  Records without a job tag fall to
    ``default`` (the RPC's own job)."""
    out: dict[str, list[dict]] = {}
    for r in recs:
        out.setdefault(r.get("job") or default, []).append(r)
    return out


# ------------------------------------------------------------- coordinator
class EventLog:
    """Append-only events.jsonl writer — the coordinator's persisted job
    event log in the work dir.  Thread-safe (RPC handler threads + the
    sweeper write concurrently); one JSON object per line."""

    FILENAME = "events.jsonl"

    def __init__(self, path: str | Path, fresh: bool = False):
        # fresh=True truncates (a fresh job on a reused work dir must not
        # splice a previous job's events); resume appends — one job, one
        # log across coordinator restarts.
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # io_ok: serializing the write+flush is this lock's purpose
        self._lock = lockdep.make_lock("event-log", io_ok=True)
        self._f = open(self.path, "w" if fresh else "a", encoding="utf-8")

    def write(self, rec: dict) -> None:
        self.write_many([rec])

    def write_many(self, recs: list[dict]) -> None:
        if not recs:
            return
        if event_audit.is_active():
            for r in recs:
                # non-event records (worker_clock observations, follow
                # cursor lines) pass through unaudited
                if r.get("t") in ("span", "instant"):
                    event_audit.record(r["t"], r.get("name"))
        lines = "".join(
            json.dumps(r, separators=(",", ":"), sort_keys=True,
                       default=str) + "\n"
            for r in recs
        )
        with self._lock:
            if self._f.closed:
                return  # late flush after job teardown: drop, don't crash
            self._f.write(lines)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    @staticmethod
    def read(path: str | Path) -> list[dict]:
        """Parse an events.jsonl; a torn final line (coordinator killed
        mid-write) is skipped, mirroring the journal's torn-tail policy."""
        out: list[dict] = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn tail / foreign line
        return out


class ClockSync:
    """Per-worker clock-offset estimation from heartbeat RTT midpoints.

    Each heartbeat carries the worker's wall-clock send time and its
    measured RTT for the previous heartbeat; the coordinator's receive time
    minus half that RTT estimates its own clock at the send instant, so
    offset = (recv - rtt/2) - sent_at, EWMA-smoothed.  Adding the offset to
    a worker's span timestamps aligns them with the coordinator row."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.offsets: dict[int, float] = {}
        self.rtts: dict[int, float] = {}

    def observe(self, worker_id: int, sent_at: float, recv_at: float,
                rtt_s: float) -> float | None:
        """Fold one heartbeat observation in; returns the updated offset
        estimate (seconds to ADD to worker timestamps), or None when the
        heartbeat carried no send timestamp (old worker / piggyback off)."""
        if worker_id < 0 or sent_at <= 0:
            return None
        rtt = rtt_s if rtt_s and rtt_s > 0 else 0.0
        est = (recv_at - rtt / 2.0) - sent_at
        prev = self.offsets.get(worker_id)
        cur = est if prev is None else prev + self.alpha * (est - prev)
        self.offsets[worker_id] = cur
        if rtt:
            self.rtts[worker_id] = rtt
        return cur


# ------------------------------------------------------------ trace export
# Record keys that are structural (row/time placement), not span payload.
_STRUCTURAL = {"t", "name", "cat", "ts", "dur", "worker", "args"}


def _tid_for(rec: dict) -> int:
    """Row assignment: coordinator records (no worker tag, or worker < 0)
    land on tid 0; worker N gets tid N+1."""
    w = rec.get("worker")
    if not isinstance(w, int) or w < 0:
        return 0
    return w + 1


def export_chrome_trace(events: list[dict]) -> dict:
    """Render event-log records as a Chrome trace_event JSON object
    ({"traceEvents": [...]}) — loadable in Perfetto (ui.perfetto.dev),
    chrome://tracing, and TensorBoard's trace viewer, the same viewers the
    jax.profiler device trace loads into (utils/trace.py).

    Timestamps are microseconds on the coordinator's clock: worker rows are
    shifted by the last persisted clock-offset estimate for that worker.
    """
    offsets: dict[int, float] = {}
    for r in events:
        if r.get("t") == "worker_clock" and isinstance(r.get("worker"), int):
            offsets[r["worker"]] = float(r.get("offset_s", 0.0))

    out: list[dict] = []
    pid = 1
    out.append({"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": "dgrep job"}})
    tids: dict[int, str] = {0: "coordinator"}
    for r in events:
        tid = _tid_for(r)
        if tid not in tids:
            tids[tid] = f"worker {r['worker']}"
    for tid, name in sorted(tids.items()):
        out.append({"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                    "args": {"name": name}})
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_sort_index", "args": {"sort_index": tid}})

    for r in events:
        t = r.get("t")
        if t not in ("span", "instant") or "ts" not in r:
            continue
        tid = _tid_for(r)
        w = r.get("worker")
        off = offsets.get(w, 0.0) if isinstance(w, int) and w >= 0 else 0.0
        args = {k: v for k, v in r.items() if k not in _STRUCTURAL}
        args.update(r.get("args") or {})
        ev: dict = {
            "name": str(r.get("name", "?")),
            "cat": str(r.get("cat", "event")),
            "pid": pid,
            "tid": tid,
            "ts": (float(r["ts"]) + off) * 1e6,
            "args": args,
        }
        if t == "span":
            ev["ph"] = "X"
            ev["dur"] = max(0.0, float(r.get("dur", 0.0))) * 1e6
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_fleet_trace(daemon_events: list[dict],
                       jobs: dict[str, list[dict]] | None = None) -> dict:
    """Render a whole work root — the daemon.jsonl fleet timeline
    (runtime/daemon_log.py) merged with every job's events.jsonl — as one
    Chrome trace (``trace-export --fleet``).

    Layout: pid 1 is the daemon fleet (sorted ABOVE the jobs), one row
    per lease epoch (epoch 0 = single-daemon) carrying the incarnation's
    lifetime as a span, its lifecycle events as instants, and — when a
    steal/acquire is followed by a ``promoted`` event — a synthesized
    ``promotion`` span whose width IS the failover latency.  Each job is
    its own process (pids 2+), rendered by export_chrome_trace
    unchanged, so a chaos SIGKILL-failover run reads top-to-bottom:
    which daemon served when, and what every job's workers were doing
    through the transition."""
    out: list[dict] = []
    pid = 1
    out.append({"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": "dgrep daemon fleet"}})
    out.append({"ph": "M", "pid": pid, "tid": 0,
                "name": "process_sort_index", "args": {"sort_index": 0}})
    by_epoch: dict[int, list[dict]] = {}
    for r in daemon_events:
        by_epoch.setdefault(int(r.get("epoch", 0)), []).append(r)
    for tid, epoch in enumerate(sorted(by_epoch)):
        recs = sorted(by_epoch[epoch], key=lambda r: r.get("ts", 0.0))
        pids = sorted({r["pid"] for r in recs if r.get("pid") is not None})
        label = f"daemon epoch {epoch}"
        if pids:
            label += f" (pid {pids[0]})"
        out.append({"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                    "args": {"name": label}})
        out.append({"ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_sort_index", "args": {"sort_index": tid}})
        stamps = [float(r["ts"]) for r in recs if "ts" in r]
        if stamps:
            # the incarnation's observed lifetime (first to last event)
            out.append({
                "name": f"lease epoch {epoch}", "cat": "lease", "ph": "X",
                "pid": pid, "tid": tid, "ts": min(stamps) * 1e6,
                "dur": max(0.0, max(stamps) - min(stamps)) * 1e6,
                "args": {"epoch": epoch},
            })
        steal_ts: float | None = None
        for r in recs:
            kind = str(r.get("kind", "?"))
            ts = float(r.get("ts", 0.0))
            args: dict = {"role": r.get("role"), "pid": r.get("pid")}
            args.update(r.get("payload") or {})
            if kind in ("lease_steal", "lease_acquire"):
                steal_ts = ts
            elif kind == "promoted" and steal_ts is not None:
                # promotion latency: stale-lease detection (the steal)
                # to serving — the gap the failover SLO histogram samples
                out.append({
                    "name": "promotion", "cat": "lease", "ph": "X",
                    "pid": pid, "tid": tid, "ts": steal_ts * 1e6,
                    "dur": max(0.0, ts - steal_ts) * 1e6,
                    "args": dict(args),
                })
                steal_ts = None
            out.append({"name": kind, "cat": "daemon", "ph": "i", "s": "t",
                        "pid": pid, "tid": tid, "ts": ts * 1e6,
                        "args": args})
    job_pid = 2
    for job_id in sorted(jobs or {}):
        doc = export_chrome_trace(jobs[job_id])
        out.append({"ph": "M", "pid": job_pid, "tid": 0,
                    "name": "process_sort_index",
                    "args": {"sort_index": job_pid}})
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = job_pid
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                ev["args"] = {"name": f"dgrep job {job_id}"}
            out.append(ev)
        job_pid += 1
    return {"traceEvents": out, "displayTimeUnit": "ms"}

"""Structured logging — replaces the reference's bare fmt.Printf/log.Fatal.

The reference logs progress with unstructured prints (coordinator.go:45,:79,
:127,:288; worker.go:48,:132,:173) and kills workers with log.Fatal
(worker.go:223).  Here: stdlib logging with a single consistent format and a
per-component child-logger helper.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"
_configured = False


def get_logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("DGREP_LOG", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        root = logging.getLogger("dgrep")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    return logging.getLogger(f"dgrep.{name}")

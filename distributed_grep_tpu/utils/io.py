"""IO helpers: chunked reads and the work-dir layout.

The reference's exactly-once story rests on write-to-temp + os.Rename as the
atomic commit (worker.go:103, worker.go:169); re-executed tasks overwrite
idempotently.  That protocol now lives in runtime/store.py as PosixStore —
one of two pluggable commit layers (NonAtomicStore emulates object-store
semantics, where rename does not exist); every data-plane write goes through
a Store.  The work-dir layout replaces the reference's /tmp/mr-data (host) +
/tmp/mr (remote) + SFTP star topology (coordinator.go:306-309, worker.go:19)
with a single shared root whose commit semantics come from its Store.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator


def read_chunks(path: str | Path, chunk_bytes: int, overlap: int = 0) -> Iterator[tuple[int, bytes]]:
    """Stream a file as (offset, chunk) pairs with an overlap halo.

    The reference reads whole files into memory (worker.go:72-76) and so
    cannot handle a file bigger than worker RAM; chunked streaming with a
    halo (>= max match length) is the long-context analogue (SURVEY.md §5).
    """
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    if overlap >= chunk_bytes:
        raise ValueError("overlap must be smaller than chunk_bytes")
    with open(path, "rb") as f:
        offset = 0
        carry = b""
        while True:
            block = f.read(chunk_bytes - len(carry))
            if not block:
                # EOF: any carried halo bytes were already yielded as part of
                # the previous chunk — never emit a carry-only chunk.
                return
            chunk = carry + block
            yield offset, chunk
            if len(chunk) < chunk_bytes:
                return
            carry = chunk[-overlap:] if overlap else b""
            offset += len(chunk) - len(carry)


def resolve_input_path(filename: str, workdir: "WorkDir") -> Path:
    """Input-split path resolution, shared by every data plane: absolute paths
    and existing cwd-relative paths are used as-is; bare names fall back to
    the work dir's inputs/ directory."""
    p = Path(filename)
    if not p.is_absolute() and not p.exists():
        p = workdir.root / "inputs" / p
    return p


class WorkDir:
    """Filesystem layout for one job under a shared root.

    inputs/         input splits (what SFTP-push of inputs becomes)
    intermediate/   mr-<map_task>-<r> shuffle files (coordinator.go:136-142)
    out/            mr-out-<r> final outputs (worker.go:169, coordinator.go:152)
    journal/        coordinator's durable task-commit journal
    commits/        per-task commit records — the unit of truth on stores
                    without atomic rename (runtime/store.py)

    ``store`` supplies the commit semantics for intermediate/out blobs
    (PosixStore by default — today's temp+fsync+rename).  Readers must go
    through the store (list_outputs does): on a NonAtomicStore the
    directories hold .part./.commit. attempt files, and only the store
    knows which attempt won.
    """

    def __init__(self, root: str | Path, store=None):
        if store is None:
            from distributed_grep_tpu.runtime.store import PosixStore

            store = PosixStore()
        self.store = store
        self.root = Path(root)
        for sub in ("inputs", "intermediate", "out", "journal", "commits"):
            (self.root / sub).mkdir(parents=True, exist_ok=True)

    def intermediate_path(self, map_task: int, reduce_part: int) -> Path:
        return self.root / "intermediate" / f"mr-{map_task}-{reduce_part}"

    def output_path(self, reduce_task: int) -> Path:
        return self.root / "out" / f"mr-out-{reduce_task}"

    def journal_path(self) -> Path:
        return self.root / "journal" / "tasks.jsonl"

    def commits_dir(self) -> Path:
        return self.root / "commits"

    def resolve_task_commit(self, kind: str, task_id: int):
        """The winning task commit record ({"parts": ...} payload dict), or
        None — the scheduler's unit of truth for completed work."""
        return self.store.resolve_task_commit(self.commits_dir(), kind, task_id)

    def clear(self) -> None:
        """Remove all job state (fresh-job reset of a reused work dir)."""
        for sub in ("inputs", "intermediate", "out", "journal", "commits"):
            for p in (self.root / sub).iterdir():
                if p.is_file():
                    p.unlink()

    def list_outputs(self) -> list[Path]:
        """Concrete paths of the committed mr-out-* blobs, sorted by logical
        name.  On a PosixStore these ARE mr-out-<r>; on a NonAtomicStore
        they are the winning .part. files — readers get exactly one fully
        committed attempt per output either way."""
        return self.store.list_committed(self.root / "out", "mr-out-*")

"""IO helpers: atomic commits, chunked reads, work-dir layout.

The reference's exactly-once story rests on write-to-temp + os.Rename as the
atomic commit (worker.go:103, worker.go:169); re-executed tasks overwrite
idempotently.  We keep exactly that design.  The work-dir layout replaces the
reference's /tmp/mr-data (host) + /tmp/mr (remote) + SFTP star topology
(coordinator.go:306-309, worker.go:19) with a single shared-FS root.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from pathlib import Path
from typing import Iterator


def atomic_write(path: str | Path, data: bytes) -> None:
    """Write-to-temp-then-rename: the reference's commit protocol."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic on POSIX; duplicate executions are safe
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_from_file(path: str | Path, src: str | Path,
                           chunk_bytes: int = 1 << 20) -> None:
    """Chunked copy-to-temp-then-rename: the atomic commit for outputs too
    large to hold in memory (the streaming-reduce path)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp")
    try:
        with os.fdopen(fd, "wb") as out, open(src, "rb") as f:
            shutil.copyfileobj(f, out, chunk_bytes)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_from_stream(path: str | Path, stream, length: int,
                             chunk_bytes: int = 1 << 20) -> None:
    """Read exactly ``length`` bytes from a stream into a temp file in
    bounded blocks, then rename-commit — the data-plane PUT receiver
    (bodies larger than RAM never materialize).  Raises ConnectionError on
    a short read so callers treat a died peer as a failed upload."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp")
    try:
        with os.fdopen(fd, "wb") as out:
            remaining = length
            while remaining > 0:
                block = stream.read(min(chunk_bytes, remaining))
                if not block:
                    raise ConnectionError(
                        f"short body: {remaining} of {length} bytes missing"
                    )
                out.write(block)
                remaining -= len(block)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_chunks(path: str | Path, chunk_bytes: int, overlap: int = 0) -> Iterator[tuple[int, bytes]]:
    """Stream a file as (offset, chunk) pairs with an overlap halo.

    The reference reads whole files into memory (worker.go:72-76) and so
    cannot handle a file bigger than worker RAM; chunked streaming with a
    halo (>= max match length) is the long-context analogue (SURVEY.md §5).
    """
    if chunk_bytes <= 0:
        raise ValueError("chunk_bytes must be positive")
    if overlap >= chunk_bytes:
        raise ValueError("overlap must be smaller than chunk_bytes")
    with open(path, "rb") as f:
        offset = 0
        carry = b""
        while True:
            block = f.read(chunk_bytes - len(carry))
            if not block:
                # EOF: any carried halo bytes were already yielded as part of
                # the previous chunk — never emit a carry-only chunk.
                return
            chunk = carry + block
            yield offset, chunk
            if len(chunk) < chunk_bytes:
                return
            carry = chunk[-overlap:] if overlap else b""
            offset += len(chunk) - len(carry)


def resolve_input_path(filename: str, workdir: "WorkDir") -> Path:
    """Input-split path resolution, shared by every data plane: absolute paths
    and existing cwd-relative paths are used as-is; bare names fall back to
    the work dir's inputs/ directory."""
    p = Path(filename)
    if not p.is_absolute() and not p.exists():
        p = workdir.root / "inputs" / p
    return p


class WorkDir:
    """Filesystem layout for one job under a shared root.

    inputs/         input splits (what SFTP-push of inputs becomes)
    intermediate/   mr-<map_task>-<r> shuffle files (coordinator.go:136-142)
    out/            mr-out-<r> final outputs (worker.go:169, coordinator.go:152)
    journal/        coordinator's durable task-commit journal
    """

    def __init__(self, root: str | Path):
        self.root = Path(root)
        for sub in ("inputs", "intermediate", "out", "journal"):
            (self.root / sub).mkdir(parents=True, exist_ok=True)

    def intermediate_path(self, map_task: int, reduce_part: int) -> Path:
        return self.root / "intermediate" / f"mr-{map_task}-{reduce_part}"

    def output_path(self, reduce_task: int) -> Path:
        return self.root / "out" / f"mr-out-{reduce_task}"

    def journal_path(self) -> Path:
        return self.root / "journal" / "tasks.jsonl"

    def clear(self) -> None:
        """Remove all job state (fresh-job reset of a reused work dir)."""
        for sub in ("inputs", "intermediate", "out", "journal"):
            for p in (self.root / sub).iterdir():
                if p.is_file():
                    p.unlink()

    def list_outputs(self) -> list[Path]:
        return sorted((self.root / "out").glob("mr-out-*"))

"""Utilities: config, logging, metrics, IO, native-library bindings."""

from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.metrics import Metrics

__all__ = ["JobConfig", "Metrics"]

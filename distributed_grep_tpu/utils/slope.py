"""Slope-method device timing, shared by bench.py and benchmarks/.

Measuring a single device pass through a slow host link (PCIe, or the axon
tunnel here) mixes dispatch/fetch latency into the kernel time.  The slope
method runs r chained passes inside ONE jit and takes the per-pass time from
the difference between two rep counts — constants cancel.

The one subtlety (learned the hard way — see bench.py history): the chained
loop body MUST depend on the loop index, or XLA's loop-invariant code motion
hoists the scan out of the fori_loop and N passes time exactly like one.
Callers therefore pad the chunk axis with `pad_rows` of '\\n' bytes and each
iteration scans a window at an i-dependent row offset.
"""

from __future__ import annotations

import functools
import time


def slope_per_pass(
    dev,
    chunk: int,
    pad_rows: int,
    scan_count_fn,
    r1: int = 2,
    r2: int = 6,
    iters: int = 3,
    count_range: tuple[int, int] | None = None,
    measurements: int = 1,
):
    """Per-pass seconds for scan_count_fn over `dev`'s leading-axis windows.

    dev            device array, leading axis of size chunk + pad_rows
    scan_count_fn  window -> scalar match count (or an array; nonzero bytes
                   are counted) — jit-traceable, tables closed over
    count_range    optional (lo, hi) per-pass count sanity band
    r1, r2         rep counts; both must be even so the two runs see the
                   same even/odd window mix (the count-drift check below
                   compares per-pass counts exactly)
    Returns (per_pass_seconds, per_pass_count_avg).
    """
    import jax
    import jax.numpy as jnp

    if r1 % 2 or r2 % 2:
        raise ValueError(f"r1/r2 must be even (same window mix per run): {r1=} {r2=}")

    @functools.partial(jax.jit, static_argnames=("reps",))
    def chained(d, reps):
        def body(i, acc):
            win = jax.lax.dynamic_slice_in_dim(d, (i % 2) * pad_rows, chunk, axis=0)
            out = scan_count_fn(win)
            return acc + (out if getattr(out, "ndim", 0) == 0 else jnp.count_nonzero(out))
        return jax.lax.fori_loop(0, reps, body, jnp.int32(0))

    c1, c2 = int(chained(dev, r1)), int(chained(dev, r2))
    # Both rep counts see the same even/odd window mix, so per-pass counts
    # must agree exactly — catches any miscounting regression for free.
    assert c2 * r1 == c1 * r2, f"per-pass count drift: {c1}/{r1} vs {c2}/{r2}"
    if count_range is not None:
        lo, hi = count_range
        assert lo * r1 <= c1 <= hi * r1, f"match count off: {c1} for {r1} passes"

    def timed(r):
        t0 = time.perf_counter()
        for _ in range(iters):
            int(chained(dev, r))
        return (time.perf_counter() - t0) / iters

    # Fast kernels need long chains: if the rep-count difference doesn't
    # dominate dispatch noise (non-positive slope, or the delta is under
    # 30% of the r1 time), escalate r2 and try again — a 170 GB/s kernel
    # at r2=10 runs ~15 ms of chain against ~100 ms of tunnel jitter.  A
    # measurement that never clears the noise gate raises rather than
    # reporting a number the gate itself distrusts (benchmark credibility
    # is the repo's core contract).
    # ``measurements`` > 1 repeats only the timed section (the jit'd
    # ``chained`` closure and its count checks are built once per call) and
    # returns the median slope — the cheap way to damp tunnel jitter.
    slopes: list[float] = []
    for _ in range(max(1, measurements)):
        for attempt in range(4):
            d1, d2 = timed(r1), timed(r2)
            delta = d2 - d1
            if delta > 0 and delta >= 0.3 * d1:
                slopes.append(delta / (r2 - r1))
                break
            if attempt < 3:
                r2 = r2 * 3
                c2 = int(chained(dev, r2))
                assert c2 * r1 == c1 * r2, f"count drift: {c1}/{r1} vs {c2}/{r2}"
        else:
            raise RuntimeError(
                f"slope never cleared the noise gate: "
                f"{d1=:.4f}s ({r1}) {d2=:.4f}s ({r2})"
            )
    return sorted(slopes)[len(slopes) // 2], c1 / r1


def _pallas_device_setup(data: bytes, target_lanes: int):
    """Shared layout/pad/upload for slope-timing the Pallas kernels: choose
    the pallas-tile layout, append 512 '\\n' pad rows (the anti-hoisting
    window scheme above), put on device.  Returns (dev, layout, lane_blocks,
    pad_rows)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_grep_tpu.ops import layout as layout_mod
    from distributed_grep_tpu.ops import pallas_scan

    lay = layout_mod.choose_layout(
        len(data),
        target_lanes=target_lanes,
        min_chunk=512,
        lane_multiple=pallas_scan.LANES_PER_BLOCK,
        chunk_multiple=512,
    )
    arr = layout_mod.to_device_array(data, lay).reshape(lay.chunk, -1, 128)
    pad_rows = 512
    pad = np.full((pad_rows,) + arr.shape[1:], 0x0A, dtype=np.uint8)
    dev = jax.device_put(jnp.asarray(np.concatenate([arr, pad], axis=0)))
    return dev, lay, lay.lanes // pallas_scan.LANES_PER_BLOCK, pad_rows


def pallas_shift_and_setup(data: bytes, model, *, target_lanes: int = 8192,
                           coarse: bool = True):
    """Device array + scan closure for slope-timing the Pallas shift-and
    kernel.  The one copy of this setup (layout choice, 512 '\\n' pad rows,
    kernel closure) shared by bench.py and benchmarks/baseline_configs.py so
    the two benches measure the identical configuration.  ``coarse``
    defaults to True because that is what the engine runs (span-granular
    candidate words + host line confirm, ops/pallas_scan.py).

    Returns (dev_array, chunk, pad_rows, scan_fn) ready for slope_per_pass.
    """
    from distributed_grep_tpu.ops import pallas_scan

    dev, lay, lane_blocks, pad_rows = _pallas_device_setup(data, target_lanes)
    sym_ranges = tuple(tuple(r) for r in model.sym_ranges)

    def scan(win):
        return pallas_scan._shift_and_pallas(
            win,
            sym_ranges=sym_ranges,
            match_bit=int(model.match_bit),
            chunk=lay.chunk,
            lane_blocks=lane_blocks,
            interpret=False,
            coarse=coarse,
        )

    return dev, lay.chunk, pad_rows, scan


def pallas_fdr_setup(data: bytes, model, *, target_lanes: int = 8192):
    """Device array + scan closure for slope-timing the Pallas FDR filter
    banks (ops/pallas_fdr.py) — all banks run per pass and their candidate
    words OR together, matching what the engine executes per segment."""
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import pallas_fdr

    dev, lay, lane_blocks, pad_rows = _pallas_device_setup(data, target_lanes)
    banks = [
        (b.m, pallas_fdr.kernel_plan(b),
         jnp.asarray(pallas_fdr.bank_device_tables(b)))
        for b in model.banks
    ]

    def scan(win):
        words = None
        for m, plan, tabs in banks:
            w = pallas_fdr._fdr_pallas(
                win, tabs, m=m, plan=plan, chunk=lay.chunk,
                lane_blocks=lane_blocks, interpret=False,
            )
            words = w if words is None else words | w
        return words

    return dev, lay.chunk, pad_rows, scan


def pallas_pairset_setup(data: bytes, model, *, target_lanes: int = 8192):
    """Device array + scan closure for slope-timing the exact short-set
    pair kernel (ops/pallas_pairset.py)."""
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import pallas_pairset

    dev, lay, lane_blocks, pad_rows = _pallas_device_setup(data, target_lanes)
    tabs = jnp.asarray(pallas_pairset.device_tables(model))

    def scan(win):
        return pallas_pairset._pairset_pallas(
            win, tabs, chunk=lay.chunk, lane_blocks=lane_blocks,
            transposed=model.transposed, fold_case=model.ignore_case,
            interpret=False,
        )

    return dev, lay.chunk, pad_rows, scan


def pallas_nfa_setup(data: bytes, model, *, target_lanes: int = 8192):
    """Device array + scan closure for slope-timing the Pallas Glushkov NFA
    kernel (ops/pallas_nfa.py) — same layout contract as the shift-and
    setup, shared by benchmarks/."""
    import jax.numpy as jnp

    from distributed_grep_tpu.ops import pallas_nfa

    dev, lay, lane_blocks, pad_rows = _pallas_device_setup(data, target_lanes)
    plan = model.kernel_plan()
    gather_b = pallas_nfa.use_gather_b(model)
    b_tabs = jnp.asarray(pallas_nfa.build_b_tables(model)) if gather_b else None

    def scan(win):
        return pallas_nfa._nfa_pallas(
            win, b_tabs, plan=plan, chunk=lay.chunk, lane_blocks=lane_blocks,
            gather_b=gather_b, interpret=False
        )

    return dev, lay.chunk, pad_rows, scan

"""jax.profiler integration: device traces + named host/device regions.

The reference has no tracing at all — only fmt.Printf progress lines
(SURVEY.md §5; coordinator.go:45, worker.go:48 et al.).  Here tracing is
first-class and TPU-native: `job_trace` wraps a whole job in a
`jax.profiler.trace` (viewable in TensorBoard / Perfetto), and `annotate`
marks task phases (assign → data-ready → kernel → commit) as
`TraceAnnotation` regions so per-task spans line up with device activity
in the same timeline.

Everything is a no-op unless tracing is switched on — either by passing
`trace_dir` explicitly or via the DGREP_TRACE_DIR environment variable —
so the hot paths pay nothing in production.

This module covers the DEVICE side of the observability story; the
cross-process control-plane side is utils/spans.py (worker→coordinator
span shipping, events.jsonl, `dgrep trace-export`).  Both render into the
same Perfetto/TensorBoard viewers, and `annotate`'s region names match the
span names the worker emits (map:read/map:compute per task id), so the
exported span rows line up with the jax.profiler device rows when a run
enables both DGREP_TRACE_DIR and the span pipeline.
"""

from __future__ import annotations

import os
from contextlib import contextmanager, nullcontext

from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("trace")

_ENV_VAR = "DGREP_TRACE_DIR"


def trace_dir() -> str | None:
    """The active trace directory, or None when tracing is off."""
    return os.environ.get(_ENV_VAR) or None


def enabled() -> bool:
    return trace_dir() is not None


@contextmanager
def job_trace(out_dir: str | None = None):
    """Trace an entire job under `jax.profiler.trace(out_dir)`.

    No-op when tracing is off or jax.profiler is unavailable (e.g. a
    worker process that never touches a device).
    """
    d = out_dir or trace_dir()
    if d is None:
        yield
        return
    try:
        import jax
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        yield
        return
    os.makedirs(d, exist_ok=True)
    log.info("profiler trace -> %s", d)
    with jax.profiler.trace(d):
        yield


def annotate(name: str):
    """Named region visible in the profiler timeline (host + device rows).

    Returns a context manager; a nullcontext when tracing is off so callers
    can annotate unconditionally.
    """
    if not enabled():
        return nullcontext()
    try:
        import jax
    except Exception:  # pragma: no cover
        return nullcontext()
    return jax.profiler.TraceAnnotation(name)


@contextmanager
def step_trace(name: str, step: int):
    """StepTraceAnnotation: groups device ops under a numbered step, the
    idiom the profiler uses to delimit training steps — here, scan passes."""
    if not enabled():
        yield
        return
    try:
        import jax
    except Exception:  # pragma: no cover
        yield
        return
    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield

"""Runtime half of the event-vocabulary contract (analyze rule
``event-registry``) — the lockdep static+dynamic pairing, applied to
telemetry names.

The static rule audits emit sites the AST can resolve; names built
dynamically (helper pass-throughs, f-string members outside the declared
family enumeration) only surface at runtime.  This recorder validates
every name actually emitted — ``SpanBuffer.add`` (worker spans/instants),
``EventLog.write_many`` (coordinator events.jsonl), ``DaemonLog.stage``
(daemon lifecycle kinds) — against ``analysis/events.py EVENTS``.

Two activation paths, like utils/lockdep.py:

- fixture: tests/conftest.py ``_event_vocab_audit`` (autouse, gated on the
  service/obs/follow/fuse/result/chaos markers) calls ``activate()`` and
  FAILS the test on any finding;
- env: ``DGREP_EVENT_AUDIT=1`` before process launch activates at import
  and additionally logs each finding as a warning — the live-daemon
  debugging recipe.

Off (the default) every hook is one module-global bool read; the hot
paths call ``record()`` OUTSIDE their buffer/staging locks, so the audit
never adds work under a lock the span pipeline holds.
"""

from __future__ import annotations

import os
import threading

_MAX_FINDINGS = 256

_lock = threading.Lock()
_active = False
_env_mode = False
_findings: list[str] = []
_flagged: set[str] = set()


def env_event_audit() -> bool:
    """DGREP_EVENT_AUDIT: ``1`` validates every emitted span/instant/
    daemon-event name against the analysis/events.py registry and logs
    undeclared names.  Default off (zero overhead)."""
    return os.environ.get("DGREP_EVENT_AUDIT", "").strip() == "1"


def is_active() -> bool:
    return _active


def activate() -> None:
    global _active
    _active = True


def deactivate() -> None:
    global _active
    _active = False


def reset() -> None:
    with _lock:
        _findings.clear()
        _flagged.clear()


def findings() -> list[str]:
    with _lock:
        return list(_findings)


def record(kind: str, name) -> None:
    """Validate one emitted event name (kind: "span"|"instant"|"daemon").
    No-op unless the audit is active; duplicate names report once."""
    if not _active or not isinstance(name, str) or not name:
        return
    # Lazy import: utils/spans.py imports this module, and the registry
    # lives in analysis/ — resolve it on first use, not at import time.
    from distributed_grep_tpu.analysis.events import lookup

    hit = lookup(name)
    if hit is None:
        msg = (f"undeclared {kind} event name {name!r}: not in "
               f"analysis/events.py EVENTS (nor any declared family)")
    elif kind not in hit[1].kinds:
        msg = (f"event {name!r} emitted as a {kind} but declared "
               f"{'/'.join(hit[1].kinds)} in analysis/events.py EVENTS")
    else:
        return
    with _lock:
        if name in _flagged or len(_findings) >= _MAX_FINDINGS:
            return
        _flagged.add(name)
        _findings.append(msg)
    if _env_mode:
        from distributed_grep_tpu.utils.logging import get_logger

        get_logger("event_audit").warning("%s", msg)


if env_event_audit():
    _env_mode = True
    activate()

"""Persistent shard-summary store: one file per shard under a work root.

Layout: ``<root>/<sha256(identity)[:40]>.tgs`` — a JSON header line
(identity, validators, version) followed by the raw bloom bytes.  Writes
are tmp + ``os.replace`` (atomic: readers see the old summary or the new
one, never a torn file) with NO fsync — a summary is a cache artifact; a
lost one rebuilds on the next cold scan, which is cheaper than an fsync
per shard on the scan path.  Loads compare identity AND validators
against the caller's FRESH stat: any size/mtime_ns/inode drift means the
content changed — the stale file is deleted and the caller scans (the
CorpusCache stale-never-served contract, persisted).

All I/O here runs in caller context with no lock held (the SummaryCache
lock wraps dict surgery only — locked-blocking discipline).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from pathlib import Path

_VERSION = 1


def _canon(obj):
    """Tuples -> lists, recursively: the JSON round-trip shape, so stored
    headers compare equal to a live key's fields."""
    if isinstance(obj, (list, tuple)):
        return [_canon(x) for x in obj]
    return obj


class IndexStore:
    def __init__(self, root):
        self.root = Path(root)
        self._made = False

    def _path_for(self, identity) -> Path:
        blob = json.dumps(_canon(identity), ensure_ascii=True,
                          separators=(",", ":"))
        h = hashlib.sha256(blob.encode("utf-8", "surrogatepass")).hexdigest()
        return self.root / f"{h[:40]}.tgs"

    def load(self, key) -> bytes | None:
        """The stored summary for ``key``, or None.  A record whose
        validators disagree with the key's fresh stat is STALE: deleted
        (best-effort) and never served."""
        p = self._path_for(key.identity)
        try:
            with open(p, "rb") as f:
                header = json.loads(f.readline())
                blob = f.read()
        except (OSError, ValueError):
            return None
        if (
            header.get("v") != _VERSION
            or header.get("identity") != _canon(key.identity)
            or len(blob) != header.get("m")
        ):
            return None
        if header.get("validators") != _canon(key.validators):
            try:
                os.unlink(p)  # stat drift: evict the stale record
            except OSError:
                pass
            return None
        return blob

    def save(self, key, summary: bytes) -> None:
        """Atomically persist ``summary`` under ``key`` (best-effort: a
        full disk degrades warm routing, never the scan)."""
        p = self._path_for(key.identity)
        header = json.dumps({
            "v": _VERSION,
            "identity": _canon(key.identity),
            "validators": _canon(key.validators),
            "m": len(summary),
        }, ensure_ascii=True, separators=(",", ":"))
        tmp = p.with_name(
            f".{p.name}.{os.getpid()}.{threading.get_ident()}.tmp"
        )
        try:
            if not self._made:
                self.root.mkdir(parents=True, exist_ok=True)
                self._made = True
            with open(tmp, "wb") as f:
                f.write(header.encode("utf-8", "surrogatepass"))
                f.write(b"\n")
                f.write(summary)
            os.replace(tmp, p)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

"""Shard-index tier: trigram/bloom summaries route queries past shards
that cannot match.

The subsystem has three halves, all jax-free (the service daemon's
control plane imports ``index.plan`` at submit time, and a remote-worker
daemon must stay importable without the ops stack):

* ``index.summary`` — the summary format: a fixed-size case-folded
  trigram-presence bloom per shard (native ``dgrep_trigram_summary``
  pass with a bit-identical numpy fallback), the in-memory
  ``SummaryCache``, the module telemetry counters, and the DGREP_INDEX /
  DGREP_INDEX_SUMMARY_BYTES knobs.
* ``index.store`` — per-work-root persistence: one file per shard keyed
  by the content-identity validator tuple (realpath + size/mtime_ns/
  inode — the CorpusCache contract), atomically replaced, stat-drift
  rejected at load.  A daemon restart reloads summaries, so "warm"
  survives the process.
* ``index.plan`` — the query planner: required-literal alternatives
  derived from the regex AST / pattern set (Google Code Search's trigram
  trick: trigram absent => literal absent => no match), plus the
  ``SplitPruner`` the service's ``plan_map_splits`` call consults so
  pruned splits never become map tasks.

Exactness never depends on the index: a summary only ever answers
"cannot match"; a maybe — or a missing/stale summary — always scans.
"""

from distributed_grep_tpu.index.summary import (  # noqa: F401
    DEFAULT_SUMMARY_BYTES,
    build_summary,
    env_index_enabled,
    env_summary_bytes,
    index_counters,
    index_counters_clear,
    summary_cache,
)

"""Trigram shard summaries: format, builders, cache, counters, knobs.

One summary is a fixed-size bloom over the CASE-FOLDED trigrams of a
shard's bytes (a file, or a packed batch window): 2 bits per trigram
position, indexed by the low/high 32-bit halves of one 64-bit Fibonacci
mix of the 24-bit folded trigram code.  Folding at build time makes
``ignore_case`` an index-time no-op — a case-insensitive query folds its
required literals to the same grams; a case-sensitive query only
over-approximates (fold can merge grams, never drop them), so the
"cannot match" verdict stays sound in both directions.

The native pass (``dgrep_trigram_summary``, utils/native.py) and the
numpy fallback below produce IDENTICAL bits — persisted summaries never
depend on which side built them (pinned by tests/test_index.py).

Knobs (single-owner rule, registered in analysis/knobs.py):

* ``DGREP_INDEX`` — the tier's kill-switch (default ON; 0/false/no
  disables every lookup, build, and prune — byte-for-byte the pre-index
  behavior).
* ``DGREP_INDEX_SUMMARY_BYTES`` — bloom size per shard (default 16 KB;
  clamped to a power of two in [1 KB, 1 MB]).  Larger summaries lower
  the bloom false-positive rate on trigram-dense shards; mixed sizes
  coexist (each summary carries its own size).
"""

from __future__ import annotations

import os
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.utils import lockdep as _lockdep

DEFAULT_SUMMARY_BYTES = 16384  # 131072 bits: ~µ-scale fp on file-sized shards

# In-memory cache cap (entries): bounded RAM for a long-lived service
# process — 4096 x 16 KB default summaries ≈ 64 MB, well under the corpus
# cache's host-bytes footprint for the same shards.
CACHE_MAX_ENTRIES = 4096

_MIX = np.uint64(0x9E3779B97F4A7C15)  # Fibonacci multiplier (same as C)


def env_index_enabled(default: bool = True) -> bool:
    """The shard-index master switch — the ONE parser of DGREP_INDEX.
    On by default (the warm service/engine paths it accelerates); "0"/
    "false"/"no" turns the whole tier off: no lookups, no builds, no
    pruning, no /status key — the pre-index behavior exactly."""
    raw = os.environ.get("DGREP_INDEX")
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no")


def env_summary_bytes(default: int = DEFAULT_SUMMARY_BYTES) -> int:
    """Per-shard bloom size — the ONE parser of DGREP_INDEX_SUMMARY_BYTES
    (malformed keeps the default, matching env_batch_bytes' shrug-off
    policy).  Rounded DOWN to a power of two in [1 KB, 1 MB]: the two-
    probe bit indexing masks with size*8-1, so a non-power-of-two would
    bias the hash and desynchronize the C and numpy builders."""
    raw = os.environ.get("DGREP_INDEX_SUMMARY_BYTES")
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    v = min(max(v, 1 << 10), 1 << 20)
    return 1 << (v.bit_length() - 1)


# --------------------------------------------------------------- trigrams

# ASCII case fold (A-Z -> a-z), as a 256-entry LUT for the vectorized paths.
_FOLD = np.arange(256, dtype=np.uint8)
_FOLD[ord("A"):ord("Z") + 1] += 32


def trigram_codes(literal: bytes) -> np.ndarray:
    """The folded 24-bit trigram codes of ``literal`` (deduped, sorted) —
    the query side of the index.  Empty for literals under 3 bytes (no
    trigram: such a literal can never be ruled out by the summary)."""
    if len(literal) < 3:
        return np.zeros(0, dtype=np.uint64)
    f = _FOLD[np.frombuffer(literal, dtype=np.uint8)].astype(np.uint64)
    v = (f[:-2] << np.uint64(16)) | (f[1:-1] << np.uint64(8)) | f[2:]
    return np.unique(v)


def _bit_indices(codes: np.ndarray, n_bits: int) -> np.ndarray:
    """The two bloom bit indices per trigram code (concatenated) — the
    shared math of the builder fallback and the membership check."""
    h = codes.astype(np.uint64) * _MIX
    mask = np.uint64(n_bits - 1)
    return np.concatenate([h & mask, (h >> np.uint64(32)) & mask])


def build_summary(data: bytes, summary_bytes: int | None = None) -> bytes:
    """The trigram bloom of ``data``: native one-pass when libdgrep
    carries dgrep_trigram_summary, else the bit-identical numpy scatter
    (chunked with a 2-byte overlap so temporaries stay bounded).  A
    shard under 3 bytes yields the all-zero summary — correct: it cannot
    contain any 3+-byte required literal."""
    m = summary_bytes if summary_bytes is not None else env_summary_bytes()
    bloom = np.zeros(m, dtype=np.uint8)
    from distributed_grep_tpu.utils import native as native_mod

    if native_mod.trigram_summary_into(data, bloom):
        _count("index_summaries_built")
        return bloom.tobytes()
    n_bits = m * 8
    step = 8 << 20
    arr = np.frombuffer(data, dtype=np.uint8)
    for pos in range(0, max(len(data) - 2, 0), step):
        piece = _FOLD[arr[pos:pos + step + 2]].astype(np.uint64)
        if piece.size < 3:
            break
        v = (
            (piece[:-2] << np.uint64(16))
            | (piece[1:-1] << np.uint64(8))
            | piece[2:]
        )
        idx = np.unique(_bit_indices(v, n_bits))
        np.bitwise_or.at(
            bloom, (idx >> np.uint64(3)).astype(np.int64),
            (np.uint8(1) << (idx & np.uint64(7)).astype(np.uint8)),
        )
    _count("index_summaries_built")
    return bloom.tobytes()


def has_all_trigrams(summary: bytes, codes: np.ndarray) -> bool:
    """True unless some trigram of the literal is ABSENT from the bloom —
    i.e. False is the proof "this literal does not occur in the shard"
    (bit absent => trigram absent => literal absent); True is only ever
    "maybe"."""
    if codes.size == 0:
        return True  # no trigram to check: can never rule the literal out
    bloom = np.frombuffer(summary, dtype=np.uint8)
    idx = _bit_indices(codes, bloom.size * 8)
    bits = (
        bloom[(idx >> np.uint64(3)).astype(np.int64)]
        >> (idx & np.uint64(7)).astype(np.uint8)
    ) & 1
    return bool(bits.all())


# ---------------------------------------------------------------- telemetry

_counters_lock = _lockdep.make_lock("index-counters")
_counters = {
    "index_shards_pruned": 0,
    "index_bytes_skipped": 0,
    "index_maybe_scans": 0,
    "index_summaries_built": 0,
}
# Lock-free never-touched fast path (the corpus cache's `_touched`
# convention): engine.scan() polls index_counters() once per chunk, and
# on processes where the index never fires that poll must not serialize
# worker threads on a process-global mutex.  Plain attribute — CPython
# reads/writes are atomic, and a stale False costs one scan's telemetry
# reading {} at the exact moment of first touch.
_touched = False


def _count(key: str, n: int = 1) -> None:
    global _touched
    with _counters_lock:
        _counters[key] += n
        _touched = True


def record_prune(n_bytes: int) -> None:
    """One shard skipped by the index (engine side)."""
    global _touched
    with _counters_lock:
        _counters["index_shards_pruned"] += 1
        _counters["index_bytes_skipped"] += int(n_bytes)
        _touched = True


def record_maybe() -> None:
    """A summary was consulted but could not rule the query out."""
    _count("index_maybe_scans")


def index_counters() -> dict:
    """Copy of the counters, or {} when the index was never touched —
    the nonzero-only contract every cache counter dict follows (zero-
    activity processes never grow stats/piggyback/status keys).  The
    never-touched answer is LOCK-FREE (see _touched above)."""
    if not _touched:
        return {}
    with _counters_lock:
        if not any(_counters.values()):
            return {}
        return dict(_counters)


def index_counters_clear() -> None:
    global _touched
    with _counters_lock:
        for k in _counters:
            _counters[k] = 0
        _touched = False


# ------------------------------------------------------------ shard keys

@dataclass(frozen=True)
class ShardKey:
    """Content identity of one shard — the same (identity, validators)
    shape as ops/layout.CorpusKey (which the engine passes here
    directly, duck-typed), redeclared so the daemon-side planner can
    derive keys without importing the ops package."""

    identity: tuple  # ("file", realpath) | ("pack", (realpath, ...))
    validators: tuple  # ((size, mtime_ns, ino), ...), one per member

    @property
    def n_bytes(self) -> int:
        return sum(v[0] for v in self.validators)


def file_key(path) -> ShardKey | None:
    """ShardKey for a filesystem path from a FRESH stat, or None when it
    cannot be statted (the caller then neither prunes nor publishes)."""
    try:
        real = os.path.realpath(os.fspath(path))
        st = os.stat(real)
    except OSError:
        return None
    return ShardKey(
        identity=("file", real),
        validators=((int(st.st_size), int(st.st_mtime_ns), int(st.st_ino)),),
    )


# ------------------------------------------------------------ summary cache

class SummaryCache:
    """Process-global LRU of (identity -> (validators, summary)) — dict
    surgery only under the lock (no I/O, no builds: the locked-blocking
    discipline; loads/builds happen in the module helpers below, outside).
    Validator mismatch at lookup evicts — stale summaries are never
    consulted (the CorpusCache revalidation contract)."""

    def __init__(self, max_entries: int = CACHE_MAX_ENTRIES):
        self._lock = _lockdep.make_lock("index-cache")
        self._max = int(max_entries)
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        # Lock-free "has this cache ever been populated" flag (the
        # `_touched` convention): may_route() reads it per scan entry,
        # and a process that can never hold a summary must not pay a
        # stat + global-mutex lookup per file just to miss.  Plain
        # attribute; conservatively stays True until clear().
        self.nonempty = False

    def lookup(self, key) -> bytes | None:
        if key is None:
            return None
        with self._lock:
            ent = self._entries.get(key.identity)
            if ent is None:
                return None
            validators, summary = ent
            if validators != key.validators:
                del self._entries[key.identity]  # stat drift: stale
                return None
            self._entries.move_to_end(key.identity)
            return summary

    def put(self, key, summary: bytes) -> None:
        if key is None:
            return
        with self._lock:
            self._entries[key.identity] = (key.validators, summary)
            self._entries.move_to_end(key.identity)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)
            self.nonempty = True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.nonempty = False


_cache = SummaryCache()
_store = None  # attached IndexStore (persistence), or None


def summary_cache() -> SummaryCache:
    return _cache


def attach_store(root) -> None:
    """Attach (or detach, root=None) the persistent summary store — the
    service threads its ``<work_root>/index`` dir through the grep app's
    ``index_dir`` option so worker-built summaries survive the daemon
    AND the workers (runtime/service.py sets it at submit)."""
    global _store
    if root is None:
        _store = None
        return
    from distributed_grep_tpu.index.store import IndexStore

    cur = _store
    if cur is None or os.fspath(cur.root) != os.fspath(root):
        _store = IndexStore(root)


def attached_store():
    return _store


def may_route() -> bool:
    """Lock-free per-scan gate: could a summary lookup possibly answer?
    True when the persistent store is attached or the in-memory cache
    has ever been populated.  False means every lookup is a structural
    miss — callers then skip the realpath+stat+lock work outright (the
    CorpusCache `_small_route_cached` discipline: no guaranteed-miss
    stat/lock per query)."""
    return _store is not None or _cache.nonempty


def lookup_summary(key) -> bytes | None:
    """The shard's summary from memory, falling back to the attached
    persistent store (store I/O runs here, outside the cache lock; a
    store hit repopulates memory).  None = no summary (or stat drift —
    both sides evict): the caller scans."""
    if key is None:
        return None
    s = _cache.lookup(key)
    if s is not None:
        return s
    st = _store
    if st is None:
        return None
    s = st.load(key)
    if s is not None:
        _cache.put(key, s)
    return s


def publish_summary(key, data: bytes) -> bytes | None:
    """Build ``data``'s summary and publish it under ``key`` (memory +
    the attached store).  Callers invoke this AFTER the scan that read
    ``data`` succeeded — the CorpusCache publish discipline — and assert
    data IS the bytes the key's fresh stat described.  Returns the
    summary (so the caller can also attach it to a CorpusCache entry),
    or None when the key is unusable."""
    if key is None:
        return None
    s = build_summary(data)
    _cache.put(key, s)
    st = _store
    if st is not None:
        st.save(key, s)  # atomic, best-effort; outside every lock
    return s


def clear() -> None:
    """Tests: empty the in-memory cache, detach the store, zero counters."""
    global _store
    _cache.clear()
    _store = None
    index_counters_clear()

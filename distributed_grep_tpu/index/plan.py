"""Query planner: required-literal alternatives + split pruning.

The routing claim the whole tier rests on: if every match of a query
must contain at least one member of a literal set (the query's
**required-literal alternatives**), then a shard whose summary lacks
some trigram of EVERY member cannot match — Google Code Search's trigram
query rewrite, reduced to the presence form a per-shard bloom can
answer.  Derivation is deliberately conservative: anything the walk
cannot prove required yields None (index-INELIGIBLE — the query scans
everything), never a weaker-than-true requirement.  Ineligible by
construction: empty-match patterns (no required bytes), approx mode
(edits can destroy any literal), and any alternative under 3 bytes (no
trigram to check).

Both sides derive from the SAME inputs — the daemon-side SplitPruner
from the JobConfig's app options, the engine from its stashed
constructor args — so planner and engine can never disagree on
eligibility.  jax-free (models/dfa is numpy-only): safe on the service
control plane.
"""

from __future__ import annotations

import numpy as np

from distributed_grep_tpu.index import summary as summary_mod
from distributed_grep_tpu.models import dfa as _dfa

# Alternatives cap: a query needing more than this many required-literal
# alternatives checks too many grams per shard to be worth the lookup
# (and giant alternations are FDR's territory anyway).
MAX_ALTERNATIVES = 64


def _singleton(node) -> int | None:
    """The one byte of a single-member Char class, or None."""
    mask = node.mask
    if mask == 0 or mask & (mask - 1):
        return None
    return mask.bit_length() - 1


def _node_alts(node) -> list[bytes] | None:
    """Literal alternatives such that EVERY match of ``node`` contains at
    least one of them, or None when no usable set exists.  Weakening is
    always sound here (a shorter run / a superset of alternatives is a
    weaker claim that still holds); returning None just forfeits pruning."""
    if isinstance(node, _dfa.Char):
        b = _singleton(node)
        return [bytes([b])] if b is not None else None
    if isinstance(node, _dfa.Anchor):
        return None  # zero-width: no required bytes
    if isinstance(node, _dfa.Repeat):
        if node.min < 1:
            return None  # optional: nothing is required
        sub = _node_alts(node.node)
        if sub is not None and len(sub) == 1 and len(sub[0]) == 1:
            # a{3,} requires "aaa": min copies of a singleton concatenate
            return [sub[0] * min(node.min, 8)]
        return sub  # >= 1 copy: the inner requirement holds
    if isinstance(node, _dfa.Alt):
        out: list[bytes] = []
        for opt in node.options:
            sub = _node_alts(opt)
            if sub is None or len(out) + len(sub) > MAX_ALTERNATIVES:
                return None  # one unconstrained branch unconstrains the Alt
            out.extend(sub)
        return out or None
    if isinstance(node, _dfa.Concat):
        # Every part is required, so we may pick the single BEST
        # requirement; consecutive singleton chars stitch into longer
        # literal runs (zero-width anchors and non-literal parts break a
        # run — breaking only weakens the claim, which stays sound).
        candidates: list[list[bytes]] = []
        run = b""
        for part in node.parts:
            b = _singleton(part) if isinstance(part, _dfa.Char) else None
            if b is not None:
                run += bytes([b])
                continue
            if run:
                candidates.append([run])
                run = b""
            if isinstance(part, (_dfa.Anchor,)):
                continue
            sub = _node_alts(part)
            if sub is not None:
                candidates.append(sub)
        if run:
            candidates.append([run])
        best: list[bytes] | None = None
        best_len = 0
        for c in candidates:
            mn = min(len(x) for x in c)
            if mn > best_len:
                best, best_len = c, mn
        return best
    return None


class QueryRequirements:
    """Compiled query side of the index: folded trigram codes per
    required-literal alternative.  ``may_match(summary)`` is True unless
    every alternative has some trigram absent — the only verdict that
    prunes, and it is exact ("cannot match"), never a guess."""

    __slots__ = ("alternatives", "literals")

    def __init__(self, literals: list[bytes]):
        self.literals = literals
        self.alternatives = [summary_mod.trigram_codes(l) for l in literals]

    def may_match(self, summary: bytes) -> bool:
        return any(
            summary_mod.has_all_trigrams(summary, codes)
            for codes in self.alternatives
        )


def _as_bytes(p) -> bytes:
    return (
        p.encode("utf-8", "surrogateescape") if isinstance(p, str)
        else bytes(p)
    )


def requirements_for_query(
    pattern: str | None = None,
    patterns: list | None = None,
    ignore_case: bool = False,
    max_errors: int = 0,
) -> QueryRequirements | None:
    """The query's required-literal alternatives, or None = ineligible
    (scan everything).  Pattern sets are literal sets by contract (the
    AC/FDR engines): the members ARE the alternatives.  Single patterns
    parse through the models/dfa AST; parsing is case-SENSITIVE — the
    summary's build-time fold makes ignore_case a query-time no-op (the
    trigram codes fold on both sides), so ``ignore_case`` only matters
    to the engines, not to eligibility.  Every alternative must carry at
    least one trigram (>= 3 bytes) — a shorter member can never be
    ruled out, which would make pruning unsound."""
    if max_errors:
        return None  # approx: k edits can destroy any required literal
    if patterns is not None:
        lits = [_as_bytes(p) for p in patterns]
        if not lits or len(lits) > MAX_ALTERNATIVES:
            return None
    else:
        if pattern is None:
            return None
        if isinstance(pattern, bytes):
            pattern = pattern.decode("utf-8", "surrogateescape")
        try:
            ast = _dfa._Parser(pattern, ignore_case=False).parse()
        except _dfa.RegexError:
            return None  # outside the subset: no sound derivation
        lits = _node_alts(ast)
        if not lits:
            return None
    if any(len(l) < 3 for l in lits):
        return None
    req = QueryRequirements(lits)
    if any(c.size == 0 for c in req.alternatives):
        return None
    return req


# ------------------------------------------------------- split pruning

class SplitPruner:
    """The daemon-side hook runtime/job.plan_map_splits consults: a file
    whose persisted summary rules the query out is dropped from the plan
    — no map task, no worker open, no dispatch.  Tallies are the
    caller's to surface (the service stamps them into per-job metrics
    and the /status "index" view); this object never touches the module
    counters (those are the ENGINE side's, and an in-process worker
    would double-count).  All I/O (store loads) runs at plan time,
    outside every service/scheduler lock."""

    def __init__(self, requirements: QueryRequirements, store):
        self.requirements = requirements
        self.store = store
        self.shards_pruned = 0
        self.bytes_skipped = 0
        self.maybe_scans = 0

    def prune(self, path) -> bool:
        key = summary_mod.file_key(path)
        if key is None:
            return False
        # memory first (an in-process-worker daemon shares the global
        # cache the workers populate), then this job's persistent store
        s = summary_mod.summary_cache().lookup(key)
        if s is None and self.store is not None:
            s = self.store.load(key)
            if s is not None:
                summary_mod.summary_cache().put(key, s)
        if s is None:
            return False
        if self.requirements.may_match(s):
            self.maybe_scans += 1
            return False
        self.shards_pruned += 1
        self.bytes_skipped += key.n_bytes
        return True


# App options the grep apps define whose zero-match output is NOT empty:
# such a job must keep its map tasks even for shards that cannot match
# (an inverted file emits every line; count/presence jobs emit a record
# per file).  Engine-level pruning stays exact for them — only the
# planner (which removes whole tasks) gates on these.
_UNPRUNABLE_OPTIONS = ("invert", "count_only", "presence_only")

GREP_APPLICATION = "distributed_grep_tpu.apps.grep_tpu"


def pruner_for_job(config, index_root) -> SplitPruner | None:
    """A SplitPruner for this JobConfig, or None when planner-level
    pruning is not sound or not possible: index off, a non-grep_tpu app
    (the planner cannot know a foreign app's zero-match output), an
    option whose zero-match output is non-empty, or an ineligible query."""
    if not summary_mod.env_index_enabled():
        return None
    if getattr(config, "application", None) != GREP_APPLICATION:
        return None
    opts = config.effective_app_options()
    if any(opts.get(k) for k in _UNPRUNABLE_OPTIONS):
        return None
    try:
        req = requirements_for_query(
            pattern=opts.get("pattern"),
            patterns=opts.get("patterns"),
            ignore_case=bool(opts.get("ignore_case")),
            max_errors=int(opts.get("max_errors") or 0),
        )
    except Exception:  # noqa: BLE001 — derivation must never break submit
        req = None
    if req is None:
        return None
    from distributed_grep_tpu.index.store import IndexStore

    store = IndexStore(index_root)
    if not (summary_mod.summary_cache().nonempty or store.root.is_dir()):
        # nothing to consult anywhere (no summary ever built in-process,
        # no persisted store yet): skip the per-file stat + guaranteed-
        # ENOENT load work — the engine side's may_route() discipline,
        # planner edition.  One dir stat per submit buys it.
        return None
    return SplitPruner(req, store)

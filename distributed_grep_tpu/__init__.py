"""distributed_grep_tpu — a TPU-native distributed-grep / MapReduce framework.

A from-scratch rebuild of the capabilities of bgilby59/distributed-grep
(reference: a Go MapReduce framework in the MIT 6.824 Lab-1 style, with grep
as the pluggable application) designed TPU-first on JAX/XLA/Pallas:

* ``apps``     — the pluggable Map/Reduce application boundary
                 (reference: application/grep.go:13-40, main/worker_launch.go:21-34).
* ``runtime``  — coordinator/worker MapReduce runtime: task scheduling,
                 heartbeat/timeout fault tolerance, streaming shuffle,
                 idempotent atomic commits
                 (reference: map_reduce/coordinator.go, map_reduce/worker.go).
* ``models``   — pattern automata ("model families"): shift-and bit-parallel
                 masks, regex -> NFA -> DFA with byte-class compression,
                 Aho-Corasick multi-pattern tables.
* ``ops``      — TPU compute path: Pallas byte-scan kernels and pure-XLA
                 fallbacks for DFA/shift-and scanning, newline indexing and
                 line-number assignment.
* ``parallel`` — device-mesh fan-out: shard_map data/sequence parallelism with
                 DFA state carried across shard boundaries, ICI collectives,
                 multi-host initialization.
* ``utils``    — config, logging, metrics, IO, native-library bindings.
"""

from distributed_grep_tpu.version import __version__

__all__ = ["__version__"]

"""Core application types: KeyValue and the Application protocol.

Reference contract: ``KeyValue`` (map_reduce/helper_types.go:8-11) and the
Map/Reduce function pair (application/grep.go:13-40).  The reference loads
applications as Go plugins exposing ``Map``/``Reduce`` symbols
(main/worker_launch.go:21-34); here an application is any object (usually a
module) exposing the same two callables, plus an optional ``configure`` hook
so job-level options (e.g. the grep pattern — which the reference hardcodes
to "" and never plumbs, application/grep.go:11) reach the application.
"""

from __future__ import annotations

from typing import Iterable, NamedTuple, Protocol, runtime_checkable


class KeyValue(NamedTuple):
    """One intermediate record emitted by Map and consumed by Reduce.

    Mirrors map_reduce/helper_types.go:8-11.  Keys are strings (they are
    hashed for partitioning and sorted for grouping); values are strings.
    """

    key: str
    value: str


@runtime_checkable
class Application(Protocol):
    """The pluggable application boundary.

    Structural protocol: a module or object with ``map_fn``/``reduce_fn``
    (named to avoid shadowing Python builtins; the loader also accepts
    ``Map``/``Reduce`` for reference-style modules).
    """

    def map_fn(self, filename: str, contents: bytes) -> list[KeyValue]:
        """Process one input split; emit intermediate key/value records."""
        ...

    def reduce_fn(self, key: str, values: list[str]) -> str:
        """Fold all values for one key into a single output string."""
        ...


def sort_by_key(records: Iterable[KeyValue]) -> list[KeyValue]:
    """Stable sort by key — the grouping precursor (helper_types.go:14-19)."""
    return sorted(records, key=lambda kv: kv.key)


def group_reduce(records: list[KeyValue], reduce_fn) -> dict[str, str]:
    """Sort-merge grouping: one reduce call per distinct key.

    Mirrors ``reduceDistinctKeys`` (map_reduce/worker.go:22-43): sort all
    records by key, walk runs of equal keys, call reduce once per run.
    """
    out: dict[str, str] = {}
    kva = sort_by_key(records)
    i = 0
    n = len(kva)
    while i < n:
        j = i
        while j < n and kva[j].key == kva[i].key:
            j += 1
        values = [kva[k].value for k in range(i, j)]
        out[kva[i].key] = reduce_fn(kva[i].key, values)
        i = j
    return out

"""TPU grep application — drop-in interchangeable with apps/grep.py.

Same Map/Reduce contract and same output records as the CPU app
(application/grep.go:13-40 semantics: key "<filename> (line number #N)",
value = the line; identity Reduce), but the per-line host regexp loop is
replaced by the ops.GrepEngine device scan: compile the pattern once to a
shift-and/DFA model, scan the whole split on the TPU, then slice only the
matched lines out of the buffer using the native newline index.

The ``backend`` option ("device" | "cpu") and every engine knob arrive via
configure() — the plumbing the reference's TODO (coordinator.go:41) never
built.  Patterns outside the device subset transparently fall back to the
host re engine inside GrepEngine, so this app never refuses a pattern the
CPU app would accept.
"""

from __future__ import annotations

import numpy as _np

from distributed_grep_tpu.apps.base import KeyValue
from distributed_grep_tpu.ops.engine import GrepEngine, cached_engine
from distributed_grep_tpu.ops.lines import count_lines, newline_index
from distributed_grep_tpu.runtime.columnar import (
    DeferredBatch,
    make_batch_from_lines,
)
from distributed_grep_tpu.utils import spans as _spans_mod

# Reduce is values[0] and keys are unique per (file, line): the runtime's
# identity-reduce collator may keep map output COLUMNAR end to end and
# write (file, line)-ordered output (runtime/columnar.IdentityCollator).
reduce_is_identity = True

_engine: GrepEngine | None = None
_invert: bool = False  # grep -v
_confirm = None  # -w/-x: boundary-wrapped host regex over candidate lines
_confirm_lit: bytes | None = None  # -w/-x literal fast path (vectorized)
_confirm_mode: str = "search"
_count_only: bool = False  # emit one per-file count record, not per-line
_presence: bool = False  # -q/-l/-L: truthiness only; streaming may stop early
_configured_with: tuple | None = None

# Progress reporting (runtime liveness, VERDICT r3 item 3): the worker
# installs a callback per task via set_progress; the engine invokes it per
# chunk/segment.  Thread-local because one process may run several worker
# slots against this shared module (http_transport.run_http_worker).
import threading as _threading

_progress = _threading.local()
# Compile-grace windows are declared by the ENGINE, per fresh kernel/layout
# shape (ops/engine.py COMPILE_GRACE_S): a one-shot app-level flag missed
# the later jit specializations every new segment-layout shape triggers
# (round-4 review finding) — the engine knows exactly when it is about to
# dispatch a shape it has not compiled yet.
from distributed_grep_tpu.ops.engine import COMPILE_GRACE_S  # noqa: F401  (re-export)


def set_progress(fn) -> None:
    """Worker hook: install (fn) or clear (None) this task's progress
    callback — fn() stamps liveness, fn(grace_s=N) declares a silent phase."""
    _progress.fn = fn


def _progress_fn():
    """The installed progress callback, handed to the engine as-is — the
    engine stamps work milestones and declares compile grace itself."""
    return getattr(_progress, "fn", None)


def configure(
    pattern: str | bytes = "",
    ignore_case: bool = False,
    backend: str = "device",
    patterns: list[str] | None = None,
    invert: bool = False,
    word_regexp: bool = False,
    line_regexp: bool = False,
    devices: object = "all",  # worker drives every local chip by default
    mesh_shape: object = None,  # e.g. [4, 2]: shard each segment's lanes
    # over a device mesh instead of round-robining segments (JSON-friendly
    # mirror of JobConfig.mesh_shape — the long-context configuration)
    mesh_axes: object = ("data",),
    pattern_axis: object = None,  # with a 2D mesh: EP-shard FDR banks
    count_only: bool = False,  # count queries (grep -c/-l/-L/-q): emit ONE
    # record per file — "<filename>" -> str(selected line count) — instead
    # of one per matched line.  A match-dense count job otherwise pays the
    # full per-line record pipeline for output it immediately collapses
    # (measured: 549k-match 64 MB `-c` fell 17.5 s -> ~1.5 s)
    presence_only: bool = False,  # refinement of count_only for -q/-l/-L:
    # only per-file TRUTHINESS is consumed, so the streaming scan may stop
    # at the first chunk containing a match (GNU grep -q/-l stop at the
    # first match); the emitted count may then be partial
    index_dir: object = None,  # shard-index persistence root (the service
    # sets <work_root>/index at submit): worker-built trigram summaries
    # land there, so the daemon's split planner — and the NEXT daemon
    # after a restart — prunes shards this worker already summarized
    **engine_opts: object,
) -> None:
    global _engine, _invert, _confirm, _count_only, _presence, _configured_with
    if isinstance(pattern, bytes):
        pattern = pattern.decode("utf-8", "surrogateescape")
    if index_dir is not None or _configured_with is not None:
        # BEFORE the same-config short-circuit: the store must follow the
        # daemon even when the engine config is unchanged across jobs —
        # attach when a dir arrives, DETACH when a later job has none (a
        # worker that outlives its daemon must not keep publishing into a
        # retired work root, and an index-off daemon's workers must stay
        # summary-free).  First-ever configure with no dir skips the
        # import entirely (one-shot CLI jobs never touch the tier).
        from distributed_grep_tpu.index import summary as _index_summary

        _index_summary.attach_store(index_dir if index_dir else None)
    _invert = bool(invert)
    _count_only = bool(count_only)
    _presence = bool(presence_only)
    mode = "line" if line_regexp else ("word" if word_regexp else "search")
    if backend == "device" and mesh_shape:
        from distributed_grep_tpu.parallel.mesh import make_mesh

        axes = tuple(mesh_axes)
        engine_opts["mesh"] = make_mesh(tuple(mesh_shape), axes)
        # lanes shard over every axis not reserved for pattern banks
        lane_axes = tuple(a for a in axes if a != pattern_axis)
        if not lane_axes:
            raise ValueError(
                f"pattern_axis {pattern_axis!r} consumes every mesh axis "
                f"{axes}: no axis left for document lanes"
            )
        engine_opts["mesh_axis"] = (
            lane_axes[0] if len(lane_axes) == 1 else lane_axes
        )
        if pattern_axis is not None:
            engine_opts["pattern_axis"] = pattern_axis
    elif backend == "device":
        engine_opts["devices"] = devices
    key = (pattern, ignore_case, backend, tuple(patterns or ()), _invert, mode,
           tuple(sorted(engine_opts.items())))
    if key == _configured_with:
        return
    # Cross-job compiled-model cache (ops/engine.cached_engine): in the
    # service regime a repeated pattern returns the SAME engine object —
    # model compile, device-table uploads, and the per-shape compile-grace
    # bookkeeping are all skipped on the hit.  Mesh engines bypass the
    # cache (no stable key); the verdict instant lands on this task's
    # trace row when the span pipeline is on.
    _engine, cache_verdict = cached_engine(
        pattern if patterns is None else None,
        patterns=patterns,
        ignore_case=ignore_case,
        backend=backend,
        **engine_opts,  # type: ignore[arg-type]
    )
    # "off" (cache-bypassed construction) emits too: explain's model_cache
    # "bypassed" counter consumes it — the registry (analysis/events.py)
    # declares all three cache:* members as produced.
    _spans_mod.instant(f"cache:{cache_verdict}", cat="engine",
                       mode=_engine.mode)
    # grep -w / -x: the device scan stays on the raw pattern (its matched
    # lines are a SUPERSET of word/line matches — a word/line match is in
    # particular a substring match), and each candidate line is confirmed
    # against the boundary-wrapped regex host-side (ONE shared builder:
    # apps/grep.build_confirm).
    from distributed_grep_tpu.apps.grep import build_confirm

    _confirm = build_confirm(
        pattern=pattern, patterns=patterns, ignore_case=ignore_case,
        mode=mode,
    )
    # -w/-x literal fast path (round 5): a single case-sensitive literal's
    # confirm is ONE native occurrence scan + boundary-byte masks
    # (apps/grep.literal_mode_lines) instead of a host regex per candidate
    # line (~8 us x 663k lines on the dense receipt corpus).
    global _confirm_lit, _confirm_mode
    _confirm_lit = None
    _confirm_mode = mode
    if _confirm is not None and patterns is None and not ignore_case:
        from distributed_grep_tpu.utils.native import native_available

        lit = _engine._native_literal() if native_available() else None
        if lit:
            _confirm_lit = lit
    _configured_with = key


def _stamp_every(progress, i: int, stride: int = 16384) -> None:
    """Throttled liveness stamp inside match-dense per-line loops: the
    engine's scan stamps stop once the scan returns, and building or
    confirming ~500k records can outlast the failure-detector window by
    itself.  The callback self-throttles; the stride just bounds call
    overhead."""
    if progress is not None and i % stride == 0:
        progress()


def map_fn(filename: str, contents: bytes) -> list[KeyValue]:
    if _engine is None:
        raise RuntimeError("grep_tpu used before configure() — no pattern set")
    result = _engine.scan(contents, progress=_progress_fn())
    return _records_for(filename, contents, result)


map_batch_paths = True  # items may be (filename, local PATH) pairs:
# scan_batch reads cold members itself and serves warm ones from the
# device corpus cache (round 7) — the worker hands paths over on local
# data planes so a repeat query never re-reads unchanged files


def map_batch_fn(items) -> list[KeyValue]:
    """Batched map (round 6): many small splits in ONE call — the engine
    packs them into shared device dispatches (GrepEngine.scan_batch /
    ops/layout.BatchPacker), so a multi-file map split pays one kernel
    pass per DGREP_BATCH_BYTES window instead of one host scan per file.
    ``items`` is a list of (filename, contents) pairs — contents may be a
    local PATH on local data planes (``map_batch_paths``; scan_batch
    reads or cache-serves those itself) — and the records are identical
    to per-file map_fn calls (the packed scan is exact at file
    granularity — every blob is newline-terminated in the packed layout,
    and the engine's confirm/stitch pass owns stripe/segment edges)."""
    if _engine is None:
        raise RuntimeError("grep_tpu used before configure() — no pattern set")
    records: list[KeyValue] = []
    _engine.scan_batch(
        items, progress=_progress_fn(),
        emit=lambda name, data, res: records.extend(
            _records_for(name, data, res)
        ),
        # shard-index member pruning skips the read and emits (name, b"",
        # empty result) — exact for print and count records (zero matches
        # IS the proven answer) but NOT for -v, whose complement needs
        # the file's real lines: invert keeps every read
        index_prune=not _invert,
    )
    return records


class _EmitOpts:
    """Per-query post-scan options — the module globals configure() sets,
    reified so the fused path (map_fused_fn) can run K queries' record
    builds side by side without reconfiguring the module."""

    __slots__ = ("confirm", "confirm_lit", "confirm_mode", "invert",
                 "count_only")

    def __init__(self, confirm, confirm_lit, confirm_mode, invert,
                 count_only):
        self.confirm = confirm
        self.confirm_lit = confirm_lit
        self.confirm_mode = confirm_mode
        self.invert = invert
        self.count_only = count_only


def _module_emit_opts() -> _EmitOpts:
    return _EmitOpts(_confirm, _confirm_lit, _confirm_mode, _invert,
                     _count_only)


# app-level option keys configure() consumes itself; everything else in
# app_options is an engine kwarg (the fused path rebuilds the same split)
_APP_OPTION_KEYS = frozenset((
    "pattern", "patterns", "ignore_case", "invert", "word_regexp",
    "line_regexp", "count_only", "presence_only", "max_errors",
    "backend", "devices", "mesh_shape", "mesh_axes", "pattern_axis",
    "index_dir",
))


def map_fused_fn(items, participants) -> list[list[KeyValue]]:
    """Cross-tenant fused map (round 13): K co-tenant queries over ONE
    shared split — one union scan per packed window (ops/fuse.py), then
    each participant's own post-scan semantics (-w/-x confirm, -v,
    record build) over its exact per-query results.  ``participants``
    carry each tenant's app_options and member names (two tenants may
    address the same content through different paths); returns one
    record list per participant, each bit-identical to that
    participant's solo map_batch_fn over the same content.  Raises
    ops/fuse.FuseError for specs the union cannot host — the worker then
    falls back to solo per-participant execution."""
    from distributed_grep_tpu.apps.grep import build_confirm
    from distributed_grep_tpu.ops import fuse as fuse_mod
    from distributed_grep_tpu.runtime.fusion import query_spec

    items = list(items)
    specs = []
    opt_sets = []
    for p in participants:
        o = dict(p.get("app_options") or {})
        spec = query_spec(o)
        if spec is None:
            raise fuse_mod.FuseError(
                f"participant {p.get('job_id')!r} query is not fusable"
            )
        specs.append(spec)
        opt_sets.append(o)
    base = opt_sets[0]
    engine_kw = {k: v for k, v in base.items() if k not in _APP_OPTION_KEYS}
    backend = base.get("backend", "device")
    if backend == "device":
        engine_kw["devices"] = base.get("devices", "all")
    scanner = fuse_mod.FusedScanner(specs, backend=backend, **engine_kw)
    emit_opts = []
    names_per: list[list | None] = []
    for p, o in zip(participants, opt_sets):
        mode = (
            "line" if o.get("line_regexp")
            else "word" if o.get("word_regexp") else "search"
        )
        confirm = build_confirm(
            pattern=o.get("pattern"), patterns=o.get("patterns"),
            ignore_case=bool(o.get("ignore_case")), mode=mode,
        )
        # no confirm-literal fast path here: it needs the participant's
        # solo engine; the regex confirm is bit-identical, and fused
        # attempts see only this split's candidate lines anyway
        emit_opts.append(_EmitOpts(confirm, None, mode,
                                   bool(o.get("invert")),
                                   bool(o.get("count_only"))))
        nm = list(p.get("filenames") or [])
        if not nm and p.get("filename"):
            nm = [p["filename"]]
        if len(nm) != len(items):
            # fail SAFE, never silently key this tenant's records by the
            # primary's paths: FuseError makes the worker fall back to
            # per-participant solo execution (each with its own names)
            raise fuse_mod.FuseError(
                f"participant {p.get('job_id')!r} has {len(nm)} member "
                f"names for a {len(items)}-item split"
            )
        names_per.append(nm)
    outs: list[list[KeyValue]] = [[] for _ in participants]

    def emit(i, name, data, results, nl) -> None:
        for k, res in enumerate(results):
            outs[k].extend(_records_for(names_per[k][i], data, res,
                                        opts=emit_opts[k], nl=nl))

    scanner.scan_batch(items, progress=_progress_fn(), emit=emit)
    return outs


def _records_for(filename: str, contents: bytes, result,
                 opts: _EmitOpts | None = None, nl=None) -> list[KeyValue]:
    """Everything after the scan — -w/-x confirm, -v, count/presence
    collapse, columnar batch build — shared by map_fn (one scan per call)
    and map_batch_fn (one packed scan, per-file demuxed results).  Runs
    under its own ``map:emit`` span so trace-export separates scan time
    from record-build time on the worker row.  ``nl`` is an optional
    precomputed newline index of ``contents`` (the fused path hands one
    shared index to K participants' record builds)."""
    with _spans_mod.span("map:emit", cat="map"):
        return _records_for_inner(filename, contents, result,
                                  opts or _module_emit_opts(), nl=nl)


def _records_for_inner(filename: str, contents: bytes, result,
                       o: _EmitOpts, nl=None) -> list[KeyValue]:
    emit = result.matched_lines  # int64 ndarray, stays vectorized throughout
    if o.confirm is not None and emit.size:
        if nl is None:
            nl = newline_index(contents)
        if o.confirm_lit is not None:
            # literal -w/-x: vectorized boundary confirm — the selected
            # lines are computed directly (they are a subset of the
            # engine's occurrence lines by construction)
            from distributed_grep_tpu.apps.grep import literal_mode_lines

            sel = literal_mode_lines(
                contents, o.confirm_lit, o.confirm_mode, nl
            )
            emit = _np.intersect1d(emit, sel)
        else:
            # Batched -w/-x confirm (round 8): ONE vectorized span pass,
            # then the host regex runs over zero-copy memoryview slices
            # of the SOURCE buffer — replacing a per-line line_span()
            # call + contents slice (~8 us/line over dense candidates).
            # Slices, not pos/endpos: the confirm regex anchors (\A/\Z,
            # the -w lookarounds) must see each LINE as the whole string
            # — a memoryview slice is exactly that, with no gather.
            from distributed_grep_tpu.runtime.columnar import line_spans

            starts, ends = line_spans(emit, nl, len(contents))
            progress = _progress_fn()
            mv = memoryview(contents)
            s_l, e_l = starts.tolist(), ends.tolist()

            def confirmed():
                for i in range(emit.size):
                    _stamp_every(progress, i)  # -w/-x over dense candidates
                    yield o.confirm.search(mv[s_l[i] : e_l[i]]) is not None

            keep = _np.fromiter(confirmed(), dtype=bool, count=emit.size)
            emit = emit[keep]
    if o.invert:
        emit = _np.setdiff1d(
            _np.arange(1, count_lines(contents) + 1, dtype=_np.int64), emit
        )
    if o.count_only:
        return [KeyValue(key=filename, value=str(int(emit.size)))]
    if not emit.size:
        return []
    if nl is None:
        nl = newline_index(contents)
    # Columnar emit, DEFERRED (rounds 5+8): ONE batch for the whole split,
    # carrying (source bytes, line numbers, newline index) instead of a
    # gathered slab — the worker's shuffle partitions it straight from the
    # source in one native pass (dgrep_build_records), so the intermediate
    # whole-batch gather the round-5 path paid never runs.  Anything that
    # needs the slab (tests, per-record consumers) materializes lazily
    # (runtime/columnar.DeferredBatch); `contents` is alive for the map
    # task's lifetime anyway on this whole-bytes path.
    batch = DeferredBatch(
        filename, emit, _np.frombuffer(contents, dtype=_np.uint8), nl,
        len(contents),
    )
    return [batch]


def map_path_fn(filename: str, path: str) -> list[KeyValue]:
    """Streaming map: the worker hands over a local path and the engine
    scans it in newline-aligned chunks (GrepEngine.scan_file) — splits
    larger than worker RAM flow end-to-end, the capability the reference's
    whole-file read forecloses (worker.go:72-76).  Matched line text is
    collected while each chunk is in memory, so output stays O(matches).

    grep -v needs every non-matching line — the complement of a stream of
    matches isn't itself bounded — so invert falls back to the whole-bytes
    path (the runtime only streams when this function is used).
    """
    if _engine is None:
        raise RuntimeError("grep_tpu used before configure() — no pattern set")
    if _invert:
        with open(path, "rb") as f:
            return map_fn(filename, f.read())
    if _count_only:
        if _confirm is None:
            # no -w/-x: the ScanResult's matched-line list IS the answer —
            # skip the per-line emit machinery entirely (549k line_span +
            # callback invocations measured ~1.3 s of a 1.6 s dense map)
            res = _engine.scan_file(
                path, progress=_progress_fn(), stop_after_match=_presence
            )
            return [KeyValue(key=filename, value=str(len(res.matched_lines)))]
        # -w/-x confirm needs the line bytes; count with O(1) state.
        # Presence mode stops the stream once one line CONFIRMS (the
        # engine's own match bit is pre-confirm, so stop_after_match
        # would false-positive here — the stop predicate decides).
        n = 0
        if _confirm_lit is not None:
            from distributed_grep_tpu.apps.grep import literal_mode_lines

            def emit_chunk_count(lines_before, buf, mlines, nl_idx) -> None:
                nonlocal n
                n += int(literal_mode_lines(
                    buf, _confirm_lit, _confirm_mode, nl_idx
                ).size)

            _engine.scan_file(
                path, emit_chunk=emit_chunk_count, progress=_progress_fn(),
                stop=(lambda: n > 0) if _presence else None,
            )
            return [KeyValue(key=filename, value=str(n))]

        def emit_count(line_no: int, line: bytes) -> None:
            nonlocal n
            if _confirm.search(line):
                n += 1

        _engine.scan_file(
            path, emit=emit_count, progress=_progress_fn(),
            stop=(lambda: n > 0) if _presence else None,
        )
        return [KeyValue(key=filename, value=str(n))]
    # Columnar emit (round 5): one LineBatch per streamed chunk, built
    # with vectorized span gathers (runtime/columnar.py) — the -w/-x
    # confirm still runs per candidate line (it is a host regex), but the
    # surviving lines batch the same way.
    import os as _os

    batches: list = []
    progress = _progress_fn()
    file_size = _os.path.getsize(path)

    def emit_chunk(lines_before: int, buf: bytes, mlines, nl_idx) -> None:
        # one map:emit span per chunk: record build separated from scan
        # time on the worker's trace row (same contract as _records_for)
        with _spans_mod.span("map:emit", cat="map"):
            _emit_chunk_inner(lines_before, buf, mlines, nl_idx)

    def _emit_chunk_inner(lines_before: int, buf: bytes, mlines, nl_idx) -> None:
        arr = _np.frombuffer(buf, dtype=_np.uint8)
        if _confirm is not None and _confirm_lit is not None:
            # literal -w/-x: one vectorized boundary confirm per chunk,
            # BEFORE the batch is built — rejected candidates never get
            # their spans gathered at all
            from distributed_grep_tpu.apps.grep import literal_mode_lines

            sel = literal_mode_lines(buf, _confirm_lit, _confirm_mode, nl_idx)
            mlines = mlines[_np.isin(mlines, sel)]
            if not mlines.size:
                return
        if (lines_before == 0 and len(buf) == file_size
                and (_confirm is None or _confirm_lit is not None)):
            # The whole file fits this one chunk (the common CLI shape:
            # files at or under the 64 MB chunk target): the buffer's
            # lifetime equals the whole-bytes path's, so the slab gather
            # defers like _records_for (round 8) and the shuffle
            # partitions straight from the source bytes in one native
            # pass.  Multi-chunk streams keep eager batches — deferring
            # would pin every chunk until shuffle.  The regex -w/-x leg
            # also stays eager: its confirm reads per-line bytes anyway.
            if mlines.size:
                batches.append(
                    DeferredBatch(filename, mlines, arr, nl_idx, len(buf))
                )
            return
        batch = make_batch_from_lines(
            filename, mlines, arr, nl_idx, len(buf),
            lineno_base=lines_before,
        )
        if _confirm is not None and _confirm_lit is None:

            def confirmed():
                for i in range(len(batch)):
                    _stamp_every(progress, i)  # dense -w/-x candidates
                    yield bool(_confirm.search(batch.line_bytes(i)))

            keep = _np.fromiter(confirmed(), dtype=bool, count=len(batch))
            if not keep.all():
                batch = batch.select(keep)
        if len(batch):
            batches.append(batch)

    _engine.scan_file(path, emit_chunk=emit_chunk, progress=progress)
    return batches


def reduce_fn(key: str, values: list[str]) -> str:
    return values[0]

"""Word-count application — proves the application boundary is pluggable.

The reference framework is application-agnostic (any Map/Reduce pair behind
the plugin interface, main/worker_launch.go:21-34); word count is the
canonical second app and, unlike grep, exercises a non-identity Reduce.
"""

from __future__ import annotations

import re

from distributed_grep_tpu.apps.base import KeyValue

_WORD = re.compile(rb"[A-Za-z]+")


def map_fn(filename: str, contents: bytes) -> list[KeyValue]:
    return [
        KeyValue(key=m.group(0).decode("ascii").lower(), value="1")
        for m in _WORD.finditer(contents)
    ]


def reduce_fn(key: str, values: list[str]) -> str:
    return str(sum(int(v) for v in values))


def reduce_stream_fn(key: str, values) -> str:
    """Streaming fold — the worker prefers this over reduce_fn: a hot key
    ("the" across a 100 GB corpus) never materializes its value list
    (runtime/extsort.py)."""
    return str(sum(int(v) for v in values))

"""Inverted index — the third application on the Map/Reduce boundary.

The 6.824 lab family's other canonical app (the reference ships only grep,
application/grep.go): Map emits (word, filename) per distinct word in the
split; Reduce folds the filenames into "count file1,file2,..." sorted and
de-duplicated.  Exists to prove the application boundary generalizes
beyond grep and wordcount — no engine coupling, pure contract.
"""

from __future__ import annotations

import re

from distributed_grep_tpu.apps.base import KeyValue

_word_re = re.compile(rb"[A-Za-z]+")
_min_len = 1


def configure(min_word_len: int = 1, **_: object) -> None:
    global _min_len
    _min_len = int(min_word_len)


def map_fn(filename: str, contents: bytes) -> list[KeyValue]:
    words = {
        w.lower().decode("ascii")
        for w in _word_re.findall(contents)
        if len(w) >= _min_len
    }
    return [KeyValue(key=w, value=filename) for w in sorted(words)]


def reduce_fn(key: str, values: list[str]) -> str:
    files = sorted(set(values))
    return f"{len(files)} {','.join(files)}"

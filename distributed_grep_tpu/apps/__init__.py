"""Pluggable Map/Reduce applications.

The application boundary mirrors the reference's plugin contract
(application/grep.go:13-40): an application supplies

    map(filename: str, contents: bytes) -> list[KeyValue]
    reduce(key: str, values: list[str]) -> str

and is loaded dynamically (loader.py is the equivalent of the Go
``plugin.Open`` + symbol lookup in main/worker_launch.go:21-34).  CPU grep
and TPU grep are drop-in interchangeable behind this interface.
"""

from distributed_grep_tpu.apps.base import Application, KeyValue
from distributed_grep_tpu.apps.loader import load_application

__all__ = ["Application", "KeyValue", "load_application"]

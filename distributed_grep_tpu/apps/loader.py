"""Dynamic application loading — the Go-plugin equivalent.

The reference worker launcher opens an application ``.so`` with
``plugin.Open`` and looks up the ``Map``/``Reduce`` symbols
(main/worker_launch.go:21-34).  Here an application is a Python module,
addressed either by dotted name (``distributed_grep_tpu.apps.grep``) or by
filesystem path (``/path/to/my_app.py``), exposing either

* ``map_fn`` / ``reduce_fn`` (preferred), or
* ``Map`` / ``Reduce``       (reference-style names), and optionally
* ``configure(**options)``   (job options, e.g. the grep pattern).

The loader fixes the reference's ``LoadMR`` return-type bug
(main/worker_launch.go:21 vs :30) by validating both callables at load time.
"""

from __future__ import annotations

import importlib.util
import itertools
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable

from distributed_grep_tpu.apps.base import KeyValue


@dataclass
class LoadedApplication:
    """A validated Map/Reduce function pair plus its source module."""

    name: str
    map_fn: Callable[[str, bytes], list[KeyValue]]
    reduce_fn: Callable[[str, list[str]], str]
    module: Any
    # optional streaming entry: receives a local file path instead of bytes
    # (the worker then spools/streams the split — splits larger than RAM)
    map_path_fn: Callable[[str, str], list[KeyValue]] | None = None
    # optional batched entry: receives a LIST of (filename, contents)
    # pairs for a multi-file map split (runtime/job.plan_map_splits) and
    # may amortize work across them (grep_tpu packs them into shared
    # device dispatches).  Apps without one get map_fn called per member.
    map_batch_fn: Callable[[list], list[KeyValue]] | None = None
    # declared by apps whose map_batch_fn also accepts (filename, PATH)
    # pairs: on a local data plane the worker hands over resolved paths
    # instead of reading members, so the engine's device corpus cache
    # (round 7) can serve a warm window with zero file reads
    map_batch_paths: bool = False
    # optional streaming reduce: receives a value ITERATOR — hot keys never
    # materialize their value list (runtime/extsort.py); must agree with
    # reduce_fn on every input
    reduce_stream_fn: Callable[[str, Any], str] | None = None
    # optional fused entry (cross-tenant scan fusion, runtime/fusion.py +
    # ops/fuse.py): map_fused_fn(items, participants) scans the split
    # ONCE for K co-tenant queries and returns one record list per
    # participant — each bit-identical to that participant's own
    # map_batch_fn over the same items.  ``participants`` carry each
    # tenant's app_options and member names.
    map_fused_fn: Callable[[list, list], list] | None = None

    def configure(self, **options: Any) -> None:
        hook = getattr(self.module, "configure", None)
        if hook is not None:
            hook(**options)

    def set_progress(self, fn: Any) -> bool:
        """Install (or clear, fn=None) a progress callback for the current
        task — apps that support it call fn() at work milestones (per
        chunk/segment) and fn(grace_s=N) ahead of a known-silent phase;
        the worker wires it to the coordinator heartbeat so the failure
        detector can run a tight window over long maps.  Returns whether
        the application supports progress reporting."""
        hook = getattr(self.module, "set_progress", None)
        if hook is None:
            return False
        hook(fn)
        return True


_instance_counter = itertools.count()


def _fresh_instance_name(stem: str) -> str:
    # Every load gets its own module instance (unique sys.modules key) so two
    # concurrent jobs never share application state — module-level config like
    # the grep pattern stays per-job, not per-process.
    return f"_dgrep_app_{stem}_{next(_instance_counter)}"


def _import_by_path(path: str) -> Any:
    p = Path(path)
    spec = importlib.util.spec_from_file_location(_fresh_instance_name(p.stem), p)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load application from path: {path}")
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def _import_fresh_by_name(dotted: str) -> Any:
    spec = importlib.util.find_spec(dotted)
    if spec is None or spec.loader is None:
        raise ImportError(f"no module named {dotted!r}")
    fresh = importlib.util.spec_from_file_location(
        _fresh_instance_name(dotted.rsplit(".", 1)[-1]), spec.origin
    )
    module = importlib.util.module_from_spec(fresh)
    sys.modules[fresh.name] = module
    fresh.loader.exec_module(module)
    return module


def load_application(spec: str, **options: Any) -> LoadedApplication:
    """Load an application by dotted module name or .py file path.

    ``options`` are forwarded to the module's ``configure`` hook if present
    (the plumbing the reference's TODO at coordinator.go:41 never built).
    """
    if spec.endswith(".py") or "/" in spec:
        module = _import_by_path(spec)
    else:
        module = _import_fresh_by_name(spec)

    map_fn = getattr(module, "map_fn", None) or getattr(module, "Map", None)
    reduce_fn = getattr(module, "reduce_fn", None) or getattr(module, "Reduce", None)
    if not callable(map_fn) or not callable(reduce_fn):
        raise TypeError(
            f"application {spec!r} must expose callable map_fn/reduce_fn "
            f"(or Map/Reduce); got map={map_fn!r} reduce={reduce_fn!r}"
        )
    map_path_fn = getattr(module, "map_path_fn", None)
    map_batch_fn = getattr(module, "map_batch_fn", None)
    reduce_stream_fn = getattr(module, "reduce_stream_fn", None)
    map_fused_fn = getattr(module, "map_fused_fn", None)
    app = LoadedApplication(
        name=spec,
        map_fn=map_fn,
        reduce_fn=reduce_fn,
        module=module,
        map_path_fn=map_path_fn if callable(map_path_fn) else None,
        map_batch_fn=map_batch_fn if callable(map_batch_fn) else None,
        map_batch_paths=bool(getattr(module, "map_batch_paths", False))
        and callable(map_batch_fn),
        reduce_stream_fn=reduce_stream_fn if callable(reduce_stream_fn) else None,
        map_fused_fn=map_fused_fn if callable(map_fused_fn) else None,
    )
    if options:
        app.configure(**options)
    return app

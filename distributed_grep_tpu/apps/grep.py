"""CPU grep application — the reference flagship app, with the pattern plumbed.

Mirrors application/grep.go: Map splits the input on newlines and emits one
record per matching line with key ``"<filename> (line number #N)"`` and value
= the line (grep.go:17-30); Reduce is the identity on the first value
(grep.go:38-40) — grep needs no aggregation, reduce only collates output.

Differences from the reference, on purpose:

* The pattern actually works.  The reference initializes ``pattern = ""``
  and never sets it (grep.go:11, TODO at coordinator.go:41), so every line
  matches.  Here the job config calls ``configure(pattern=...)`` before any
  map task runs.
* Input is bytes, decoded permissively (grep must survive non-UTF8 corpora).
* Line numbers are 1-based like grep -n (the reference is 0-based via
  ``range`` index; 1-based is what users of grep expect and what our tests
  compare against).
"""

from __future__ import annotations

import re

from distributed_grep_tpu.apps.base import KeyValue

# Job-configured state (set via configure(); the reference's missing plumbing).
# The loader gives every job its own module instance, so this is per-job, not
# per-process, state.
_pattern: re.Pattern[bytes] = re.compile(b"")
_configured_with: tuple | None = None


def configure(pattern: str | bytes = b"", ignore_case: bool = False, **_: object) -> None:
    global _pattern, _configured_with
    if isinstance(pattern, str):
        pattern = pattern.encode("utf-8")
    key = (pattern, ignore_case)
    if key == _configured_with:
        return  # configure runs per task assignment; skip the recompile
    _pattern = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    _configured_with = key


def map_fn(filename: str, contents: bytes) -> list[KeyValue]:
    out: list[KeyValue] = []
    for lineno, line in enumerate(contents.split(b"\n"), start=1):
        if _pattern.search(line):
            out.append(
                KeyValue(
                    key=f"{filename} (line number #{lineno})",
                    value=line.decode("utf-8", errors="replace"),
                )
            )
    return out


def reduce_fn(key: str, values: list[str]) -> str:
    return values[0]

"""CPU grep application — the reference flagship app, with the pattern plumbed.

Mirrors application/grep.go: Map splits the input on newlines and emits one
record per matching line with key ``"<filename> (line number #N)"`` and value
= the line (grep.go:17-30); Reduce is the identity on the first value
(grep.go:38-40) — grep needs no aggregation, reduce only collates output.

Differences from the reference, on purpose:

* The pattern actually works.  The reference initializes ``pattern = ""``
  and never sets it (grep.go:11, TODO at coordinator.go:41), so every line
  matches.  Here the job config calls ``configure(pattern=...)`` before any
  map task runs.
* Input is bytes, decoded permissively (grep must survive non-UTF8 corpora).
* Line numbers are 1-based like grep -n (the reference is 0-based via
  ``range`` index; 1-based is what users of grep expect and what our tests
  compare against).
"""

from __future__ import annotations

import re

from distributed_grep_tpu.apps.base import KeyValue

# Job-configured state (set via configure(); the reference's missing plumbing).
# The loader gives every job its own module instance, so this is per-job, not
# per-process, state.
_pattern: re.Pattern[bytes] | None = re.compile(b"")
_ac_tables: list | None = None  # Aho-Corasick banks when configured with a set
_invert: bool = False  # grep -v
_configured_with: tuple | None = None


def configure(
    pattern: str | bytes = b"",
    ignore_case: bool = False,
    patterns: list[str | bytes] | None = None,
    invert: bool = False,
    **_: object,
) -> None:
    """``pattern`` is a regex; ``patterns`` is a literal set (grep -F -f).
    Sets compile to Aho-Corasick banks scanned by the native C DFA scanner
    (a 10k-literal alternation through Python re would be O(set) per byte),
    keeping the CPU app interchangeable with the TPU app on big rulesets.
    ``invert`` = grep -v: emit the lines that do NOT match."""
    global _pattern, _ac_tables, _invert, _configured_with
    if isinstance(pattern, str):
        pattern = pattern.encode("utf-8", "surrogateescape")
    _invert = bool(invert)
    key = (pattern, ignore_case, tuple(patterns) if patterns else None, _invert)
    if key == _configured_with:
        return  # configure runs per task assignment; skip the recompile
    if patterns:
        from distributed_grep_tpu.models.aho import compile_aho_corasick_banks

        norm = [
            p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p)
            for p in patterns
        ]
        _ac_tables = compile_aho_corasick_banks(norm, ignore_case=ignore_case)
        _pattern = None
    else:
        _ac_tables = None
        _pattern = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    _configured_with = key


def map_fn(filename: str, contents: bytes) -> list[KeyValue]:
    if _ac_tables is not None:
        matched = _ac_matched_lines(contents)
    else:
        matched = None
    lines = contents.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()  # trailing '\n' does not open a phantom empty line (grep -n)
    out: list[KeyValue] = []
    for lineno, line in enumerate(lines, start=1):
        hit = (lineno in matched) if matched is not None else _pattern.search(line)
        if bool(hit) != _invert:
            out.append(
                KeyValue(
                    key=f"{filename} (line number #{lineno})",
                    value=line.decode("utf-8", errors="replace"),
                )
            )
    return out


def _ac_matched_lines(contents: bytes) -> set[int]:
    """One native DFA pass per bank over the whole split; offsets -> lines."""
    import numpy as np

    from distributed_grep_tpu.models.dfa import reference_scan
    from distributed_grep_tpu.ops.lines import line_of_offsets, newline_index

    offsets = np.unique(
        np.concatenate([reference_scan(t, contents) for t in _ac_tables])
    )
    if offsets.size == 0:
        return set()
    nl = newline_index(contents)
    return set(line_of_offsets(offsets.astype(np.int64), nl).tolist())


def reduce_fn(key: str, values: list[str]) -> str:
    return values[0]

"""CPU grep application — the reference flagship app, with the pattern plumbed.

Mirrors application/grep.go: Map splits the input on newlines and emits one
record per matching line with key ``"<filename> (line number #N)"`` and value
= the line (grep.go:17-30); Reduce is the identity on the first value
(grep.go:38-40) — grep needs no aggregation, reduce only collates output.

Differences from the reference, on purpose:

* The pattern actually works.  The reference initializes ``pattern = ""``
  and never sets it (grep.go:11, TODO at coordinator.go:41), so every line
  matches.  Here the job config calls ``configure(pattern=...)`` before any
  map task runs.
* Input is bytes, decoded permissively (grep must survive non-UTF8 corpora).

Line numbers are 1-based like grep -n — SAME as the reference, whose Map
emits ``line_number+1`` over its 0-based ``range`` index (grep.go:25).
"""

from __future__ import annotations

import re

from distributed_grep_tpu.apps.base import KeyValue

# Reduce is values[0] and keys are unique per (file, line): the runtime's
# identity-reduce collator keeps map output columnar and writes
# (file, line)-ordered output (runtime/columnar.py) — interchangeable
# with apps/grep_tpu.py, including the shuffle fast path.
reduce_is_identity = True

# Job-configured state (set via configure(); the reference's missing plumbing).
# The loader gives every job its own module instance, so this is per-job, not
# per-process, state.
_pattern: re.Pattern[bytes] | None = re.compile(b"")
_ac_tables: list | None = None  # Aho-Corasick banks when configured with a set
_ac_confirm: re.Pattern[bytes] | None = None  # -w/-x confirm for set mode
_invert: bool = False  # grep -v
_line_mode: str = "search"  # "search" | "word" (-w) | "line" (-x)
_count_only: bool = False  # emit one per-file count record, not per-line
_presence: bool = False  # -q/-l/-L: truthiness only; may stop at first hit
_configured_with: tuple | None = None

# GNU grep word constituents in the C locale: [A-Za-z0-9_]
_W = rb"[0-9A-Za-z_]"


def wrap_mode(pattern: bytes, mode: str) -> bytes:
    """Wrap a regex for grep -w / -x semantics.  Non-capturing, so group
    numbers (and any backreferences) inside ``pattern`` are unchanged."""
    if mode == "word":
        return rb"(?<!" + _W + rb")(?:" + pattern + rb")(?!" + _W + rb")"
    if mode == "line":
        return rb"\A(?:" + pattern + rb")\Z"
    return pattern


def build_confirm(
    pattern: str | bytes | None = None,
    patterns: list | None = None,
    ignore_case: bool = False,
    mode: str = "search",
) -> "re.Pattern[bytes] | None":
    """The -w/-x per-line confirm regex — ONE definition for every
    consumer (this app, apps/grep_tpu.configure, the CLI's streaming
    stdin path): a literal set escapes and alternates, a single pattern
    wraps as-is; mode 'search' needs no confirm (None)."""
    if mode == "search":
        return None
    if patterns is not None:
        norm = [
            p.encode("utf-8", "surrogateescape") if isinstance(p, str)
            else bytes(p) for p in patterns
        ]
        base = b"(?:" + b"|".join(re.escape(p) for p in norm) + b")"
    else:
        from distributed_grep_tpu.models.dfa import expand_posix_classes

        # POSIX classes must expand before re sees them (re misparses
        # [[:digit:]]; models/dfa.expand_posix_classes docstring)
        base = expand_posix_classes(
            pattern.encode("utf-8", "surrogateescape")
            if isinstance(pattern, str) else bytes(pattern)
        )
    return re.compile(
        wrap_mode(base, mode), re.IGNORECASE if ignore_case else 0
    )


def configure(
    pattern: str | bytes = b"",
    ignore_case: bool = False,
    patterns: list[str | bytes] | None = None,
    invert: bool = False,
    word_regexp: bool = False,
    line_regexp: bool = False,
    count_only: bool = False,
    presence_only: bool = False,
    **_: object,
) -> None:
    """``pattern`` is a regex; ``patterns`` is a literal set (grep -F -f).
    Sets compile to Aho-Corasick banks scanned by the native C DFA scanner
    (a 10k-literal alternation through Python re would be O(set) per byte),
    keeping the CPU app interchangeable with the TPU app on big rulesets.
    ``invert`` = grep -v: emit the lines that do NOT match.  ``word_regexp``
    / ``line_regexp`` = grep -w / -x: the scan stays on the raw pattern
    (set mode: candidates from the AC banks) and each candidate line is
    confirmed against the boundary-wrapped regex.  ``count_only`` = count
    queries (grep -c/-l/-L/-q): one record per file, key = filename, value
    = selected line count — same contract as apps/grep_tpu.py."""
    global _pattern, _ac_tables, _ac_confirm, _invert, _line_mode, \
        _count_only, _presence, _configured_with
    if isinstance(pattern, str):
        pattern = pattern.encode("utf-8", "surrogateescape")
    _invert = bool(invert)
    _count_only = bool(count_only)
    _presence = bool(presence_only)
    _line_mode = "line" if line_regexp else ("word" if word_regexp else "search")
    key = (pattern, ignore_case, tuple(patterns) if patterns else None, _invert,
           _line_mode)
    if key == _configured_with:
        return  # configure runs per task assignment; skip the recompile
    flags = re.IGNORECASE if ignore_case else 0
    if patterns:
        from distributed_grep_tpu.models.aho import compile_aho_corasick_banks

        norm = [
            p.encode("utf-8", "surrogateescape") if isinstance(p, str) else bytes(p)
            for p in patterns
        ]
        _ac_tables = compile_aho_corasick_banks(norm, ignore_case=ignore_case)
        _pattern = None
        _ac_confirm = build_confirm(
            patterns=norm, ignore_case=ignore_case, mode=_line_mode
        )
    else:
        from distributed_grep_tpu.models.dfa import expand_posix_classes

        _ac_tables = None
        _ac_confirm = None
        # expand POSIX classes for re (this app IS re-based by design —
        # the reference mirror); keeps it line-identical to the TPU app
        _pattern = re.compile(
            wrap_mode(expand_posix_classes(pattern), _line_mode), flags
        )
    _configured_with = key


_WORD_BYTES = None  # lazy [256] bool lookup: GNU word constituents


def literal_mode_lines(
    contents: bytes, lit: bytes, mode: str, nl=None
):
    """1-based line numbers ``grep -w`` / ``-x`` selects for a LITERAL
    pattern — the vectorized replacement for the per-candidate-line regex
    confirm (measured ~8 us/line over 663k dense candidates): one native
    occurrence scan plus boundary-byte masks.  Semantically identical to
    ``wrap_mode``'s lookarounds (which are differentially pinned against
    GNU grep 3.8): -w keeps occurrences whose previous AND next bytes are
    non-word (line/buffer edges count as non-word); -x keeps occurrences
    spanning exactly line start to line end."""
    import numpy as np

    from distributed_grep_tpu.ops.lines import newline_index
    from distributed_grep_tpu.utils.native import literal_scan

    global _WORD_BYTES
    if _WORD_BYTES is None:
        t = np.zeros(256, dtype=bool)
        # GNU word constituents in the C locale (_W): 0-9 A-Z a-z _
        for lo, hi in ((48, 57), (65, 90), (97, 122)):
            t[lo : hi + 1] = True
        t[95] = True  # '_'
        _WORD_BYTES = t
    ends = literal_scan(contents, lit).astype(np.int64)
    empty = np.zeros(0, dtype=np.int64)
    if not ends.size:
        return empty
    n = len(contents)
    arr = np.frombuffer(contents, dtype=np.uint8)
    starts = ends - len(lit)
    prev = np.where(starts > 0, arr[np.maximum(starts - 1, 0)], 0x0A)
    nxt = np.where(ends < n, arr[np.minimum(ends, n - 1)], 0x0A)
    if mode == "word":
        ok = ~_WORD_BYTES[prev] & ~_WORD_BYTES[nxt]
    else:  # "line": the occurrence IS the whole line
        ok = (prev == 0x0A) & (nxt == 0x0A)
    ends = ends[ok]
    if not ends.size:
        return empty
    if nl is None:
        nl = newline_index(contents)
    # ends stay ascending through the boolean mask: native linear merge
    from distributed_grep_tpu.ops.lines import unique_match_lines

    return unique_match_lines(ends, nl)


def map_fn(filename: str, contents: bytes) -> list[KeyValue]:
    if _ac_tables is not None:
        matched = _ac_matched_lines(contents)
    else:
        matched = None
    lines = contents.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()  # trailing '\n' does not open a phantom empty line (grep -n)
    sel_nos: list[int] = []
    sel_lines: list[bytes] = []
    n_selected = 0
    for lineno, line in enumerate(lines, start=1):
        if matched is not None:
            hit = lineno in matched and (
                _ac_confirm is None or _ac_confirm.search(line)
            )
        else:
            hit = _pattern.search(line)
        if bool(hit) != _invert:
            if _count_only:
                n_selected += 1
                if _presence:
                    break  # grep -q/-l: first selected line settles it
                continue
            sel_nos.append(lineno)
            sel_lines.append(line)
    if _count_only:
        return [KeyValue(key=filename, value=str(n_selected))]
    if not sel_nos:
        return []
    # Columnar emit (round 5): one LineBatch for the split — a join + a
    # cumsum instead of a KeyValue + f-string + utf-8 decode per matched
    # line (runtime/columnar.py; same record semantics, same shuffle
    # partitioning).
    import numpy as np

    from distributed_grep_tpu.runtime.columnar import LineBatch

    lens = np.fromiter(
        (len(l) for l in sel_lines), dtype=np.int64, count=len(sel_lines)
    )
    offsets = np.zeros(lens.size + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    return [LineBatch(
        filename=filename,
        linenos=np.asarray(sel_nos, dtype=np.int64),
        offsets=offsets,
        slab=b"".join(sel_lines),
    )]


def _ac_matched_lines(contents: bytes) -> set[int]:
    """One native DFA pass per bank over the whole split; offsets -> lines."""
    import numpy as np

    from distributed_grep_tpu.models.dfa import reference_scan
    from distributed_grep_tpu.ops.lines import line_of_offsets, newline_index

    offsets = np.unique(
        np.concatenate([reference_scan(t, contents) for t in _ac_tables])
    )
    if offsets.size == 0:
        return set()
    nl = newline_index(contents)
    return set(line_of_offsets(offsets.astype(np.int64), nl).tolist())


def reduce_fn(key: str, values: list[str]) -> str:
    return values[0]

"""CLI launchers — the L4 layer (reference: main/coordinator_launch.go,
main/worker_launch.go), unified into one entry point.

    python -m distributed_grep_tpu grep PATTERN FILE...        in-process grep
    python -m distributed_grep_tpu run --config job.json       any application
    python -m distributed_grep_tpu coordinator --config ...    distributed mode
    python -m distributed_grep_tpu worker --addr host:port     distributed mode

The reference's coordinator takes input files as argv and hardcodes
everything else (coordinator_launch.go:11-23); the worker takes the
application .so path (worker_launch.go:11-19).  Here both take a JobConfig
(JSON + flag overrides) and applications are Python modules.
"""

from __future__ import annotations

import argparse
import json
import sys

from distributed_grep_tpu.utils.config import JobConfig


# group-number-sensitivity check (backreferences / conditional group
# tests, which do not survive being joined into an alternation): ONE
# definition, shared with the scan-fusion eligibility guard — re-homed
# to runtime/fusion.py (ops-free, CLI-importable) in round 13
from distributed_grep_tpu.runtime.fusion import has_backref as _has_backref


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--n-reduce", type=int, default=None)
    p.add_argument("--workers", type=int, default=2, help="in-process worker threads")
    p.add_argument("--work-dir", default=None)
    p.add_argument("--backend", default=None, choices=["cpu", "tpu", "auto"])
    p.add_argument("--metrics", action="store_true", help="print job metrics to stderr")


def _validate_regex(rx: str):
    """re.compile after POSIX-class expansion — the user-facing validity
    check.  Expansion can itself reject (unknown [:name:], like GNU's
    "Unknown character class name"); both failures surface as the same
    invalid-pattern diagnostic (exit 2)."""
    import re

    from distributed_grep_tpu.models.dfa import RegexError, expand_posix_classes

    try:
        re.compile(expand_posix_classes(rx))
    except RegexError as e:
        raise re.error(str(e)) from e


def _grep_stdin_stream(args: argparse.Namespace, patterns) -> int:
    """GNU-streaming stdin grep (round 5): one in-process split fed from
    incremental pipe reads through the same engine the job path uses.

    Chunks cut at newline boundaries keep every engine mode exact (the
    scan_file contract, ops/engine.py); -w/-x candidates confirm against
    the boundary-wrapped regex per line like the apps do.  Presence
    queries return at the first selected line — the pipe is NOT drained
    (GNU semantics the round-4 spool could not give); -m stops reading at
    the cap like GNU.  Reference: worker.go:72-76 reads whole files; GNU
    grep streams — this path sides with GNU.
    """
    from distributed_grep_tpu.ops.engine import GrepEngine
    from distributed_grep_tpu.ops.lines import count_lines, line_span, newline_index

    label = "(standard input)"
    backend = (
        "cpu"
        if args.backend == "cpu"
        or (args.backend is None and not args.max_errors)
        else "device"
    )
    try:
        eng = GrepEngine(
            args.pattern if patterns is None else None,
            patterns=patterns,
            ignore_case=args.ignore_case,
            backend=backend,
            max_errors=args.max_errors or 0,
            **({"devices": "all"} if backend == "device" else {}),
        )
    except Exception as e:  # noqa: BLE001 — mirrors the job path's exit 2
        print(f"error: invalid pattern: {e}", file=sys.stderr)
        return 2
    from distributed_grep_tpu.apps.grep import build_confirm

    confirm = build_confirm(
        pattern=args.pattern, patterns=patterns,
        ignore_case=args.ignore_case,
        mode=(
            "line" if args.line_regexp
            else "word" if args.word_regexp else "search"
        ),
    )

    presence = args.quiet or args.files_with_matches or args.files_without_match
    f = sys.stdin.buffer
    # read1 (not read): a pipe must hand over whatever is AVAILABLE, not
    # block until a full chunk accumulates — which is also why this loop
    # cannot reuse GrepEngine.scan_file (its pipelined reader issues
    # full-chunk read() calls, correct for files, a stall on live pipes);
    # the newline-carry logic mirrors scan_file's chunk contract.
    read1 = getattr(f, "read1", None) or f.read
    carry = b""
    lines_before = 0
    n_selected = 0
    cap = args.max_count
    stdout = sys.stdout
    # GNU -m 0 selects nothing, prints nothing, exits 1 — and reads
    # nothing (probed: `printf 'a\n' | grep -m 0 a -` returns at once)
    done = cap == 0
    while not done:
        block = read1(1 << 20)
        final = not block
        buf = carry + block
        if not final:
            cut = buf.rfind(b"\n")
            if cut < 0:
                carry = buf  # no complete line yet: keep reading
                continue
            carry, buf = buf[cut + 1 :], buf[: cut + 1]
        else:
            carry = b""
        if buf:
            sel = eng.scan(buf).matched_lines.tolist()
            nl = None
            if confirm is not None and sel:
                nl = newline_index(buf)
                sel = [
                    ln for ln in sel
                    if confirm.search(buf[slice(*line_span(nl, ln, len(buf)))])
                ]
            if args.invert:
                sel = sorted(set(range(1, count_lines(buf) + 1)) - set(sel))
            for ln in sel:
                n_selected += 1
                if presence:
                    done = True
                    break  # first selected line settles -q/-l/-L
                if not args.count:
                    if nl is None:
                        nl = newline_index(buf)
                    s, e = line_span(nl, ln, len(buf))
                    head = "" if args.no_filename else f"{label} "
                    print(
                        f"{head}(line number #{lines_before + ln}) "
                        f"{buf[s:e].decode('utf-8', errors='replace')}",
                        file=stdout,
                    )
                if cap is not None and n_selected >= cap:
                    done = True  # GNU -m: stop READING at the cap
                    break
            if sel and not presence and not args.count:
                stdout.flush()  # matches appear as the pipe produces them
            lines_before += count_lines(buf)
        if final:
            break
    rc = 0 if n_selected else 1

    def finish(code: int) -> int:
        if args.metrics:
            # the streaming path has no job/scheduler, so the metrics are
            # the stream's own counters (same stderr-JSON contract as the
            # job path's res.metrics)
            print(json.dumps({
                "counters": {
                    "stdin_lines": lines_before,
                    "selected_lines": n_selected,
                },
                "streaming_stdin": True,
            }, indent=2, sort_keys=True), file=sys.stderr)
        return code

    if args.quiet:
        return finish(rc)
    if args.files_with_matches:
        if n_selected:
            print(label)
        return finish(rc)
    if args.files_without_match:
        if not n_selected:
            print(label)
        return finish(rc)
    if args.count:
        shown = n_selected if cap is None else min(n_selected, cap)
        prefix = (
            f"{label}:"
            if args.with_filename and not args.no_filename else ""
        )
        print(f"{prefix}{shown}")
        return finish(rc)
    return finish(rc)


def _resolve_pattern_args(args: argparse.Namespace) -> tuple[int, list | None]:
    """Resolve -e/-f/-F/-E plus the positional PATTERN slot into the
    engine-facing query — the ONE front half shared by cmd_grep and
    cmd_submit (service tenants must be able to submit the same
    multi-pattern jobs the local CLI runs; ISSUE 11 satellite).  Returns
    (0, patterns) on success — ``args.pattern`` then holds the
    single-pattern form (possibly a joined alternation), ``patterns``
    the literal set (grep -F / plain -f) — or (2, None) after printing
    the GNU-shaped diagnostic.  Mutates args like GNU's option rules: a
    positional PATTERN displaced by -e/-f parses as the first input
    file."""
    import re
    from pathlib import Path

    patterns: list[str] | None = None
    if args.e_patterns:
        # like grep: -e supplies the pattern(s); the positional slot, if
        # used, parses as the first input file
        if args.pattern is not None:
            args.files.insert(0, args.pattern)
            args.pattern = None
        if args.patterns_file:
            print("error: use -e or -f, not both", file=sys.stderr)
            return 2, None
        if args.fixed_strings:
            # literal set -> set engines; like grep -F, an embedded newline
            # separates alternative patterns
            patterns = [p for e in args.e_patterns for p in e.split("\n")]
        elif len(args.e_patterns) == 1:
            args.pattern = args.e_patterns[0]
        else:
            for rx in args.e_patterns:
                try:
                    _validate_regex(rx)
                except re.error as e:
                    print(f"error: invalid pattern {rx!r}: {e}", file=sys.stderr)
                    return 2, None
            if any(_has_backref(rx) for rx in args.e_patterns):
                print("error: -e patterns use backreferences, which do not "
                      "survive being joined into one alternation",
                      file=sys.stderr)
                return 2, None
            args.pattern = "(?:" + "|".join(
                f"(?:{rx})" for rx in args.e_patterns) + ")"
    elif args.fixed_strings and args.pattern is not None:
        if "\n" in args.pattern:
            patterns = args.pattern.split("\n")  # grep -F: newline = OR
        else:
            args.pattern = re.escape(args.pattern)
    if args.patterns_file:
        if args.pattern is not None:
            # like grep: -f replaces the positional pattern, which then
            # parses as the first input file
            args.files.insert(0, args.pattern)
            args.pattern = None
        pf = Path(args.patterns_file)
        if not pf.exists():
            print(f"error: no such file: {args.patterns_file}", file=sys.stderr)
            return 2, None
        # bytes + surrogateescape: pattern files need not be UTF-8 (the apps
        # re-encode with surrogateescape, so arbitrary bytes round-trip).
        # Split on \n only — splitlines() would also split on \r/\v/\f/\x85
        # and silently fragment literal patterns containing those bytes.
        raw = pf.read_bytes().split(b"\n")
        if raw and raw[-1] == b"":
            raw.pop()  # a trailing newline is a terminator, not an empty pattern
        if not raw:
            print(f"error: empty pattern file: {args.patterns_file}", file=sys.stderr)
            return 2, None
        if any(not ln for ln in raw):
            # grep -f: an empty pattern line matches every line
            patterns = None
            args.pattern = ""
        elif args.extended_regexp:
            # grep -E -f: each line is a regex; the set is their alternation,
            # compiled by the single-pattern engines (NFA/DFA)
            decoded = [ln.decode("utf-8", "surrogateescape") for ln in raw]
            for rx in decoded:
                try:
                    _validate_regex(rx)
                except re.error as e:
                    print(f"error: invalid pattern {rx!r}: {e}", file=sys.stderr)
                    return 2, None
            if len(decoded) > 1 and any(_has_backref(rx) for rx in decoded):
                # Joining lines into one alternation offsets group numbers
                # by the capturing groups of earlier lines, so a line's
                # backreference would silently point at another line's
                # group.  re.compile can't catch the semantic change.
                print(
                    "error: -E -f pattern lines use backreferences, which "
                    "do not survive being joined into one alternation; "
                    "run such patterns individually",
                    file=sys.stderr,
                )
                return 2, None
            patterns = None
            # non-capturing groups: wrapping with (..) would renumber any
            # backreferences inside the lines (the device subset compiler
            # parses (?:..) too, models/dfa.py)
            args.pattern = "(?:" + "|".join(f"(?:{rx})" for rx in decoded) + ")"
        else:
            patterns = [ln.decode("utf-8", "surrogateescape") for ln in raw]
    if args.pattern is None and patterns is None:
        print("error: need a PATTERN or -f FILE", file=sys.stderr)
        return 2, None
    # validate any single-pattern path — including the -E -f alternation,
    # whose wrapping can break group-sensitive regexes (backreferences)
    # even when every line compiled on its own
    if patterns is None and args.pattern is not None:
        try:
            _validate_regex(args.pattern)
        except re.error as e:
            print(f"error: invalid pattern {args.pattern!r}: {e}", file=sys.stderr)
            return 2, None
    return 0, patterns


def cmd_grep(args: argparse.Namespace) -> int:
    import re
    from pathlib import Path

    from distributed_grep_tpu.runtime.job import run_job

    # -R implies -r everywhere (cwd default, stdin gating, the walk);
    # the dereference flag itself only changes symlink handling
    if getattr(args, "dereference_recursive", False):
        args.recursive = True
    if args.fixed_strings and args.extended_regexp:
        print("error: -E and -F are conflicting matchers", file=sys.stderr)
        return 2
    if args.word_regexp and args.line_regexp:
        args.word_regexp = False  # grep: -x subsumes -w
    if args.max_count is not None and args.max_count < 0:
        print("error: invalid max count", file=sys.stderr)
        return 2
    if args.max_errors and (args.word_regexp or args.line_regexp):
        print("error: -w/-x are not supported with --max-errors (approximate "
              "matches have no exact boundaries)", file=sys.stderr)
        return 2
    rc, patterns = _resolve_pattern_args(args)
    if rc:
        return rc
    import os as _os

    if getattr(args, "follow", False):
        # Streaming tier (round 17): a standing query polls its inputs
        # for growth and suffix-scans appends.  Modes that re-read whole
        # files or need a final line set cannot stream; approximate
        # matching and -w/-x keep their one-shot paths.
        conflicts = [
            flag for flag, on in (
                ("-o", args.only_matching),
                ("-A/-B/-C", args.context is not None
                 or args.before_context or args.after_context),
                ("-b", args.byte_offset),
                ("-m", args.max_count is not None),
                ("-w", args.word_regexp),
                ("-x", args.line_regexp),
                ("-L", args.files_without_match),
                ("--max-errors", bool(args.max_errors)),
            ) if on
        ]
        if conflicts:
            print(f"error: --follow does not support "
                  f"{', '.join(conflicts)}", file=sys.stderr)
            return 2
        if (not args.files and not args.recursive) or "-" in args.files:
            print("error: --follow needs named FILE arguments (cannot "
                  "follow standard input)", file=sys.stderr)
            return 2

    if args.max_errors:
        # validated BEFORE any stdin spooling: a guaranteed exit-2
        # invocation must not first drain (and write to disk) the pipe
        if patterns:
            print("error: --max-errors applies to a single pattern, not -f",
                  file=sys.stderr)
            return 2
        from distributed_grep_tpu.models.approx import MAX_ERRORS
        from distributed_grep_tpu.models.shift_and import try_compile_shift_and

        if not 1 <= args.max_errors <= MAX_ERRORS:
            print(f"error: --max-errors must be 1..{MAX_ERRORS}", file=sys.stderr)
            return 2
        if try_compile_shift_and(args.pattern, ignore_case=args.ignore_case) is None:
            print("error: --max-errors needs a literal/class-sequence pattern "
                  "of <= 32 symbols", file=sys.stderr)
            return 2
        if args.only_matching:
            print("error: -o is not supported with --max-errors (approximate "
                  "matches have no unique matched substring)", file=sys.stderr)
            return 2

    stdin_label: str | None = None  # resolved spool path shown as GNU's label
    stdin_spool: str | None = None  # raw spool path as placed in args.files
    stdin_only = (
        (not args.files and not args.recursive) or args.files == ["-"]
    )
    if stdin_only and not (
        args.only_matching or args.byte_offset or args.context is not None
        or args.before_context or args.after_context
    ):
        # Round 5: stdin as the ONLY input streams through the engine
        # in-process with GNU semantics — presence queries (-q/-l/-L)
        # stop at the first settled match WITHOUT draining the pipe
        # (`tail -f log | dgrep -q pat -` terminates like GNU), counts
        # and default print run chunk-by-chunk to EOF with bounded
        # memory and matches print as they arrive.  Modes that re-read
        # the input (-o, context, -b) keep the spool below.
        return _grep_stdin_stream(args, patterns)
    if (not args.files and not args.recursive) or "-" in args.files:
        # GNU grep: the FILE "-" mixed with real files means standard
        # input.  The runtime schedules map tasks over real files, so
        # stdin is spooled once to a temp file, searched like any split,
        # and displayed as "(standard input)".  Repeated "-" collapses to
        # the one spool (GNU's second read of stdin sees EOF anyway).
        # Batch semantics here, deliberately: mixed-input jobs go through
        # the scheduler, which needs finite splits ((-o/-b/context
        # stdin-only jobs spool too — they re-read their input).
        import atexit
        import shutil as _shutil
        import tempfile as _tempfile

        fd, _spool = _tempfile.mkstemp(prefix="dgrep-stdin-")
        with _os.fdopen(fd, "wb") as _out:
            _shutil.copyfileobj(sys.stdin.buffer, _out, 1 << 20)
        atexit.register(lambda p=_spool: _os.path.exists(p) and _os.unlink(p))
        stdin_spool = _spool
        stdin_label = str(Path(_spool).resolve())
        if args.files:
            repl, seen = [], False
            for f in args.files:
                if f == "-":
                    if not seen:
                        repl.append(_spool)
                    seen = True
                else:
                    repl.append(f)
            args.files = repl
        else:
            args.files = [_spool]
    if args.recursive and not args.files:
        args.files = ["."]  # GNU grep -r with no FILE searches the cwd
    # args.files can no longer be empty here: a no-FILE invocation either
    # spooled stdin (non-recursive) or defaulted to the cwd (-r)

    def _readable(f: str) -> bool:
        p = Path(f)
        return p.exists() and (p.is_dir() or _os.access(f, _os.R_OK))

    good, bad = [], []
    for f in args.files:
        (good if _readable(f) else bad).append(f)
    had_file_errors = bool(bad)
    if bad:
        if not args.no_messages:
            print(f"error: cannot read: {', '.join(bad)}", file=sys.stderr)
        args.files = good
        if not args.files:
            return 2  # nothing searchable, like grep
    import fnmatch

    filters = getattr(args, "glob_filters", None) or []

    def _included(name: str) -> bool:
        # GNU applies --include/--exclude to explicitly listed files too
        # (with or without -r), and treats them as ONE ordered list: the
        # LAST glob matching the basename decides; a file matching no glob
        # defaults to included iff the list starts with an exclude (or is
        # empty) — probed against grep 3.8 (tests/test_fuzz_cli.py)
        decision = None
        for kind, g in filters:
            if fnmatch.fnmatch(name, g):
                decision = kind
        if decision is None:
            return not filters or filters[0][0] == "exclude"
        return decision == "include"

    excl_dirs = getattr(args, "exclude_dir", None) or []

    def _dir_excluded(name: str) -> bool:
        # GNU --exclude-dir matches directory BASENAMES — both descended
        # directories and explicitly named command-line ones (probed
        # against grep 3.8: `grep -r --exclude-dir=build pat build/`
        # searches nothing and exits 1).  Globs containing '/' therefore
        # never match (a basename has no '/') — probed against grep 3.8
        # too: `--exclude-dir=build/sub`, `./build`, and `*/sub` all
        # exclude nothing there as well, so basename-only IS the
        # GNU-compatible behavior (round-4 ADVICE follow-up, pinned by
        # test_fuzz_cli.py::test_exclude_dir_slash_glob_matches_gnu).
        return any(fnmatch.fnmatch(name, g) for g in excl_dirs)

    deref_recursive = getattr(args, "dereference_recursive", False)
    if args.recursive or deref_recursive:
        expanded: list[str] = []
        walk_bad: list[str] = []
        for f in args.files:
            pf = Path(f)
            if pf.is_dir():
                if excl_dirs and _dir_excluded(pf.name):
                    continue  # GNU skips matching command-line dirs too
                # os.walk with in-place dirnames pruning: an excluded
                # subtree (node_modules, .git) is never descended at all,
                # unlike a post-hoc rglob filter that stats every file
                # under it.  Files collect per root then sort, preserving
                # the global lexicographic order the rglob walk produced.
                # -R (GNU --dereference-recursive) follows symlinked
                # dirs/files met during the descent, with a global
                # (dev, ino) visited set: each real directory is
                # searched ONCE, which both breaks symlink cycles and
                # collapses multi-route duplicates.  (GNU searches a dir
                # reachable via two sibling symlinks once per route —
                # unrepresentable here, since this CLI displays resolved
                # absolute paths, so per-route duplicates would print as
                # identical lines; the matched (file, line) SET is equal
                # either way.)  Plain -r follows symlinks only when they
                # ARE the command-line argument — os.walk with
                # followlinks=False already never descends symlinked
                # dirs, and symlinked files are skipped below
                # (GNU-verified semantics).
                collected: list[Path] = []
                seen_dirs: set[tuple[int, int]] = set()
                seen_files: set[str] = set()
                if deref_recursive:
                    try:
                        st = _os.stat(pf)
                        seen_dirs.add((st.st_dev, st.st_ino))
                    except OSError:
                        pass
                for root, dirnames, filenames in _os.walk(
                    pf, followlinks=deref_recursive
                ):
                    if excl_dirs:
                        dirnames[:] = [d for d in dirnames
                                       if not _dir_excluded(d)]
                    if deref_recursive:
                        keep = []
                        for d in dirnames:
                            try:
                                st = _os.stat(_os.path.join(root, d))
                            except OSError:
                                continue  # vanished mid-walk
                            key = (st.st_dev, st.st_ino)
                            if key in seen_dirs:
                                continue  # cycle / already visited
                            seen_dirs.add(key)
                            keep.append(d)
                        dirnames[:] = keep
                    collected.extend(
                        Path(root) / name for name in filenames
                    )
                for sub in sorted(collected):
                    if deref_recursive and sub.is_symlink() and not sub.exists():
                        # GNU -R reports dangling symlinks met during
                        # the descent ("No such file...") and exits 2
                        walk_bad.append(str(sub))
                        continue
                    if not sub.is_file() or not _included(sub.name):
                        continue  # is_file(): skip dangling symlinks etc.
                    if not deref_recursive and sub.is_symlink():
                        continue  # plain -r: skip symlinked files (GNU)
                    if deref_recursive:
                        # -R file dedup: a file reachable both directly
                        # and via a file symlink is scanned/printed ONCE.
                        # Keyed on the RESOLVED path — exactly what this
                        # CLI displays — so per-route duplicates (which
                        # would print as identical lines; GNU prints each
                        # route under its own traversal path) collapse,
                        # while HARD links keep printing separately like
                        # GNU (distinct resolved paths, distinct files).
                        try:
                            key = str(sub.resolve())
                        except OSError:
                            pass  # vanished mid-walk; access check below
                        else:
                            if key in seen_files:
                                continue
                            seen_files.add(key)
                    sp = str(sub)
                    if not _os.access(sp, _os.R_OK):
                        # unreadable files found in the tree get the same
                        # -s / exit-2 semantics as explicit arguments
                        # instead of failing a map task (GNU grep -r)
                        walk_bad.append(sp)
                        continue
                    expanded.append(sp)
            elif f == stdin_spool or _included(pf.name):
                # the spool's temp basename must not be glob-filtered:
                # stdin is not a file name (GNU applies no filters to it)
                expanded.append(f)
        if walk_bad:
            had_file_errors = True
            if not args.no_messages:
                print(f"error: cannot read: {', '.join(walk_bad)}",
                      file=sys.stderr)
        if not expanded:
            # GNU grep -r exits 1 silently when nothing is searchable
            # (empty tree, or everything --include-filtered) — probed
            return 2 if had_file_errors else 1
        args.files = expanded
    else:
        dirs = [f for f in args.files if Path(f).is_dir()]
        if dirs:
            if not args.no_messages:
                print(f"error: {', '.join(dirs)}: is a directory (use -r)",
                      file=sys.stderr)
            return 2
        args.files = [f for f in args.files
                      if f == stdin_spool or _included(Path(f).name)]
        # stdin is not a file name: --include/--exclude never apply (GNU)
        if not args.files:
            return 2 if had_file_errors else 1  # everything --include-filtered

    if getattr(args, "follow", False):
        # the expanded, readability-filtered file set is final: hand it
        # to the standing-query loop (tail -f semantics, grep output)
        return _grep_follow(args, patterns, had_file_errors)

    # Count queries (-c/-l/-L/-q) with no mode that needs per-line output
    # downstream: the app emits ONE count record per file instead of one
    # record per matched line, so a match-dense count job skips the whole
    # per-line record pipeline (549k-match 64 MB `-c` measured 17.5 s with
    # per-line records; the scan itself is ~0.3 s).  Context/-b/-o need
    # line sets, and -o's record VALUES, so they keep per-line records.
    count_only = (
        (args.count or args.quiet or args.files_with_matches
         or args.files_without_match)
        and args.context is None
        and not args.before_context and not args.after_context
        and not args.byte_offset and not args.only_matching
    )
    # The CLI always runs the engine app: on --backend tpu/auto the device
    # scan, on cpu the native C scanners (DFA/AC/memmem) — ~20x the
    # reference-mirror per-line re loop that apps/grep.py keeps for parity
    # demonstrations (profiled: 3.2M re.search calls = 1.2 s per 256 MB).
    cfg = JobConfig(
        input_files=[str(Path(f).resolve()) for f in args.files],
        application="distributed_grep_tpu.apps.grep_tpu",
        app_options={
            "ignore_case": args.ignore_case,
            "invert": args.invert,
            **({"word_regexp": True} if args.word_regexp else {}),
            **({"line_regexp": True} if args.line_regexp else {}),
            **({"max_errors": args.max_errors} if args.max_errors else {}),
            **({"count_only": True} if count_only else {}),
            # -q/-l/-L consume only per-file truthiness: the scan may stop
            # at the first match (GNU grep does); -c needs the full count
            **({"presence_only": True}
               if count_only and not args.count else {}),
            # Backend resolution: no flag defaults to the cpu engine path
            # (native scanners, no jax import) EXCEPT for --max-errors,
            # whose fast core is the XLA approx kernel (on the CPU jax
            # backend without a TPU — orders of magnitude faster than the
            # host oracle loop).  An EXPLICIT --backend cpu always wins,
            # max-errors included.
            **(
                {"backend": "cpu"}
                if args.backend == "cpu"
                or (args.backend is None and not args.max_errors)
                else {}
            ),
            **({"patterns": patterns} if patterns else {"pattern": args.pattern}),
        },
        n_reduce=args.n_reduce or 10,
    )
    if len(cfg.input_files) > 1:
        # Cross-file batching (round 6): a grep -r over a source tree is
        # the many-small-files regime — group sub-threshold files into
        # multi-file map splits (runtime/job.plan_map_splits) so one map
        # task, and one packed device dispatch per window
        # (GrepEngine.scan_batch), covers many files instead of each
        # paying its own task + scan.  Exact per-file results either way;
        # DGREP_BATCH_BYTES overrides (0 disables).  Pays on the cpu
        # engine too (one native pass + one task commit per window), so
        # it is not gated on the backend.
        from distributed_grep_tpu.ops.layout import DEFAULT_BATCH_BYTES

        cfg.batch_bytes = DEFAULT_BATCH_BYTES
    if cfg.app_options.get("backend") != "cpu":
        # device backend (explicit tpu, auto, or --max-errors): mid-task
        # heartbeats (worker progress callbacks + the app's declared
        # compile-grace window, VERDICT r3 item 3) keep legitimate work
        # alive, so the detector window only needs headroom over the
        # heartbeat cadence — 30 s instead of the old 120 s band-aid that
        # made genuine worker death 12x slower to detect
        cfg.task_timeout_s = max(cfg.task_timeout_s, 30.0)
    if args.work_dir:
        cfg.work_dir = args.work_dir
    else:
        import tempfile

        cfg.work_dir = tempfile.mkdtemp(prefix="dgrep-")
        # Ephemeral workdir: nobody can resume a randomly-named temp dir,
        # so the per-task fsync'd journal is pure overhead here (a
        # 2,000-file grep -r paid 2,000 fsyncs for nothing — round 5),
        # and so is the blob store's fsync-before-rename (round 8: ~0.3 s
        # per dense 64 MB job; the atomic rename commit stays, only crash
        # durability is waived — a power cut costs a re-run).  --work-dir
        # jobs keep both: their path is re-addressable.
        cfg.journal = False
        cfg.durable = False
    ctx_before = args.context if args.context is not None else args.before_context
    ctx_after = args.context if args.context is not None else args.after_context

    from distributed_grep_tpu.runtime.job import GREP_KEY_RE

    res = run_job(cfg, n_workers=args.workers)
    # Parse matched (file, line number) pairs from the result KEYS (the
    # shared end-anchored grep-key shape — a value may itself contain
    # " (line number #"), not the joined lines.  Only the modes that
    # re-read the input files (-o, context, -b) need full per-file line
    # SETS; the default/-c/-l/-L/-q modes stream the job output with
    # per-file counters so a match-dense job keeps flat RSS (the reduce
    # side already spills to disk; collation must not un-do that).
    need_sets = bool(
        ctx_before or ctx_after or args.byte_offset
        or (args.only_matching and args.max_count is not None)
    )
    matched: dict[str, set[int]] | None = None
    counts: dict[str, int] = {f: 0 for f in cfg.input_files}
    # Default print mode needs no pre-count pass: selection counts only
    # decide the exit code there, and the print loop observes every
    # record anyway — a match-dense job should not pay a full extra
    # iter_results + key-parse pass (round-5 columnar work).
    default_print = not (
        args.quiet or args.files_without_match or args.files_with_matches
        or args.count or args.only_matching or ctx_before or ctx_after
    )
    stream_counts = default_print and not need_sets and not count_only
    if need_sets:
        matched = {f: set() for f in cfg.input_files}
        # bytes-parsed pre-pass (round 5): no regex / value decode per
        # record — the -o/-b/context set building over match-dense output
        for path, ln in res.iter_grep_keys():
            s = matched.get(path)
            if s is not None:
                s.add(ln)
        if args.max_count is not None:
            # grep -m: keep only the first NUM selected lines per file
            matched = {f: set(sorted(ln)[: args.max_count])
                       for f, ln in matched.items()}
        counts = {f: len(matched[f]) for f in cfg.input_files}
    elif not stream_counts:
        for key, v in res.iter_results():
            if count_only:
                # count records: key = filename, value = selected count
                f, add = key, int(v)
            else:
                m = GREP_KEY_RE.match(key)
                if not m:
                    continue
                f, add = m.group(1), 1
            if f in counts:
                counts[f] += add
                if args.quiet and counts[f]:
                    break  # -q: one selected line settles the answer
        if args.max_count is not None:
            counts = {f: min(c, args.max_count) for f, c in counts.items()}
    def disp(path: str) -> str:
        # GNU grep shows stdin under this label wherever a name prints
        return "(standard input)" if path == stdin_label else path

    any_selected = any(counts[f] for f in cfg.input_files)
    # grep exit conventions: -q reports selection (0) even after file
    # errors; otherwise an error forces 2
    rc_final = 0 if any_selected else 1
    if had_file_errors:
        rc_final = 2

    if args.quiet:
        return 0 if any_selected else rc_final
    if args.files_without_match:
        # grep -L: names of files with no selected lines, argv order.
        # Exit code follows MATCH presence (0 iff any line selected
        # anywhere), not listing presence — differentially verified
        # against GNU grep 3.8 (tests/test_fuzz_cli.py)
        listed = [f for f in cfg.input_files if not counts[f]]
        for f in listed:
            print(disp(f))
        exit_early = 2 if had_file_errors else (0 if any_selected else 1)
        if args.metrics:
            print(json.dumps(res.metrics, indent=2, sort_keys=True),
                  file=sys.stderr)
        return exit_early
    if args.files_with_matches:
        # grep -l: names only, argv order, each file once
        for f in cfg.input_files:
            if counts[f]:
                print(disp(f))
    elif args.count:
        # grep -c: one "<file>:<count>" line per input, in argv order
        for f in cfg.input_files:
            # -H forces the prefix even for a single input (GNU)
            prefix = (f"{disp(f)}:"
                      if (len(cfg.input_files) > 1 or args.with_filename)
                      and not args.no_filename else "")
            print(f"{prefix}{counts[f]}")
    elif args.only_matching:
        # grep -o: each matched substring on its own line.  -v has no
        # matched substrings (grep prints nothing for -v -o).
        if not args.invert:
            offsets = _line_offsets(matched) if args.byte_offset else None
            _print_only_matching(res, args, patterns, matched, offsets,
                                 disp=disp)
    elif ctx_before or ctx_after:
        # the '--' group separator is global across input files, like grep
        printed_any = False
        for f in cfg.input_files:
            printed_any = _print_with_context(
                f, matched[f], ctx_before, ctx_after, printed_any,
                no_filename=args.no_filename,
                byte_offset=args.byte_offset,
                display=disp(f),
            )
    else:
        # default print: stream in (file, line) order with bounded memory
        # (identity-reduce jobs arrive pre-sorted and merge; others
        # external re-sort — runtime/job.iter_results_sorted); -m caps
        # per file as lines stream past
        offsets = _line_offsets(matched) if args.byte_offset else None
        emitted: dict[str, int] = {f: 0 for f in cfg.input_files}
        # per-record key parsing only when some option consumes the parts
        # (match-dense default output otherwise prints the record as-is)
        needs_parse = (
            args.max_count is not None or args.no_filename
            or offsets is not None or stdin_label is not None
        )
        saw_any = False
        if not needs_parse and res.fileline_sorted:
            # match-dense fast path: display lines stream as BYTES from
            # the pre-sorted output files (no per-record str round trip)
            sys.stdout.flush()
            out_buf = sys.stdout.buffer
            for block in res.display_blocks_sorted():
                if block:
                    out_buf.write(block)
                    saw_any = True
            out_buf.flush()
            if stream_counts:
                rc_final = 2 if had_file_errors else (0 if saw_any else 1)
            if args.metrics:
                print(json.dumps(res.metrics, indent=2, sort_keys=True),
                      file=sys.stderr)
            return rc_final
        for key, value in res.iter_results_sorted():
            if not needs_parse:
                saw_any = True
                print(f"{key} {value}")
                continue
            m = GREP_KEY_RE.match(key)
            if args.max_count is not None and m and m.group(1) in emitted:
                if emitted[m.group(1)] >= args.max_count:
                    continue  # dropped by the -m cap — and not counted
                    # toward the exit code (GNU -m 0 exits 1)
                emitted[m.group(1)] += 1
            saw_any = True
            if m and (args.no_filename or offsets is not None
                      or stdin_label is not None):
                path, ln = m.group(1), int(m.group(2))
                head = "" if args.no_filename else f"{disp(path)} "
                boff = (f"(byte #{offsets[path].get(ln, '?')}) "
                        if offsets is not None else "")
                print(f"{head}(line number #{ln}) {boff}{value}")
            else:
                print(f"{key} {value}")
        if stream_counts:
            # the pre-count pass was skipped: the streamed records decide
            # the exit code (selection presence), file errors still win
            rc_final = 2 if had_file_errors else (0 if saw_any else 1)
    if args.metrics:
        print(json.dumps(res.metrics, indent=2, sort_keys=True), file=sys.stderr)
    return rc_final


def _follow_record_line(rec: dict, *, no_filename: bool = False) -> str | None:
    """THE display formatting for a follow/stream text record — the one
    place the local follow loop and the stream client share, so the
    dialect cannot drift between them (or from the one-shot print path):
    surrogateescape round-trip from the scanner, then the replace-decode
    the one-shot leg uses.  None for records with no text line (count
    deltas, presence marks, resets — caller-specific rendering)."""
    if "text" not in rec:
        return None
    text = rec["text"].encode("utf-8", "surrogateescape").decode(
        "utf-8", "replace"
    )
    head = "" if no_filename else f"{rec['file']} "
    return f"{head}(line number #{rec['line']}) {text}"


def _print_follow_reset(rec: dict) -> None:
    """Truncation/replacement notice — stderr, like tail's 'file
    truncated': the stream's line numbers restart for a new file
    generation and the consumer must not splice them onto the old one."""
    print(f"dgrep: {rec['file']}: file truncated or replaced; "
          f"following new data", file=sys.stderr)


def _grep_follow(args: argparse.Namespace, patterns, had_file_errors) -> int:
    """One-shot CLI standing query (``dgrep grep --follow``): build the
    engine once, poll the inputs at the DGREP_FOLLOW_POLL_S cadence, and
    print matches as they arrive in the default print format.  Count-only
    modes (-c/-l/-q) never materialize lines.  ``--follow-idle-s S``
    exits once no input has grown for S seconds (the testable/benchmark
    shape); 0 runs until interrupted.  On exit the unterminated tail
    line (if any) is scanned too, so the printed set is byte-identical
    to a one-shot run over the final file state."""
    import time as _time
    from pathlib import Path

    from distributed_grep_tpu.ops.engine import cached_engine
    from distributed_grep_tpu.runtime.follow import (
        FollowScanner,
        env_follow_poll_s,
    )

    # resolve to absolute like the one-shot print path does — the
    # displayed filename prefix must match a one-shot run's byte for
    # byte (pinned by the relative-path parity test)
    files = [str(Path(f).resolve()) for f in args.files]
    backend = (
        "cpu" if (args.backend == "cpu" or args.backend is None) else "device"
    )
    eng, _verdict = cached_engine(
        args.pattern if patterns is None else None,
        patterns=patterns,
        ignore_case=args.ignore_case,
        backend=backend,
    )
    count_only = bool(args.count or args.quiet or args.files_with_matches)
    scanner = FollowScanner(
        eng, files, invert=args.invert, count_only=count_only,
        presence_only=count_only and not args.count,
    )
    poll_s = env_follow_poll_s()
    idle_s = max(0.0, float(getattr(args, "follow_idle_s", 0.0) or 0.0))

    def print_records(groups) -> None:
        for _path, records, _cur in groups:
            for rec in records:
                if rec.get("reset"):
                    _print_follow_reset(rec)
                    continue
                line = _follow_record_line(
                    rec, no_filename=args.no_filename
                )
                if line is not None:
                    print(line, flush=True)
                elif rec.get("match") and args.files_with_matches:
                    print(rec["file"], flush=True)

    last_news = _time.monotonic()
    try:
        while True:
            groups = scanner.poll_once()
            print_records(groups)
            if groups:
                last_news = _time.monotonic()
            if args.quiet and scanner.any_selected():
                return 0
            if idle_s and _time.monotonic() - last_news >= idle_s:
                break
            _time.sleep(poll_s)
    except KeyboardInterrupt:
        pass
    # finalize: the oracle (a one-shot scan of the final state) includes
    # a last line with no trailing newline — scan it before reporting.
    # LOOP until nothing drains: one final poll consumes at most one
    # per-wake read window per file, and a writer that raced ahead of the
    # last regular wake may have left more than a window behind.
    while True:
        groups = scanner.poll_once(final=True)
        if not groups:
            break
        print_records(groups)
    if args.count:
        for f in files:
            prefix = (f"{f}:"
                      if (len(files) > 1 or args.with_filename)
                      and not args.no_filename else "")
            print(f"{prefix}{scanner.cursors[f].emitted}")
    any_selected = scanner.any_selected()
    if args.quiet:
        return 0 if any_selected else (2 if had_file_errors else 1)
    return 2 if had_file_errors else (0 if any_selected else 1)


def _line_offsets(matched: dict[str, set[int]]) -> dict[str, dict[int, int]]:
    """Per file, the starting byte offset of each matched line (grep -b).

    Streams each file in bounded blocks (a -b -v over a huge file must not
    slurp it — the scan side keeps memory bounded, so this side does too):
    per block, the native newline index gives the block's line-start
    offsets; wanted line numbers resolve against the running line count."""
    from distributed_grep_tpu.ops.lines import newline_index

    out: dict[str, dict[int, int]] = {}
    for path, lines in matched.items():
        out[path] = {}
        if not lines:
            continue
        want = sorted(lines)
        wi = 0
        line_no = 1  # number of the line starting at `base + next offset`
        base = 0
        with open(path, "rb") as f:
            if want[0] == 1:
                out[path][1] = 0
                wi = 1
            while wi < len(want):
                block = f.read(1 << 24)
                if not block:
                    break
                nl = newline_index(block)
                # the line AFTER the k-th newline of this block is number
                # line_no + k + 1 and starts at base + nl[k] + 1
                while wi < len(want):
                    k = want[wi] - line_no - 1
                    if k < 0 or k >= len(nl):
                        break
                    out[path][want[wi]] = base + int(nl[k]) + 1
                    wi += 1
                line_no += len(nl)
                base += len(block)
    return out


def _read_line_bytes(f, offset: int) -> bytes:
    """The raw bytes of the line starting at ``offset`` (to the next
    newline), read incrementally from an OPEN handle — grep -o -b needs
    byte-exact match positions, which the replace-decoded display strings
    cannot give.  Callers keep one handle per path (match-dense files
    would otherwise pay an open() per matched line)."""
    chunks = []
    f.seek(offset)
    while True:
        block = f.read(1 << 16)
        if not block:
            break
        cut = block.find(b"\n")
        if cut >= 0:
            chunks.append(block[:cut])
            break
        chunks.append(block)
    return b"".join(chunks)


def _print_only_matching(res, args, patterns, matched, offsets=None,
                         disp=lambda p: p) -> None:
    import re

    from distributed_grep_tpu.runtime.job import GREP_KEY_RE

    from distributed_grep_tpu.apps.grep import wrap_mode

    mode = ("line" if args.line_regexp
            else ("word" if args.word_regexp else "search"))
    flags = re.IGNORECASE if args.ignore_case else 0
    if patterns is not None:
        # literal set: leftmost-longest among the alternatives, like grep -F
        base = "|".join(re.escape(p) for p in
                        sorted(patterns, key=len, reverse=True))
    else:
        base = args.pattern
    # -w/-x constrain which substrings count as matches, not just which
    # lines are selected — wrap before finditer.  With -b (offsets) the
    # match runs over the RAW LINE BYTES (exact offsets on any encoding);
    # otherwise over the display string.
    # ONE matcher for every -o path: the BYTES regex (GNU's C-locale
    # byte-wise semantics, incl. ASCII-only -i folding — the str-typed
    # fallback previously Unicode-folded, so `-o -i` could select
    # different substrings than `-o -i -m N` — round-5 review).
    from distributed_grep_tpu.models.dfa import expand_posix_classes

    # POSIX classes expand before re sees them (re misparses [[:digit:]])
    wrapped = wrap_mode(
        expand_posix_classes(base.encode("utf-8", "surrogateescape")), mode)
    rx_b = re.compile(wrapped, flags)

    if offsets is None and matched is None and res.fileline_sorted:
        # plain -o over a grep-shaped job (round 5): merge the pre-sorted
        # outputs as BYTES and finditer the raw line bytes — no str round
        # trip per record
        last_p: str | None = None
        prefix_path = ""
        for (p, ln), value_b in res.iter_grep_records_bytes():
            if ln:
                if p != last_p:
                    last_p = p
                    prefix_path = (
                        "" if args.no_filename else f"{disp(p)} "
                    )
                prefix = f"{prefix_path}(line number #{ln}) "
            else:
                prefix = ""  # non-grep-shaped key: match the value alone
            for hit in rx_b.finditer(value_b):
                if hit.group(0):
                    print(f"{prefix}{hit.group(0).decode('utf-8', 'replace')}")
        return

    handles: dict[str, object] = {}  # -b: one open handle per path
    try:
        for key, value in res.iter_results_sorted():
            m = GREP_KEY_RE.match(key)
            if m and matched is not None and \
                    int(m.group(2)) not in matched.get(m.group(1), ()):
                continue  # line dropped by the -m cap
            prefix = ""
            line_off = None
            if m:
                if not args.no_filename:
                    prefix = f"{disp(m.group(1))} "
                prefix += f"(line number #{m.group(2)}) "
                if offsets is not None:
                    line_off = offsets.get(m.group(1), {}).get(int(m.group(2)))
            if line_off is not None:
                # GNU -o -b: offset of the MATCH, byte-exact — match on the
                # raw line bytes, not the replace-decoded display string
                path = m.group(1)
                f = handles.get(path)
                if f is None:
                    f = handles[path] = open(path, "rb")
                raw = _read_line_bytes(f, line_off)
                for hit in rx_b.finditer(raw):
                    if hit.group(0):
                        print(f"{prefix}(byte #{line_off + hit.start()}) "
                              f"{hit.group(0).decode('utf-8', 'replace')}")
                continue
            for hit in rx_b.finditer(
                value.encode("utf-8", "surrogateescape")
            ):
                if hit.group(0):
                    print(f"{prefix}"
                          f"{hit.group(0).decode('utf-8', 'replace')}")
    finally:
        for f in handles.values():
            f.close()


def _print_with_context(path: str, lines_set: set[int], before: int,
                        after: int, printed_any: bool,
                        no_filename: bool = False,
                        byte_offset: bool = False,
                        display: str | None = None) -> bool:
    """grep -A/-B/-C over one file, streaming (memory bounded by the
    context width).  Matched lines print in the usual key format; context
    lines use ')-' instead of ') ' and non-contiguous groups are separated
    by '--', mirroring grep's match/context markers.  With ``byte_offset``
    (-b) each line also carries its line-start offset — '(byte #K) ' on
    matches, '(byte #K)- ' on context, mirroring GNU's ':' vs '-'
    separators.  ``printed_any`` carries across files so the separator is
    global like grep's; returns the updated flag."""
    import collections

    prevq: collections.deque = collections.deque(maxlen=max(before, 0))
    pending_after = 0
    last_printed = 0
    head = "" if no_filename else f"{display if display is not None else path} "

    def fmt(n: int, off: int, ctx: bool) -> str:
        sep = "-" if ctx else ""
        b = f" (byte #{off}){sep}" if byte_offset else ""
        return f"{head}(line number #{n}){sep}{b} "

    # errors="replace" matches the default output mode exactly: map
    # values are replace-decoded at emit time (apps/grep.py), so the
    # same matched line must print identically under -C.  (Lone
    # surrogates would also crash a strict-encoding stdout.)  Decode
    # LAZILY — only lines actually printed pay it (round 5: the loop
    # used to decode every line of the file).
    def dec(raw: bytes) -> str:
        return raw.rstrip(b"\n").decode("utf-8", "replace")

    pos = 0
    with open(path, "rb") as f:
        for n, raw in enumerate(f, 1):
            off = pos
            pos += len(raw)
            if n in lines_set:
                if printed_any and (
                    last_printed == 0 or n - last_printed > len(prevq) + 1
                ):
                    print("--")
                for qn, qoff, qraw in prevq:
                    if qn > last_printed:
                        print(f"{fmt(qn, qoff, ctx=True)}{dec(qraw)}")
                prevq.clear()
                print(f"{fmt(n, off, ctx=False)}{dec(raw)}")
                printed_any = True
                last_printed = n
                pending_after = after
            elif pending_after > 0:
                print(f"{fmt(n, off, ctx=True)}{dec(raw)}")
                last_printed = n
                pending_after -= 1
            elif before:
                prevq.append((n, off, raw))
    return printed_any


def cmd_run(args: argparse.Namespace) -> int:
    from distributed_grep_tpu.runtime.job import run_job

    overrides = {}
    if args.n_reduce:
        overrides["n_reduce"] = args.n_reduce
    if args.work_dir:
        overrides["work_dir"] = args.work_dir
    cfg = JobConfig.load(args.config, **overrides)
    res = run_job(cfg, n_workers=args.workers, resume=args.resume)
    for line in res.sorted_lines():
        print(line)
    if args.metrics:
        print(json.dumps(res.metrics, indent=2, sort_keys=True), file=sys.stderr)
    return 0


def cmd_coordinator(args: argparse.Namespace) -> int:
    from distributed_grep_tpu.runtime.http_coordinator import serve_coordinator

    cfg = JobConfig.load(args.config)
    status = serve_coordinator(cfg, resume=args.resume)
    # stdout contract: exactly one JSON line naming the committed outputs
    # (scripts and the multi-process tests parse it)
    print(json.dumps({"outputs": status["outputs"]}))
    return 0


def cmd_worker(args: argparse.Namespace) -> int:
    from distributed_grep_tpu.runtime.http_transport import run_http_worker

    run_http_worker(addr=args.addr, n_parallel=args.slots)
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """Grep-as-a-service daemon (runtime/service.py): a long-lived
    multi-tenant coordinator serving a stream of jobs over persistent
    workers and engines.  Blocks until SIGINT/SIGTERM; remote workers
    attach with `worker --addr`, clients submit with `submit --addr`.

    HA mode (round 18, runtime/lease.py) switches on via ``--standby``
    or a set DGREP_LEASE_TTL_S: the daemon contends for the work-root
    lease — winner serves (with every durable flush fenced on lease
    ownership), loser parks as a standby that polls the lease and
    promotes through the normal resume path the moment it goes stale.
    Without either switch this is the exact pre-lease single-daemon
    path: no lease file, no /status "role" key."""
    import signal
    import tempfile
    import threading

    from distributed_grep_tpu.runtime.daemon_log import DaemonLog, env_daemon_log
    from distributed_grep_tpu.runtime.lease import lease_configured
    from distributed_grep_tpu.runtime.service import GrepService, ServiceServer

    work_root = args.work_root or tempfile.mkdtemp(prefix="dgrep-svc-")
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # non-main thread (tests drive the service directly)
    if getattr(args, "standby", False) or lease_configured():
        return _serve_ha(args, work_root, stop)
    # fleet timeline (round 19): daemon.jsonl in the work root; off is a
    # true no-op — no file, no staged list, service hooks never installed
    daemon_log = DaemonLog(work_root) if env_daemon_log() else None
    service = GrepService(
        work_root=work_root,
        max_jobs=args.max_jobs,
        queue_depth=args.queue,
        spans=args.spans,
        resume=False if args.no_resume else None,
        daemon_log=daemon_log,
    )
    server = ServiceServer(service, host=args.host, port=args.port)
    server.start()
    scaler = _start_worker_pool(args, service, stop)
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    stop.set()
    if scaler is not None:
        scaler.join(timeout=5.0)
    server.shutdown()
    service.stop()
    # stdout contract (mirrors cmd_coordinator): exactly one JSON line —
    # the final service status snapshot
    print(json.dumps(service.status()))
    return 0


def _start_worker_pool(args: argparse.Namespace, service, stop):
    """Local worker loops + (optionally) the elastic scaler thread.
    Returns the scaler thread (joined at teardown) or None."""
    import threading

    if args.workers:
        service.start_local_workers(args.workers)
    if not (args.max_workers and args.max_workers > args.workers):
        return None

    # Elastic local pool (round 16): follow the service's own scale
    # advice (queue depth / pending tasks / in-flight age) between
    # the base --workers floor and the --max-workers ceiling.
    # Attach/detach is safe by construction — service-allocated ids,
    # fresh-id reconnect, quarantine; shrink drains loops at their
    # next idle poll, never mid-task.
    def scale_loop() -> None:
        while not stop.wait(2.0):
            advice = service.scale_advice()["advice"]
            cur = service.local_pool_size()
            if advice == "grow" and cur < args.max_workers:
                service.scale_local_pool(cur + 1)
            elif advice == "shrink" and cur > args.workers:
                service.scale_local_pool(max(args.workers, cur - 1))

    scaler = threading.Thread(target=scale_loop, name="svc-scaler",
                              daemon=True)
    scaler.start()
    return scaler


def _serve_ha(args: argparse.Namespace, work_root: str, stop) -> int:
    """The active/standby loop behind ``dgrep serve --standby`` (or a
    set DGREP_LEASE_TTL_S): contend for the work-root lease; serve while
    holding it (renewal heartbeat + write fence), park as a StandbyServer
    while not.  A deposed active — its lease stolen after a stall —
    demotes back to standby instead of exiting, and a standby promotes
    via the normal registry-resume path, so failover is just "the other
    daemon restarts the service from the shared work root"."""
    import time as _time
    from pathlib import Path

    from distributed_grep_tpu.runtime.daemon_log import DaemonLog, env_daemon_log
    from distributed_grep_tpu.runtime.lease import (
        WorkRootLease,
        env_lease_renew_s,
    )
    from distributed_grep_tpu.runtime.service import (
        GrepService,
        ServiceServer,
        StandbyServer,
    )
    from distributed_grep_tpu.utils import metrics as metrics_mod

    port = args.port
    standby = None
    last_status: dict = {}
    try:
        while not stop.is_set():
            if port == 0 and standby is None:
                # pin the ephemeral port BEFORE the lease advertises it:
                # workers and clients dial one stable address per daemon
                # across its standby/active transitions
                standby = StandbyServer(work_root, host=args.host,
                                        port=0).start()
                port = standby.port
            lease = WorkRootLease(Path(work_root),
                                  addr=f"{args.host}:{port}")
            poll_s = env_lease_renew_s()
            park_t0 = None
            # detection→serving clock for the failover SLO: reset before
            # every acquire attempt, so after the SUCCESSFUL one it marks
            # the poll that noticed the stale lease
            detect_t = _time.monotonic()
            while not lease.acquire():
                if standby is None:
                    standby = StandbyServer(work_root, host=args.host,
                                            port=port).start()
                    last_status = standby.status()
                if park_t0 is None:
                    park_t0 = _time.monotonic()
                if stop.wait(poll_s):
                    return _emit_final(last_status or
                                       {"service": True, "role": "standby"})
                detect_t = _time.monotonic()
            if standby is not None:
                # promotion: free the port for the real server (HTTPServer
                # sets allow_reuse_address, so the rebind is immediate)
                standby.shutdown()
                standby = None
            stolen = lease.epoch > 1
            # Fleet timeline: ONLY the lease holder opens daemon.jsonl
            # (TaskJournal's open truncates a torn tail — a standby
            # opening the active's live file would corrupt it), so the
            # log is built per incarnation, after acquire.
            daemon_log = None
            if env_daemon_log():
                daemon_log = DaemonLog(work_root, epoch=lease.epoch,
                                       role="active")
                if park_t0 is not None:
                    daemon_log.stage(
                        "standby_park",
                        parked_s=round(_time.monotonic() - park_t0, 3))
                daemon_log.append_now(
                    "lease_steal" if stolen else "lease_acquire",
                    addr=f"{args.host}:{port}",
                    **({"prev_epoch": lease.epoch - 1} if stolen else {}))
            service = GrepService(
                work_root=work_root,
                max_jobs=args.max_jobs,
                queue_depth=args.queue,
                spans=args.spans,
                # promotion IS resume: registry replay re-admits queued
                # jobs, resumes running ones, reloads follow cursors
                resume=False if args.no_resume else None,
                lease=lease,
                daemon_log=daemon_log,
            )
            server = ServiceServer(service, host=args.host, port=port)
            server.start()
            port = server.port
            lease.start_renewal(on_lost=service._on_lease_lost,
                                on_renew=service.lease_renewed)
            if daemon_log is not None and (stolen or park_t0 is not None):
                # serving point: registry replayed, server bound, renewal
                # running — the failover SLO sample and the trace-side
                # promotion span's right edge
                failover_s = _time.monotonic() - detect_t
                metrics_mod.histogram(
                    "dgrep_daemon_failover_seconds").observe(failover_s)
                daemon_log.append_now(
                    "promoted", addr=f"{args.host}:{port}",
                    failover_s=round(failover_s, 6),
                    running=len(service._running),
                    queued=len(service._queue))
            import threading as _threading

            pool_stop = _threading.Event()  # per incarnation: a deposed
            # service's scaler must not keep scaling it from the afterlife
            scaler = _start_worker_pool(args, service, pool_stop)
            try:
                while not stop.wait(0.5):
                    if service.deposed_event.is_set():
                        break
            except KeyboardInterrupt:
                stop.set()
            pool_stop.set()
            if scaler is not None:
                scaler.join(timeout=5.0)
            server.shutdown()
            lease.stop_renewal()
            # a deposed service's stop() stages cancellations whose
            # flushes the fence DROPS (by design — no deposed writes);
            # a stopping owner's stop() flushes then releases the lease
            service.stop()
            if daemon_log is not None:
                # deposed path: stop() left the log open (close is
                # lease-gated); discard drops the fenced stage and frees
                # the handle before the next contention cycle.  No-op
                # after a graceful close.
                daemon_log.discard()
            last_status = service.status()
            if stop.is_set():
                return _emit_final(last_status)
            # deposed: demote and contend again as a standby
    finally:
        if standby is not None:
            standby.shutdown()
    return _emit_final(last_status or {"service": True, "role": "standby"})


def _emit_final(status: dict) -> int:
    # stdout contract (mirrors cmd_serve's single-daemon path): exactly
    # one JSON line — the final status snapshot
    print(json.dumps(status))
    return 0


def cmd_submit(args: argparse.Namespace) -> int:
    """Client for a running service daemon: POST the job, optionally wait
    for completion, print exactly ONE JSON line (job_id/state/outputs)."""
    import time as _time
    import urllib.error

    from distributed_grep_tpu.runtime.http_transport import (
        client_call,
        split_addrs,
    )

    if args.config:
        cfg = JobConfig.load(args.config)
    elif args.pattern is not None or args.e_patterns or args.patterns_file:
        if args.fixed_strings and args.extended_regexp:
            print("error: -E and -F are conflicting matchers",
                  file=sys.stderr)
            return 2
        # pattern-set parity with the local CLI (ISSUE 11 satellite): -e
        # PAT -e PAT / -f patfile / -F newline-sets resolve through the
        # SAME front half cmd_grep uses, so service tenants can submit
        # the multi-pattern jobs the fusion layer serves
        rc, patterns = _resolve_pattern_args(args)
        if rc:
            return rc
        if not args.files:
            print("error: need FILE arguments to submit", file=sys.stderr)
            return 2
        from pathlib import Path as _Path

        cfg = JobConfig(
            input_files=[str(_Path(f).resolve()) for f in args.files],
            application="distributed_grep_tpu.apps.grep_tpu",
            app_options={
                "backend": args.backend,
                **({"ignore_case": True} if args.ignore_case else {}),
                **({"patterns": patterns} if patterns
                   else {"pattern": args.pattern}),
            },
            n_reduce=args.n_reduce or 10,
        )
    else:
        print("error: need --config, or PATTERN/-e/-f and FILE arguments",
              file=sys.stderr)
        return 2
    if getattr(args, "follow", False) and not cfg.follow:
        from dataclasses import replace as _dc_replace

        cfg = _dc_replace(cfg, follow=True)
    if getattr(args, "follow_poll_s", None) and cfg.follow:
        # applied even when --config already set follow=true: the
        # command-line cadence override must never be silently dropped
        from dataclasses import replace as _dc_replace

        cfg = _dc_replace(cfg, follow_poll_s=args.follow_poll_s)
    def call(method: str, path: str, body: bytes | None = None) -> dict:
        # the transport's bounded-jittered-retry helper: a transient
        # connection reset mid-poll retries instead of killing the client
        # before the daemon-death JSON fallback below can fire (with an
        # address LIST, each retry also rotates to the next daemon)
        return client_call(args.addr, method, path, body=body,
                           timeout=args.timeout)

    # HA address list (round 18): with several --addr members the client
    # mints a submit_token so the POST becomes IDEMPOTENT — the service
    # dedups on it, so a reply lost to a failover can safely re-POST to
    # the promoted daemon and land on the SAME job.  Single-address
    # submits stay the historical token-free single-shot (byte-identical
    # wire payloads).
    multi_addr = len(split_addrs(args.addr)) > 1
    if multi_addr and not cfg.submit_token:
        import secrets
        from dataclasses import replace as _dc_replace

        cfg = _dc_replace(cfg, submit_token=secrets.token_hex(16))
    submit_deadline = _time.monotonic() + args.timeout
    while True:
        try:
            # to_json() is ensure_ascii json.dumps output: strict is
            # exact.  Single-address: SINGLE-SHOT on purpose — submission
            # without a token is not idempotent, and a reply lost after
            # the daemon registered the job would re-POST a duplicate job
            # (the polls below retry; they're reads).  Multi-address: the
            # token above makes re-POSTs dedup, so the retry loop (which
            # rotates addresses) is safe to engage.
            reply = client_call(
                args.addr, "POST", "/jobs",
                cfg.to_json().encode("utf-8", "strict"),
                timeout=args.timeout, retry=multi_addr,
            )
            break
        except urllib.error.HTTPError as e:
            if (multi_addr and e.code == 503
                    and _time.monotonic() < submit_deadline):
                # failover window: a STANDBY answered (503) — the
                # transport never retries an answered request, but the
                # tokenized submit may re-POST until a daemon promotes
                _time.sleep(0.5)
                continue
            detail = e.read()[:500].decode("utf-8", "replace")
            print(f"error: submit rejected ({e.code}): {detail}",
                  file=sys.stderr)
            return 2
        except OSError as e:  # incl. CoordinatorGone: retry schedule dry
            print(f"error: cannot reach service at {args.addr}: {e}",
                  file=sys.stderr)
            return 2
    job_id = reply["job_id"]
    if cfg.follow:
        # a standing query has no completion to wait for: stream it on
        # request, else hand back the subscription endpoint
        if getattr(args, "stream", False):
            return _stream_follow(call, job_id, args)
        print(json.dumps({"job_id": job_id, "state": "following",
                          "stream": f"/jobs/{job_id}/stream"}))
        return 0
    if not args.wait:
        print(json.dumps({"job_id": job_id, "state": "submitted"}))
        return 0
    deadline = _time.monotonic() + args.timeout
    status: dict = {}
    out = {"job_id": job_id, "state": "unknown"}
    try:
        # the job is admitted: from here every outcome — daemon restart
        # mid-poll included — still prints exactly ONE JSON line
        while _time.monotonic() < deadline:
            try:
                status = call("GET", f"/jobs/{job_id}")
            except OSError:
                # failover window (HTTPError is an OSError subclass: a
                # standby answers polls 503 until it promotes) — with an
                # address list, keep polling out the budget; the promoted
                # daemon resumes the job and answers.  Single-address
                # keeps the historical fail-fast.
                if not multi_addr:
                    raise
                _time.sleep(0.5)
                continue
            if status.get("state") in ("done", "failed", "cancelled"):
                break
            _time.sleep(0.2)
        out["state"] = status.get("state", "unknown")
        if status.get("state") == "done":
            out["outputs"] = call("GET", f"/jobs/{job_id}/result")["outputs"]
        elif status.get("error"):
            out["error"] = status["error"]
        # shard-index routing, surfaced without trace-export: how many
        # shards the planner never dispatched (and the bytes they would
        # have scanned) — nonzero-only, so index-off daemons and
        # unpruned jobs keep the exact pre-index line
        counters = (status.get("metrics") or {}).get("counters") or {}
        if counters.get("index_shards_pruned"):
            out["index_shards_pruned"] = int(
                counters["index_shards_pruned"]
            )
            out["index_bytes_skipped"] = int(
                counters.get("index_bytes_skipped", 0)
            )
        # result-cache routing, same nonzero-only contract: how many map
        # splits answered from stored results without a scan
        if counters.get("result_splits_reused"):
            out["result_splits_reused"] = int(
                counters["result_splits_reused"]
            )
            out["result_bytes_unscanned"] = int(
                counters.get("result_bytes_unscanned", 0)
            )
        if args.explain and status.get("state") in ("done", "failed"):
            # the routing report, inline on the one JSON line — best
            # effort: a daemon too old for /explain answers 404, the
            # submit result must not fail over a diagnostics rider
            try:
                out["explain"] = call("GET", f"/jobs/{job_id}/explain")
            except (OSError, ValueError):
                pass
    except OSError as e:  # urllib.error.* are OSError subclasses
        out["error"] = f"lost service at {args.addr}: {e}"
    print(json.dumps(out))
    return 0 if out["state"] == "done" else 1


def _stream_follow(call, job_id: str, args: argparse.Namespace) -> int:
    """Drive GET /jobs/<id>/stream with a moving cursor, printing each
    record as a grep-shaped line (count records as "+N" deltas), until
    --timeout elapses or the job leaves RUNNING; then exactly one JSON
    summary line (the submit stdout contract, streamed lines above it)."""
    import time as _time

    deadline = _time.monotonic() + args.timeout
    cursor = 0
    printed = 0
    dropped = 0
    state = "running"
    while _time.monotonic() < deadline:
        # the server-side long-poll window must sit comfortably INSIDE
        # the transport's socket timeout (args.timeout — the same value
        # bounds each request): a window equal to the remaining budget
        # races the socket timer and the final poll reports a bogus
        # "lost service" instead of draining cleanly
        window = min(10.0, max(0.5, deadline - _time.monotonic()),
                     max(0.5, args.timeout - 2.0))
        try:
            reply = call(
                "GET",
                f"/jobs/{job_id}/stream?cursor={cursor}"
                f"&timeout={window:.1f}",
            )
        except OSError as e:
            print(f"error: lost service mid-stream: {e}", file=sys.stderr)
            break
        cursor = int(reply.get("next", cursor))
        state = reply.get("state", state)
        dropped += int(reply.get("dropped", 0))
        records = reply.get("records") or []
        for rec in records:
            printed += 1
            if rec.get("reset"):
                _print_follow_reset(rec)
                continue
            line = _follow_record_line(rec)
            if line is not None:
                print(line, flush=True)
            elif "count" in rec:
                print(f"{rec['file']}: +{int(rec['count'])}", flush=True)
            elif rec.get("match"):
                print(rec["file"], flush=True)
        if state in ("done", "failed", "cancelled") and not records:
            break  # terminal and drained; "queued" keeps polling — the
            # standing query starts once an admission slot frees up
        if not records and state != "running":
            # a queued job's page answers immediately (no runner, no
            # long-poll yet): pace the re-poll instead of hot-spinning
            _time.sleep(min(0.5, max(0.0, deadline - _time.monotonic())))
    out: dict = {"job_id": job_id, "state": state, "records": printed,
                 "cursor": cursor}
    if dropped:
        out["dropped"] = dropped
    print(json.dumps(out))
    return 0


def cmd_trace_export(args: argparse.Namespace) -> int:
    """Render a job's events.jsonl (the span pipeline's persisted event
    log, utils/spans.py) as Chrome trace_event JSON — loadable in Perfetto
    (ui.perfetto.dev), chrome://tracing, and TensorBoard's trace viewer,
    next to the jax.profiler device trace from DGREP_TRACE_DIR.

    ``--fleet``: the positional is a service WORK ROOT instead — the
    daemon.jsonl fleet timeline (all incarnations, epoch-ordered) merges
    with every job's events.jsonl into one trace, daemon rows above
    worker rows, promotion latency rendered as a span."""
    from pathlib import Path

    from distributed_grep_tpu.utils.spans import (
        EventLog,
        export_chrome_trace,
        export_fleet_trace,
    )

    if getattr(args, "fleet", False):
        from distributed_grep_tpu.runtime import daemon_log as daemon_log_mod

        root = Path(args.events)
        if root.is_file():  # a daemon.jsonl path: the root holds it
            root = root.parent
        if not (root / daemon_log_mod.FILENAME).exists():
            print(f"error: no {daemon_log_mod.FILENAME} under {root} "
                  f"(serve with DGREP_DAEMON_LOG on)", file=sys.stderr)
            return 2
        jobs = {
            p.parent.name: EventLog.read(p)
            for p in sorted(root.glob(f"*/{EventLog.FILENAME}"))
        }
        doc = export_fleet_trace(daemon_log_mod.DaemonLog.read(root), jobs)
    else:
        path = Path(args.events)
        if path.is_dir():  # a work dir: the log lives at its root
            path = path / EventLog.FILENAME
        if not path.exists():
            print(f"error: no event log at {path} (run the job with "
                  f"JobConfig.spans=true or DGREP_SPANS=1)", file=sys.stderr)
            return 2
        doc = export_chrome_trace(EventLog.read(path))
    if args.out and args.out != "-":
        Path(args.out).write_text(json.dumps(doc))
        print(f"{len(doc['traceEvents'])} trace events -> {args.out}",
              file=sys.stderr)
    else:
        json.dump(doc, sys.stdout)
        print()
    return 0


def cmd_explain(args: argparse.Namespace) -> int:
    """Per-query routing report (round 15): which kernel family ran,
    host-vs-device route, shards index-pruned, fused or solo, model/
    corpus cache verdicts, per-stage walls — one JSON document assembled
    from the job's events.jsonl + piggybacked engine stats, so the "why
    was this query fast/slow" answer needs no Perfetto session.  With
    --addr the daemon assembles it (GET /jobs/<id>/explain); without,
    TARGET is a local work dir (or events.jsonl path) and the report is
    built from the event log alone."""
    import urllib.error

    if args.addr:
        from distributed_grep_tpu.runtime.http_transport import client_call

        try:
            doc = client_call(args.addr, "GET",
                              f"/jobs/{args.target}/explain",
                              timeout=args.timeout)
        except urllib.error.HTTPError as e:
            detail = e.read()[:200].decode("utf-8", "replace")
            print(f"error: explain failed ({e.code}): {detail}",
                  file=sys.stderr)
            return 2
        except OSError as e:
            print(f"error: cannot reach service at {args.addr}: {e}",
                  file=sys.stderr)
            return 2
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    from pathlib import Path

    from distributed_grep_tpu.runtime import explain as explain_mod
    from distributed_grep_tpu.utils.spans import EventLog

    path = Path(args.target)
    if path.is_dir():
        path = path / EventLog.FILENAME
    if not path.exists():
        print(f"error: no event log at {path} (run the job with "
              f"\"spans\": true or DGREP_SPANS=1, or pass --addr for a "
              f"service job)", file=sys.stderr)
        return 2
    # a service job's workdir is <work_root>/<job_id>: when the fleet
    # timeline sits next to it, the disruptions section rides along
    from distributed_grep_tpu.runtime import daemon_log as daemon_log_mod

    daemon_events = None
    work_root = path.parent.parent
    if (work_root / daemon_log_mod.FILENAME).exists():
        daemon_events = daemon_log_mod.DaemonLog.read(work_root)
    doc = explain_mod.assemble(
        job_id=path.parent.name, config=None, state="",
        submitted_at=None, started_at=None, finished_at=None,
        metrics_counters={}, events=EventLog.read(path),
        daemon_events=daemon_events,
    )
    print(json.dumps(doc, indent=2, sort_keys=True))
    return 0


def cmd_status(args: argparse.Namespace) -> int:
    """Operator surface for a running coordinator: pretty-print its
    GET /status JSON (task states per phase + metrics counters)."""
    import urllib.error

    from distributed_grep_tpu.runtime.http_transport import client_call

    url = f"http://{args.addr}/status"
    try:
        # the transport's bounded-retry helper (net-retry rule): transient
        # resets retry instead of failing the operator's one-shot query
        status = client_call(args.addr, "GET", "/status",
                             timeout=args.timeout)
    except urllib.error.HTTPError as e:  # reached, but not a coordinator
        print(f"error: {url} answered {e.code} {e.reason}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"error: cannot reach coordinator at {args.addr}: {e}",
              file=sys.stderr)
        return 2
    except ValueError:  # 200 with a non-JSON body (proxy page, wrong port)
        print(f"error: {url} did not return JSON — not a coordinator?",
              file=sys.stderr)
        return 2
    print(json.dumps(status, indent=2, sort_keys=True))
    return 0


def env_top_interval_s(default: float = 2.0) -> float:
    """`dgrep top` refresh cadence — the ONE parser of
    DGREP_TOP_INTERVAL_S (malformed or <= 0 keeps the default, the
    env_batch_bytes shrug-off policy)."""
    import os

    raw = os.environ.get("DGREP_TOP_INTERVAL_S")
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def _parse_metrics_text(text: str) -> dict[str, float]:
    """Prometheus exposition -> {name: value} for UNLABELED samples
    (gauges/counters and histogram _sum/_count lines; labeled bucket
    lines are skipped — top reads only the plain series)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2 or "{" in parts[0]:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


def _kv_line(d: dict) -> str:
    return "  ".join(f"{k}={d[k]}" for k in sorted(d))


def _render_top(statuses: dict[str, dict | None],
                active_addr: str | None,
                metrics: dict[str, float]) -> str:
    """One refresh of the console as plain text: role banner per address,
    headline gauges, scale/quarantine state, windowed cache-hit ratios
    (from /metrics), and the per-worker table with the SAME freshness
    signal the scale advisor reads (last_event_age_s)."""
    lines: list[str] = []
    roles = []
    for addr, st in statuses.items():
        role = "down" if st is None else str(st.get("role", "active"))
        roles.append(f"{addr} [{role.upper()}]")
    lines.append("dgrep top — " + "   ".join(roles))
    st = statuses.get(active_addr) if active_addr else None
    if st is None:
        standby = next((s for s in statuses.values() if s), None)
        if standby is None:
            lines.append("no daemon reachable")
        else:
            lines.append("no ACTIVE daemon — parked standby answers; "
                         f"lease names {standby.get('active', '?')}")
        return "\n".join(lines)
    lines.append(
        f"uptime {st.get('uptime_s', 0.0):8.1f}s   "
        f"queued {st.get('queued', 0)}/{st.get('queue_depth_cap', '?')}   "
        f"running {len(st.get('running', []))}/{st.get('max_jobs', '?')}   "
        f"workers {len(st.get('workers', {}))}   "
        f"quarantined {st.get('workers_quarantined', 0)}"
    )
    scale = st.get("scale")
    if scale:
        lines.append(f"scale: {_kv_line(scale)}")
    ratios = {
        k.replace("dgrep_", "").replace("_hit_ratio", ""): round(v, 3)
        for k, v in metrics.items() if k.endswith("_hit_ratio")
    }
    if ratios:
        lines.append("cache hit ratios (window): " + _kv_line(ratios))
    failovers = metrics.get("dgrep_daemon_failover_seconds_count")
    if failovers:
        mean = (metrics.get("dgrep_daemon_failover_seconds_sum", 0.0)
                / failovers)
        lines.append(f"failovers: {int(failovers)} "
                     f"(mean {mean:.2f}s promotion latency)")
    latency = st.get("latency")
    if latency:
        for key, summ in sorted(latency.items()):
            lines.append(f"latency {key}: {_kv_line(summ)}")
    follow = st.get("follow")
    if follow:
        follow = dict(follow)
        groups = follow.pop("groups", None)
        lines.append(f"follow: {_kv_line(follow)}")
        for g in groups or []:
            # Per-group wake lag (now - last wake) is the standing-query
            # liveness signal — a stuck group runner shows here first.
            lines.append(
                f"  group [{','.join(str(j) for j in g.get('jobs', []))}]: "
                f"members={g.get('members', 0)} files={g.get('files', 0)} "
                f"poll_s={g.get('poll_s', 0)} wakes={g.get('wakes', 0)} "
                f"wake_lag_s={g.get('wake_lag_s', 0.0)}"
            )
    workers = st.get("workers") or {}
    if workers:
        lines.append("")
        lines.append(f"{'WID':>4} {'EVENT AGE':>10} {'JOB':>8} "
                     f"{'TASK':>6} {'QUAR':>6}  GBPS")
        for wid in sorted(workers, key=lambda w: int(w)):
            row = workers[wid]
            m = row.get("metrics") or {}
            quar = row.get("quarantined_s")
            lines.append(
                f"{wid:>4} {row.get('last_event_age_s', 0.0):>9.1f}s "
                f"{str(row.get('job') or '-'):>8} "
                f"{str(row.get('task') if row.get('task') is not None else '-'):>6} "
                f"{(f'{quar:.0f}s' if quar else '-'):>6}  "
                f"{m.get('gbps', 0.0):.3f}"
            )
    jobs = st.get("jobs") or {}
    active_jobs = {j: d for j, d in jobs.items()
                   if d.get("state") in ("running", "queued")}
    if active_jobs:
        lines.append("")
        for jid in sorted(active_jobs):
            d = active_jobs[jid]
            prog = ""
            if "map_total" in d:
                prog = f"  map {d.get('map_completed', 0)}/{d['map_total']}"
            lines.append(f"job {jid}: {d.get('state')}{prog}")
    return "\n".join(lines)


def cmd_top(args: argparse.Namespace) -> int:
    """Live fleet console (round 19): poll /status + /metrics across the
    address list — each address queried directly (single-shot, no retry
    burn on a dead daemon) so the banner shows the WHOLE fleet's roles,
    and the body renders the active's view (standby-aware: a parked
    standby never masks the active the way first-listed-wins would)."""
    import time as _time

    from distributed_grep_tpu.runtime.http_transport import (
        client_call,
        client_text,
        split_addrs,
    )

    addrs = split_addrs(args.addr)
    interval = args.interval if args.interval else env_top_interval_s()
    try:
        while True:
            statuses: dict[str, dict | None] = {}
            for a in addrs:
                try:
                    st = client_call(a, "GET", "/status",
                                     timeout=args.timeout, retry=False)
                    statuses[a] = st if isinstance(st, dict) else None
                except Exception:  # noqa: BLE001 — down/parked/not-ours
                    statuses[a] = None
            active_addr = next(
                (a for a, s in statuses.items()
                 if s and s.get("service")
                 and s.get("role", "active") == "active"),
                None)
            metrics: dict[str, float] = {}
            if active_addr is not None:
                try:
                    metrics = _parse_metrics_text(client_text(
                        active_addr, "/metrics", timeout=args.timeout))
                except Exception:  # noqa: BLE001 — console stays up
                    pass
            screen = _render_top(statuses, active_addr, metrics)
            if args.once:
                print(screen)
                return 0 if any(statuses.values()) else 2
            # redraw in place, top(1)-style
            sys.stdout.write("\x1b[H\x1b[2J" + screen + "\n")
            sys.stdout.flush()
            _time.sleep(interval)
    except KeyboardInterrupt:
        return 0


class _GlobFilterAction(argparse.Action):
    """--include/--exclude share one ORDERED filter list (GNU grep decides
    by the last matching glob, so relative option order is semantic)."""

    def __call__(self, parser, namespace, value, option_string=None):
        lst = getattr(namespace, "glob_filters", None) or []
        kind = "include" if "include" in option_string else "exclude"
        lst.append((kind, value))
        namespace.glob_filters = lst


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "analyze":
        # Project invariant checker (analysis/): AST-walked rules encoding
        # the contracts CLAUDE.md documents as prose.  Dispatched before
        # the main parser so analysis/checker.py stays the single owner of
        # the checker's flags (REMAINDER can't forward leading options).
        from distributed_grep_tpu.analysis.checker import main as analyze_main

        return analyze_main(argv[1:])
    parser = argparse.ArgumentParser(prog="distributed_grep_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    # add_help=False frees -h for grep's no-filename flag (GNU grep -h);
    # --help still works
    p = sub.add_parser("grep", help="distributed grep over input files",
                       add_help=False)
    p.add_argument("--help", action="help",
                   help="show this help message and exit")
    p.add_argument("pattern", nargs="?", default=None)
    p.add_argument("files", nargs="*")
    p.add_argument("-i", "--ignore-case", action="store_true")
    p.add_argument("-v", "--invert", action="store_true",
                   help="emit non-matching lines (grep -v)")
    p.add_argument("--max-errors", type=int, default=0, metavar="K",
                   help="agrep: match within K edit errors (literal/class "
                        "patterns, K=1..3)")
    p.add_argument("-c", "--count", action="store_true",
                   help="print match counts per file instead of lines (grep -c)")
    p.add_argument("-l", "--files-with-matches", action="store_true",
                   help="print only names of files containing matches (grep -l)")
    p.add_argument("-o", "--only-matching", action="store_true",
                   help="print each matched substring on its own line (grep -o)")
    p.add_argument("-A", "--after-context", type=int, default=0, metavar="N",
                   help="print N lines of trailing context (grep -A)")
    p.add_argument("-B", "--before-context", type=int, default=0, metavar="N",
                   help="print N lines of leading context (grep -B)")
    p.add_argument("-C", "--context", type=int, default=None, metavar="N",
                   help="print N lines of context before and after (grep -C)")
    p.add_argument(
        "-f", "--patterns-file", default=None,
        help="pattern set, one per line: literals by default (grep -F -f; "
             "device scan uses Aho-Corasick/FDR pattern-set engines), or "
             "regexes with -E (compiled as one alternation)",
    )
    p.add_argument("-E", "--extended-regexp", action="store_true",
                   help="with -f: treat pattern-file lines as regexes")
    p.add_argument("-F", "--fixed-strings", action="store_true",
                   help="treat PATTERN / -e patterns as literal strings")
    p.add_argument("-e", "--regexp", action="append", default=None,
                   metavar="PATTERN", dest="e_patterns",
                   help="pattern to match (repeatable; lines matching any "
                        "are selected)")
    p.add_argument("-w", "--word-regexp", action="store_true",
                   help="match only whole words (grep -w)")
    p.add_argument("-x", "--line-regexp", action="store_true",
                   help="match only whole lines (grep -x)")
    p.add_argument("-m", "--max-count", type=int, default=None, metavar="NUM",
                   help="stop after NUM selected lines per file (grep -m)")
    p.add_argument("-L", "--files-without-match", action="store_true",
                   help="print only names of files with no matches (grep -L)")
    p.add_argument("-q", "--quiet", "--silent", action="store_true",
                   help="no output; exit 0 iff any line is selected (grep -q)")
    p.add_argument("-r", "--recursive", action="store_true",
                   help="descend into directory arguments (grep -r)")
    p.add_argument("-R", "--dereference-recursive", action="store_true",
                   help="like -r, but follow all symlinks (grep -R); "
                        "directory cycles are pruned silently")
    p.add_argument("--follow", action="store_true",
                   help="standing query (round 17): poll the inputs for "
                        "appended data and print matches as they arrive "
                        "(tail -f | grep, with per-file cursors and "
                        "truncation-aware rescans)")
    p.add_argument("--follow-idle-s", type=float, default=0.0, metavar="S",
                   help="with --follow: exit once no input has grown for "
                        "S seconds (0 = run until interrupted)")
    p.add_argument("-b", "--byte-offset", action="store_true",
                   help="print each line's starting byte offset (grep -b)")
    p.add_argument("-h", "--no-filename", action="store_true",
                   help="omit the file name prefix from output (grep -h)")
    p.add_argument("-s", "--no-messages", action="store_true",
                   help="suppress messages about missing/unreadable files "
                        "(grep -s)")
    # GNU-compatibility no-ops: each names behavior that is already this
    # CLI's default, so scripts written against GNU grep keep working.
    # -n: line numbers always print (the output format embeds them, the
    # reference app's key shape); -H: file names always print unless -h;
    # -a: input is always processed as binary-safe text (lines split on
    # \n only, output lossily decoded — there is no "binary file" mode).
    p.add_argument("-n", "--line-number", action="store_true",
                   help="accepted for GNU compatibility (line numbers "
                        "always print here)")
    p.add_argument("-H", "--with-filename", action="store_true",
                   help="accepted for GNU compatibility (file names "
                        "always print here unless -h)")
    p.add_argument("-a", "--text", action="store_true",
                   help="accepted for GNU compatibility (input is always "
                        "treated as binary-safe text here)")
    p.add_argument("--exclude-dir", action="append", metavar="GLOB",
                   help="with -r: skip descended directories whose basename "
                        "matches GLOB (repeatable, grep --exclude-dir; like "
                        "GNU grep, a GLOB containing '/' never matches a "
                        "basename)")
    p.add_argument("--include", action=_GlobFilterAction, dest="glob_filters",
                   default=None, metavar="GLOB",
                   help="search only files whose basename matches GLOB "
                        "(repeatable; applies to explicit files too; ordered "
                        "with --exclude, last matching glob wins, like GNU "
                        "grep)")
    p.add_argument("--exclude", action=_GlobFilterAction, dest="glob_filters",
                   default=None, metavar="GLOB",
                   help="skip files whose basename matches GLOB (repeatable; "
                        "ordered with --include, last matching glob wins, "
                        "like GNU grep)")
    _add_common(p)
    p.set_defaults(fn=cmd_grep)

    p = sub.add_parser("run", help="run any MapReduce application from a job config")
    p.add_argument("--config", required=True)
    p.add_argument("--resume", action="store_true", help="replay journal, skip done tasks")
    _add_common(p)
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("coordinator", help="serve the distributed control plane")
    p.add_argument("--config", required=True)
    p.add_argument("--resume", action="store_true")
    p.set_defaults(fn=cmd_coordinator)

    p = sub.add_parser("status", help="query a running coordinator's task/metric state")
    p.add_argument("--addr", required=True, help="coordinator http address host:port")
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser(
        "top",
        help="live fleet console: roles, queue/running/workers, cache "
             "hit ratios, per-worker freshness and quarantine — "
             "refreshed from /status + /metrics",
    )
    p.add_argument("--addr", required=True,
                   help="daemon http address host:port — or a comma-"
                        "separated active,standby list: every member is "
                        "polled, the banner shows each one's role, the "
                        "body renders the active's view")
    p.add_argument("--interval", type=float, default=None, metavar="S",
                   help="refresh cadence (default DGREP_TOP_INTERVAL_S, "
                        "2 s)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit (no screen redraw; "
                        "exit 2 when no daemon answers)")
    p.add_argument("--timeout", type=float, default=5.0)
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "trace-export",
        help="render a job's events.jsonl span log as Chrome trace JSON "
             "(Perfetto/TensorBoard-loadable)",
    )
    p.add_argument("events",
                   help="path to events.jsonl, or the job work dir "
                        "containing it (with --fleet: the service WORK "
                        "ROOT holding daemon.jsonl)")
    p.add_argument("-o", "--out", default="-",
                   help="output file (default: stdout)")
    p.add_argument("--fleet", action="store_true",
                   help="render a whole work root: the daemon.jsonl fleet "
                        "timeline (every incarnation, epoch-ordered, "
                        "promotion latency as a span) merged with every "
                        "job's events.jsonl")
    p.set_defaults(fn=cmd_trace_export)

    # listed for --help discoverability; the real dispatch (with the
    # checker's own flags) happens above, before this parser runs
    sub.add_parser("analyze",
                   help="project invariant checker (exit 1 on violations; "
                        "see `analyze --help` for rules/baseline/knobs)")

    p = sub.add_parser("worker", help="connect to a coordinator and process tasks")
    p.add_argument("--addr", required=True,
                   help="coordinator http address host:port — or a comma-"
                        "separated active,standby list: retries rotate "
                        "across it, and the worker parks while only "
                        "standbys answer")
    p.add_argument("--slots", type=int, default=1, help="parallel task slots")
    p.set_defaults(fn=cmd_worker)

    p = sub.add_parser(
        "serve",
        help="grep-as-a-service daemon: persistent multi-tenant coordinator "
             "serving a stream of jobs (submit with `submit --addr`)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="listen port (0 = ephemeral; the bound port is "
                        "logged at startup)")
    p.add_argument("--work-root", default=None,
                   help="root directory for per-job work dirs "
                        "(default: a fresh temp dir)")
    p.add_argument("--workers", type=int, default=2,
                   help="in-process worker loops to attach (0 = none; "
                        "remote workers attach via `worker --addr`)")
    p.add_argument("--max-workers", type=int, default=None,
                   help="elastic ceiling for the local pool: grow toward "
                        "it on the /status scale advice (queue depth, "
                        "pending tasks, in-flight age), shrink back to "
                        "--workers when idle; unset = fixed pool")
    p.add_argument("--max-jobs", type=int, default=None,
                   help="concurrent running-job cap "
                        "(DGREP_SERVICE_MAX_JOBS overrides)")
    p.add_argument("--queue", type=int, default=None,
                   help="queued-submission cap, admission control "
                        "(DGREP_SERVICE_QUEUE overrides)")
    p.add_argument("--spans", action="store_true",
                   help="span pipeline for every job (per-job events.jsonl)")
    p.add_argument("--no-resume", action="store_true",
                   help="do not replay the work root's jobs.jsonl registry "
                        "(default: a restarted daemon re-admits queued jobs "
                        "and resumes running ones; DGREP_SERVICE_RESUME=0 "
                        "is the env equivalent)")
    p.add_argument("--standby", action="store_true",
                   help="active/standby failover: contend for the work "
                        "root's lease file — serve while holding it, park "
                        "as a standby (answering /status role=standby) "
                        "while another daemon does, and promote via the "
                        "resume path when its lease goes stale past "
                        "DGREP_LEASE_TTL_S (setting that env var enables "
                        "the same mode without this flag)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "submit",
        help="submit a job to a running service daemon and print one JSON "
             "line (job_id, state, outputs)",
    )
    p.add_argument("--addr", required=True,
                   help="service http address host:port — or a comma-"
                        "separated active,standby list: the submit is "
                        "tokenized (idempotent) and follows a failover")
    p.add_argument("--config", default=None,
                   help="job config JSON (like `run --config`); otherwise "
                        "give PATTERN and FILE arguments")
    p.add_argument("pattern", nargs="?", default=None)
    p.add_argument("files", nargs="*")
    p.add_argument("-i", "--ignore-case", action="store_true")
    # pattern-set parity with the local grep CLI (same resolution front
    # half, _resolve_pattern_args): multi-pattern submits are what the
    # scan-fusion layer and the FDR set engines serve
    p.add_argument("-e", "--regexp", action="append", default=None,
                   metavar="PATTERN", dest="e_patterns",
                   help="pattern(s); repeatable — the job runs their union")
    p.add_argument("-f", "--patterns-file", default=None,
                   help="newline-separated pattern file (like grep -f)")
    p.add_argument("-F", "--fixed-strings", action="store_true",
                   help="treat PATTERN / -e patterns as literal strings")
    p.add_argument("-E", "--extended-regexp", action="store_true",
                   help="with -f: treat pattern lines as regexes "
                        "(joined alternation)")
    p.add_argument("--backend", default="cpu", choices=["cpu", "device"],
                   help="engine backend for the PATTERN/FILE form (default "
                        "cpu: host scanners, no jax import on the workers; "
                        "device engages the TPU path — and the warm-compile "
                        "amortization — on accelerator deployments)")
    p.add_argument("--n-reduce", type=int, default=None)
    p.add_argument("--no-wait", dest="wait", action="store_false",
                   help="return after submission instead of waiting for "
                        "completion")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="overall wait budget in seconds (with waiting on)")
    p.add_argument("--explain", action="store_true",
                   help="include the per-query routing report "
                        "(GET /jobs/<id>/explain) in the final JSON line")
    p.add_argument("--follow", action="store_true",
                   help="submit a STANDING query (round 17): the daemon "
                        "suffix-scans the inputs as they grow; subscribe "
                        "via GET /jobs/<id>/stream (or --stream here)")
    p.add_argument("--follow-poll-s", type=float, default=None, metavar="S",
                   help="with --follow: wake cadence override "
                        "(DGREP_FOLLOW_POLL_S wins; default 0.5 s)")
    p.add_argument("--stream", action="store_true",
                   help="with --follow: print stream records as they "
                        "arrive until --timeout elapses, then one JSON "
                        "summary line")
    p.set_defaults(fn=cmd_submit, wait=True)

    p = sub.add_parser(
        "explain",
        help="per-query routing report: kernel family, host/device "
             "route, index prunes, fusion, cache hits — from a service "
             "job (--addr JOB_ID) or a local work dir's events.jsonl",
    )
    p.add_argument("target",
                   help="job id (with --addr) or a work dir / "
                        "events.jsonl path")
    p.add_argument("--addr", default=None,
                   help="service http address host:port (assembles the "
                        "report daemon-side)")
    p.add_argument("--timeout", type=float, default=10.0)
    p.set_defaults(fn=cmd_explain)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

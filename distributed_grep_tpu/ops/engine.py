"""GrepEngine: compiled pattern + device scan + host stitching, one object.

Engine selection, fastest first (the pluggable-backend story the north star
pins — CPU grep and TPU grep are interchangeable behind the application
interface):

1. ``shift_and`` — literal/class sequences <= 32 symbols: bit-parallel VPU
   scan (Pallas kernel on TPU, XLA scan elsewhere);
2. ``nfa``       — general regex (alternations, repeats, '^') <= 128
   Glushkov positions: bit-parallel position-automaton Pallas kernel
   (models/nfa.py, ops/pallas_nfa.py) — per-word bit-ops (range-compare
   or lane-gather B), so it keeps Pallas throughput where the DFA's
   per-byte table gather would fall off the cliff;
3. ``dfa``       — anything the subset compiler handles within the state
   cap ('$' accepts, big patterns, pattern-set banks): vectorized DFA
   table scan (XLA);
4. ``re``        — host fallback (Python re per line) for patterns outside
   the subset (e.g. newline-consuming) — the reference's own strategy
   (application/grep.go:20-30), kept as the escape hatch.

Orthogonal modes: ``fdr`` (large literal sets — Pallas bucket filter +
exact host confirm, models/fdr.py), ``pairset`` (all-1-2-byte sets —
exact row-partition pair kernel, no confirm, models/pairset.py), and
``approx`` (``max_errors=k`` agrep matching — k+1-row bit-parallel
recurrence, models/approx.py).

Large documents are scanned in segments (bounded device memory — the
reference instead reads whole files and cannot handle files larger than
RAM, worker.go:72-76); segment starts and stripe starts are boundary
positions whose lines get exact host re-scans (ops/lines.py).
"""

from __future__ import annotations

import os as _os
import re as _re
import time as _time_mod
from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.models.aho import compile_aho_corasick_banks
from distributed_grep_tpu.models.fdr import (
    FP_CEILING_PER_BYTE,
    FdrError,
    FdrModel,
    compile_fdr,
)
from distributed_grep_tpu.models.dfa import (
    DfaTable,
    RegexError,
    build_stride_table,
    choose_stride,
    compile_dfa,
    reference_scan,
    enumerate_literal_set,
)
from distributed_grep_tpu.models.approx import (
    MAX_ERRORS,
    ApproxModel,
    line_matches as approx_line_matches,
    scan_reference as approx_scan_reference,
    try_compile_approx,
)
from distributed_grep_tpu.models.nfa import GlushkovModel, compile_scan_model
from distributed_grep_tpu.models.shift_and import (
    ShiftAndModel,
    filtered_for_device,
    try_compile_shift_and,
)
from distributed_grep_tpu.ops import lines as lines_mod
from distributed_grep_tpu.utils import spans as spans_mod
from distributed_grep_tpu.utils.logging import get_logger

# A cold XLA/Mosaic compile through a tunneled TPU runs ~20-40 s with no
# observable progress; the scan declares it as a bounded grace window on
# its progress callback (per fresh layout shape) so a tight
# failure-detector window tolerates compiles without being blind to hangs.
COMPILE_GRACE_S = float(_os.environ.get("DGREP_COMPILE_GRACE_S", "90"))

# First-touch device responsiveness wall (engine._device_responsive): a
# wedged device transport hangs jax's backend init in C with no exception
# to catch, so the first jax touch is time-boxed on a side thread.  Cold
# init through a healthy tunnel is ~1-2 s; 30 s is comfortably above any
# legitimate init and far below a hung map task's cost.  The verdict is
# PROCESS-wide (one backend per process; responsiveness cannot change
# within it, like _accel_backend's cache) and lock-serialized so
# concurrent first scans wait for one probe instead of hanging past it.
DEVICE_PROBE_S = float(_os.environ.get("DGREP_DEVICE_PROBE_S", "30"))

# Mid-scan stall wall: the per-segment collect/feed waits are time-boxed
# so a device that black-holes AFTER a healthy first touch (observed: the
# tunnel degraded from fast connection-errors to indefinite hangs within
# the same outage) degrades the scan to the exact host engines instead of
# hanging the dispatch thread forever.  Generous: a legitimate segment
# collect through the slow tunnel (upload + execute + confirm) is tens of
# seconds at worst.
DEVICE_STALL_S = float(_os.environ.get("DGREP_DEVICE_STALL_S", "300"))

# A degraded engine re-probes the device this often (0 disables): device
# outages are usually transient (the observed tunnel drop recovered in
# past sessions), and a long-lived worker should win the device back
# instead of staying on host scanners forever.  A failed retry costs one
# bounded probe (DEVICE_PROBE_S) per window.
DEVICE_RETRY_S = float(_os.environ.get("DGREP_DEVICE_RETRY_S", "600"))
import threading as _threading_mod

from distributed_grep_tpu.utils import lockdep as _lockdep

# io_ok: racers deliberately WAIT on an in-flight probe under this lock
# rather than falling through to a hanging device call.
_device_probe_lock = _lockdep.make_lock("device-probe", io_ok=True)
# Process-global probe state {verdict, at}: one backend per process, so
# one verdict serves every engine; a False verdict re-probes at most once
# per DEVICE_RETRY_S window PROCESS-WIDE (N degraded engines share the
# single probe instead of each paying their own).  Guarded by the lock —
# racers wait on an in-flight probe rather than falling through to a
# hanging device call.
_device_probe_state: dict = {"verdict": None, "at": 0.0}


# Substrings that mark a device-scan exception as TRANSPORT evidence (the
# tunnel/link, not a deterministic kernel or pattern defect).  Drawn from
# the observed outage phases: grpc-style status names, socket-level errno
# text, and the tunnel's own "Connection Failed" wording.
_TRANSPORT_ERR_MARKERS = (
    "connection", "unavailable", "deadline", "timed out", "timeout",
    "socket", "transport", "tunnel", "broken pipe", "reset by peer",
    "unreachable",
)


def _accepts_grace_kwarg(progress) -> bool:
    """Whether a progress callback can take ``grace_s=`` — decided from
    its SIGNATURE (once per scan), not by catching TypeError around the
    live call, which cannot distinguish 'callback lacks the kwarg' from a
    TypeError raised inside the callback body (round-4 ADVICE).  C
    callables without an introspectable signature are assumed modern: a
    TypeError from them is then a real bug and propagates."""
    import inspect

    try:
        inspect.signature(progress).bind(grace_s=COMPILE_GRACE_S)
        return True
    except TypeError:
        return False
    except ValueError:  # no introspectable signature (C callable)
        return True


def _is_transport_error(e: BaseException) -> bool:
    """True when a device-scan failure looks like the transport died
    (jaxlib RuntimeError/XlaRuntimeError carrying connection wording)
    rather than a deterministic per-pattern failure.  Transport-evidence
    demotions stay eligible for the DEVICE_RETRY_S un-demote; anything
    unrecognized keeps the conservative permanent per-engine demotion
    (a wrong True here costs one bounded probe per retry window; a wrong
    False costs the device until process restart — round-4 ADVICE)."""
    if not isinstance(e, RuntimeError):
        return False
    msg = f"{type(e).__name__}: {e}".lower()
    return any(m in msg for m in _TRANSPORT_ERR_MARKERS)


def _report_device_sick() -> None:
    """A demotion (stall wall, exhausted routes, failed first touch) is
    process-wide evidence: jax answers `jax.devices()` from its client
    cache after a successful init, so only REAL device work can observe a
    mid-session black-hole — record the sickness so every engine's next
    probe is the deep retry, not the stale cached True."""
    with _device_probe_lock:
        _device_probe_state.update(verdict=False, at=_time_mod.monotonic())


def _probe_device_blocking() -> bool:
    """Time-boxed DEEP device probe on an abandoned daemon thread: backend
    init (`jax.devices()` — the call that hangs on a cold wedge) plus one
    tiny round trip (`device_put` + block_until_ready — the only way to
    observe a transport that black-holed AFTER a healthy init, since
    devices() is answered from jax's cache from then on).  ~ms when
    healthy."""
    import queue as _queue

    out: _queue.Queue = _queue.Queue()

    def probe() -> None:
        try:
            import jax

            jax.devices()
            jax.block_until_ready(
                jax.device_put(np.zeros(8, np.uint8))
            )
            out.put(True)
        except Exception:  # noqa: BLE001 — broken backend = not responsive
            out.put(False)

    _threading_mod.Thread(target=probe, daemon=True, name="dev-probe").start()
    try:
        return out.get(timeout=DEVICE_PROBE_S)
    except _queue.Empty:
        return False

log = get_logger("engine")

# Persistent read-ahead pools for scan_file's disk/scan overlap (round 6):
# ONE one-slot daemon pool per scanning thread, PROCESS-wide — shared by
# every engine, so neither constructing engines in a loop nor scanning
# thousands of files spawns threads (the old per-file ThreadPoolExecutor
# measured real overhead on a 2,000-file grep -r, round-5 note).  Entries
# for dead threads are pruned (pool shut down via its sentinel) on the
# next pool creation, so a process that churns worker threads does not
# accumulate idle daemon readers.
_reader_pools: dict = {}
_reader_pools_lock = _lockdep.make_lock("reader-pools")


def _thread_reader_pool():
    me = _threading_mod.get_ident()
    with _reader_pools_lock:
        pool = _reader_pools.get(me)
        if pool is None:
            live = {t.ident for t in _threading_mod.enumerate()}
            for ident in [k for k in _reader_pools if k not in live]:
                _reader_pools.pop(ident).shutdown(wait=False)
            from distributed_grep_tpu.ops.device_scan import _DaemonPool

            pool = _DaemonPool(1, thread_name_prefix="dgrep-read")
            _reader_pools[me] = pool
        return pool

# Coarse span path: above this many candidate lines per segment, per-line
# Python confirm would crawl — one native DFA pass over the whole segment
# (C, ~GB/s, vectorized line mapping) resolves everything instead.
SPAN_CONFIRM_LINE_LIMIT = 4096

# ------------------------------------------------ cross-job model cache
# The grep-as-a-service regime (runtime/service.py) reconfigures engines
# per task as jobs multiplex over shared workers; without a cache every
# pattern re-pays model compile (AC banks, FDR plans, Glushkov builds)
# and — on a real chip — the ~20-40 s first XLA/Mosaic compile per fresh
# (mode, mesh, model_gen, shape) key.  cached_engine() memoizes whole
# engines by their construction arguments: a cache hit returns the SAME
# engine object, so its _compiled_keys / jit caches / uploaded device
# tables come along for free and the compile-grace path is skipped on
# the repeat submit.  Engines are scan-thread-safe by construction
# (thread-local stats/nl stash, per-thread reader pools — the same
# contract concurrent worker slots already rely on), so sharing one
# across jobs is the round-4 sharing story, widened.
DEFAULT_MODEL_CACHE_ENTRIES = 32


def env_model_cache_entries(default: int = DEFAULT_MODEL_CACHE_ENTRIES) -> int:
    """Entry cap for the cross-job compiled-model cache — the ONE parser
    of DGREP_MODEL_CACHE (0 disables caching; malformed keeps the
    default, matching env_batch_bytes' shrug-off policy)."""
    raw = _os.environ.get("DGREP_MODEL_CACHE")
    if raw is None or raw == "":
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        return default


from collections import OrderedDict as _OrderedDict

# io_ok: holding the cache lock ACROSS engine construction is the design
# (same-pattern races collapse into one compile) — blocking under it is
# the lock's purpose, not an accident.
_model_cache_lock = _lockdep.make_lock("model-cache", io_ok=True)
_model_cache: "_OrderedDict[tuple, GrepEngine]" = _OrderedDict()
# Counters get their OWN lock: cached_engine holds _model_cache_lock across
# a whole engine construction (seconds for big literal sets), and every
# scan() stamps these counters into its stats — the stamp must never stall
# behind another thread's compile.
_model_cache_stats_lock = _lockdep.make_lock("model-cache-stats")
_model_cache_stats = {
    "compile_cache_hits": 0,
    "compile_cache_misses": 0,
    "compile_cache_evictions": 0,
}


def _count_cache(key: str, n: int = 1) -> None:
    with _model_cache_stats_lock:
        _model_cache_stats[key] += n


def model_cache_counters() -> dict:
    """Copy of the cache counters, or {} when the cache was never touched
    (so zero-activity processes never grow stats/piggyback keys)."""
    with _model_cache_stats_lock:
        if not any(_model_cache_stats.values()):
            return {}
        return dict(_model_cache_stats)


def model_cache_clear() -> None:
    """Drop every cached engine and zero the counters (tests)."""
    with _model_cache_lock:
        _model_cache.clear()
        with _model_cache_stats_lock:
            for k in _model_cache_stats:
                _model_cache_stats[k] = 0


def invalidate_cached_engine(eng: "GrepEngine") -> None:
    """Evict an engine whose compiled model changed underneath its cache
    key — the FDR retune path (ops/device_scan.swap_fdr_plan) bumps
    _model_gen when it adopts a recompiled plan, and that plan was tuned
    under ONE corpus's measured candidate rates: the next job asking for
    this pattern must start from the base pricing, not inherit another
    corpus's calibration."""
    with _model_cache_lock:
        evicted = 0
        for k in [k for k, v in _model_cache.items() if v is eng]:
            del _model_cache[k]
            evicted += 1
    if evicted:
        _count_cache("compile_cache_evictions", evicted)


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


def cached_engine(
    pattern: str | None = None,
    *,
    patterns: list[str] | None = None,
    **kw,
) -> tuple["GrepEngine", str]:
    """A (possibly shared) engine for these construction args, plus the
    cache verdict: "hit" (same object as a previous call — model compile
    AND the per-shape compile-grace bookkeeping are skipped), "miss"
    (constructed and cached), or "off" (cache disabled via
    DGREP_MODEL_CACHE=0, or the args are uncacheable — mesh engines are
    EXPLICITLY bypassed: jax.sharding.Mesh hashes by value, but equal-
    shaped meshes over different device sets would collide, and a mesh
    engine's sharded state is tied to ITS devices — always construct
    fresh; an explicit devices= LIST is bypassed for the same reason,
    while the symbolic devices="all" (the grep_tpu default) stays
    cacheable).

    Construction runs UNDER the cache lock: two workers racing the same
    pattern serialize into one compile + one hit instead of two compiles
    (the whole point in the service regime); distinct-pattern
    constructions serialize too — the accepted cost, bounded by one
    model compile."""
    cap = env_model_cache_entries()
    key: tuple | None = (
        pattern,
        _hashable(patterns) if patterns is not None else None,
        _hashable(kw),
    )
    dev = kw.get("devices")
    if kw.get("mesh") is not None or not (dev is None or isinstance(dev, str)):
        key = None
    else:
        try:
            hash(key)
        except TypeError:
            key = None
    if cap <= 0 or key is None:
        return GrepEngine(pattern, patterns=patterns, **kw), "off"
    with _model_cache_lock:
        eng = _model_cache.get(key)
        if eng is not None:
            _model_cache.move_to_end(key)
            _count_cache("compile_cache_hits")
            return eng, "hit"
        eng = GrepEngine(pattern, patterns=patterns, **kw)
        _model_cache[key] = eng
        _count_cache("compile_cache_misses")
        evicted = 0
        while len(_model_cache) > cap:
            _model_cache.popitem(last=False)
            evicted += 1
        if evicted:
            _count_cache("compile_cache_evictions", evicted)
        return eng, "miss"


@dataclass
class ScanResult:
    matched_lines: np.ndarray  # sorted 1-based line numbers (always exact)
    # EXACT matched-line count — always equals matched_lines.size, on every
    # mode/backend (unified in round 3: it used to mean end offsets on
    # exact paths, pre-confirm candidates on the filter paths, making
    # cross-mode numbers non-comparable).  Kept as a field so scan_file can
    # sum it across chunks.  Telemetry lives in engine.stats instead:
    # "candidates" (pre-confirm filter hits), "end_offsets" (exact match
    # end offsets where a path computes them).
    n_matches: int
    bytes_scanned: int


class GrepEngine:
    """Scan documents for one compiled pattern (or literal pattern set)."""

    def __init__(
        self,
        pattern: str | None = None,
        *,
        patterns: list[str] | None = None,  # multi-literal set -> Aho-Corasick
        ignore_case: bool = False,
        backend: str = "device",  # "device" (jnp/pallas) | "cpu" (host re/native)
        max_errors: int = 0,  # agrep: match within <= k edit errors
        devices: object = None,  # None = default device; "all" = every local
        # chip (segments round-robin across them); or an explicit list
        mesh: object = None,  # jax.sharding.Mesh: each segment's lanes shard
        # across the mesh and the SAME Pallas kernels run per device under
        # shard_map with a psum'd candidate count (parallel/sharded_kernels)
        mesh_axis: object = "data",
        pattern_axis: object = None,  # FDR mode on a 2D mesh: shard
        # same-plan filter banks over this axis (EP — tables are the
        # sharded operand) while lanes shard over mesh_axis
        interpret: bool = False,  # force Pallas interpret mode (CI mesh tests)
        target_lanes: int = 1024,
        segment_bytes: int = 64 * 1024 * 1024,
        max_states: int = 4096,
        max_states_per_bank: int = 1 << 16,
        device_min_bytes: int | None = None,  # inputs smaller than this
        # scan on host even on a device engine: a device round-trip is
        # latency-bound (~ms on PCIe, ~100 ms through a tunnel) while the
        # exact host scanners do sub-MB inputs in <= low ms — the grep -r
        # many-small-files regime.  None = DGREP_DEVICE_MIN_BYTES or 1 MB.
        batch_bytes: int | None = None,  # scan_batch packing window: small
        # inputs accumulate until the packed buffer reaches this size, then
        # flush as ONE dispatch (ops/layout.BatchPacker) — the cross-file
        # batching that puts the many-small-files regime back on the
        # kernels.  None = DGREP_BATCH_BYTES or 32 MB; 0 disables packing
        # (scan_batch then degrades to per-item scans).
        corpus_bytes: int | None = None,  # device corpus cache budget
        # (ops/layout.CorpusCache): scans with a content key keep their
        # packed/padded segments device-resident so a repeat query over
        # unchanged inputs skips the read/pack/upload path entirely.
        # None = DGREP_CORPUS_BYTES, else off (0) on CPU backends and
        # DEFAULT_CORPUS_BYTES_ACCEL on real accelerators; 0 disables.
    ):
        if (pattern is None) == (patterns is None):
            raise ValueError("exactly one of pattern / patterns is required")
        if max_errors and patterns is not None:
            raise ValueError("max_errors applies to a single pattern, not a set")
        self.backend = backend
        self.devices = devices
        self.mesh = mesh
        self.mesh_axis = mesh_axis
        self.pattern_axis = pattern_axis
        self._interpret = interpret
        if mesh is not None and devices is not None:
            raise ValueError("mesh and devices are mutually exclusive")
        if mesh is not None:
            # fail at construction, not inside the scan's kernel-failure
            # net (a bad axis name there would masquerade as a Mosaic
            # failure and silently demote the engine to its slow path)
            known = set(mesh.shape)
            lane_axes = (
                (mesh_axis,) if isinstance(mesh_axis, str) else tuple(mesh_axis)
            )
            if not lane_axes or not set(lane_axes) <= known:
                raise ValueError(
                    f"mesh_axis {mesh_axis!r} not in mesh axes {sorted(known)}"
                )
            if pattern_axis is not None and (
                pattern_axis not in known or pattern_axis in lane_axes
            ):
                raise ValueError(
                    f"pattern_axis {pattern_axis!r} must name a mesh axis "
                    f"outside mesh_axis {lane_axes}"
                )
        self.target_lanes = target_lanes
        self.segment_bytes = segment_bytes
        if device_min_bytes is not None:
            self.device_min_bytes = device_min_bytes
        else:
            # ONE parse (ops/layout.env_device_min_bytes), shared with the
            # map-split planner's "small file" bound — the two sides of
            # the batching contract can't drift on a malformed override
            from distributed_grep_tpu.ops.layout import env_device_min_bytes

            self.device_min_bytes = env_device_min_bytes()
        if batch_bytes is not None:
            self.batch_bytes = int(batch_bytes)
        else:
            # ONE parse for the env override, shared with the map-split
            # planner (JobConfig.effective_batch_bytes) — a stricter parse
            # here would crash worker engines on an env var the planner
            # already shrugged off
            from distributed_grep_tpu.ops.layout import env_batch_bytes

            self.batch_bytes = env_batch_bytes()
        # None = resolve lazily at scan time (_corpus_budget): the env
        # knob wins, else the backend decides — and probing the backend
        # here would import jax on CPU-only constructions
        self.corpus_bytes = (
            int(corpus_bytes) if corpus_bytes is not None else None
        )
        self.ignore_case = ignore_case
        # Shard-index inputs (distributed_grep_tpu/index): the ORIGINAL
        # query as constructed, captured BEFORE the literal-set routing
        # below rebinds pattern/patterns — requirements derive from the
        # query text via index.plan (the daemon-side planner derives from
        # the same inputs, so the two sides cannot disagree on
        # eligibility).  Resolved lazily in _index_requirements.
        self._index_query = (
            pattern,
            tuple(patterns) if patterns is not None else None,
            bool(ignore_case),
            int(max_errors),
        )
        self._index_req: object = False  # False = unresolved; None = ineligible

        self.shift_and: ShiftAndModel | None = None
        self._sa_filtered: ShiftAndModel | None = None  # rare-class device filter
        self.glushkov: GlushkovModel | None = None
        self.glushkov_exact: GlushkovModel | None = None
        self.table: DfaTable | None = None
        # Pattern sets beyond one automaton's uint16 state space compile to
        # several independent banks (Hyperscan-style ruleset sharding); each
        # bank is one extra device pass and matched lines are unioned.
        self.tables: list[DfaTable] = []
        self._dev_tables: dict | None = None  # device -> bank tables
        self._re_fallback: _re.Pattern[bytes] | None = None
        self.fdr: FdrModel | None = None
        self._fdr_dev_tables: dict | None = None  # device -> reach tables
        self._fdr_ep_dev_tables = None  # stacked pattern-axis-sharded tables
        self.pairset = None  # exact short-set model (models/pairset.py)
        self._fdr_pairset = None  # device engine for a mixed set's 1-byte
        # members (OR'd into the FDR candidate words)
        self._pairset_dev_tables: dict | None = None
        self._fdr_confirm = None  # utils/native.ConfirmSet (FDR mode only)
        self._fdr_broken = False
        self._pallas_broken = False  # any Pallas kernel failed at runtime
        # Compile-grace bookkeeping: every (kernel, layout shape) this
        # engine has COMPLETED a dispatch for.  A dispatch whose key is not
        # in here may block on a fresh XLA/Mosaic compile (~20-40 s through
        # a tunneled TPU), so it declares a grace window on the progress
        # callback first — per SHAPE, not once per process: a job over
        # differently-sized files jit-specializes per distinct tail layout
        # (round-4 review finding).  Keys are added only after the kernel
        # call returns (compile done), so concurrent scans blocked on the
        # same compile each declare their own grace.
        self._compiled_keys: set = set()
        self._model_gen = 0  # bumped when a retune swaps kernel constants
        self._accel_cached: bool | None = None  # see _accel_backend
        self._device_broken = False  # every device route failed: host-only
        self._device_demotion_permanent = False  # deterministic (non-
        # transport) failure: exempt from the DEVICE_RETRY_S un-demote
        self._device_probed = False  # first-touch responsiveness wall done
        # THREAD-LOCAL: one engine is scanned concurrently by worker slots
        # sharing the app module (grep_tpu), and a shared stash would let
        # thread A consume thread B's newline index whenever their splits
        # happen to be the same length — silently wrong nullable-at-$
        # results.  Each scan() call owns its thread's slot.
        import threading as _threading

        self._nl_local = _threading.local()
        self._nfa_filter = False  # Glushkov model is a candidate superset
        self.approx: ApproxModel | None = None
        self._approx_all_lines = False
        # Device-path observability (populated by _scan_device, empty for
        # the re/native modes): filter candidates, host confirm seconds,
        # scan wall seconds — the numbers behind the tuner's
        # max(scan, confirm) overlap model.  Read with .get().
        # Backed per-THREAD (the stats property below): concurrent worker
        # slots each see the counters of their own scan; a shared dict let
        # one slot's `self.stats = {}` reset land under another slot's
        # in-flight `+=` (crash or cross-scan telemetry bleed into the
        # retune).  Within one scan, _scan_device captures its dict once
        # so collect-pool threads mutate the owning scan's counters.
        self._stats_local = _threading.local()

        # Hyperscan-style literal decomposition: a regex that denotes a
        # finite literal set — alternations / small class products like
        # (volcano|anarchism|needle) — routes to the pattern-set engines
        # (AC banks + FDR device filter), which scan such sets faster than
        # the Glushkov NFA kernel compiled from the same regex.  Shift-and-
        # eligible patterns keep their (faster still) single-pass path, and
        # approximate matching keeps the regex form.
        self._literal_set_source: str | None = None
        sa_model = None  # compiled here, reused by the single-pattern branch
        routed_fdr: FdrModel | None = None  # probe model, reused when routed
        if pattern is not None and patterns is None and not max_errors:
            sa_model = try_compile_shift_and(pattern, ignore_case=ignore_case)
            if sa_model is None:
                lits = enumerate_literal_set(pattern, ignore_case=ignore_case)
                route = lits is not None and len(lits) >= 2
                if route and backend == "device":
                    # Only reroute when the FDR filter actually hosts the
                    # set (members >= 2 bytes, candidate rate under the
                    # ceiling): a set that falls back to the XLA DFA-bank
                    # device path would be far slower than the Glushkov
                    # NFA this regex otherwise compiles to.  The probe
                    # model is kept — the set branch reuses it.  Probe
                    # under the engine's chip-aware pricing: both the
                    # plan choice AND the round-5 native-crossover floor
                    # depend on n_chips, so a default-pricing probe
                    # would veto multi-chip-viable sets (and its model
                    # would need recompiling anyway).
                    try:
                        routed_fdr = compile_fdr(
                            lits, ignore_case=ignore_case,
                            pricing=self._fdr_base_pricing(),
                        )
                    except FdrError:
                        route = False
                if route:
                    self._literal_set_source = (
                        pattern if isinstance(pattern, str)
                        else pattern.decode("utf-8", "surrogateescape")
                    )
                    patterns = lits  # type: ignore[assignment]
                    pattern = None

        if max_errors:
            # agrep family (models/approx.py): literal/class-sequence
            # patterns only — the k-error recurrence rides the shift-and
            # symbol model.
            self.pattern = pattern
            if not 1 <= max_errors <= MAX_ERRORS:
                raise ValueError(f"max_errors must be 1..{MAX_ERRORS}")
            base = try_compile_shift_and(pattern, ignore_case=ignore_case)
            if base is None:
                raise ValueError(
                    "approximate matching needs a literal/class-sequence "
                    "pattern of <= 32 symbols (no anchors/alternation/repeats)"
                )
            if base.length <= max_errors:
                # deleting the whole pattern costs <= k edits: every line
                # (incl. empty ones) contains a match — like an empty regex
                self._approx_all_lines = True
            else:
                self.approx = try_compile_approx(
                    pattern, max_errors, ignore_case=ignore_case
                )
                assert self.approx is not None
            self.mode = "approx"
        elif patterns is not None:
            self.pattern = (
                self._literal_set_source or f"<set of {len(patterns)}>"
            )
            # Exact AC banks always exist: they are the CPU/native engine,
            # the DFA-bank device fallback, AND the host confirm oracle for
            # the FDR filter path.
            self.tables = compile_aho_corasick_banks(
                patterns, ignore_case=ignore_case,
                max_states_per_bank=max_states_per_bank,
            )
            self.table = self.tables[0]
            self.mode = "dfa"
            # Large literal sets: FDR bucketed filter (models/fdr.py) on the
            # Pallas path + exact per-line host confirm — the Hyperscan-style
            # architecture that keeps 1k..10k-pattern sets off the per-byte
            # table-gather cliff.  Literals shorter than 2 bytes can't form a
            # pair check and stay on the exact DFA banks (run additionally).
            if backend == "device":
                def _blen(p):
                    return len(p.encode("utf-8", "surrogateescape") if isinstance(p, str) else p)

                long_pats = [p for p in patterns if _blen(p) >= 2]
                short_pats = [p for p in patterns if _blen(p) < 2]
                # All members 1-2 bytes: the exact row-partition pair
                # kernel (models/pairset.py) beats BOTH alternatives —
                # FDR would filter 2-byte windows and pay a confirm
                # stream; the native route leaves the device idle.  Exact
                # on device, so it is tried first (round-4 closure of the
                # MXU question: the gather factorization wins the
                # shared-contraction formulation's ceiling).
                from distributed_grep_tpu.models.pairset import (
                    PairsetError,
                    compile_pairset,
                    expected_match_density,
                )

                if max(_blen(p) for p in patterns) <= 2:
                    # Exact kernel or not, matches are fetched O(matches)
                    # from the device: a set expected to match at ~0.1+/byte
                    # (a member like " " or "e") makes the sparse fetch the
                    # bottleneck and the device pass pointless — the same
                    # ceiling that keeps over-dense sets off the FDR filter
                    # routes these to the native host scanner.
                    dens = expected_match_density(
                        patterns, ignore_case=ignore_case
                    )
                    if dens > FP_CEILING_PER_BYTE:
                        log.warning(
                            "short set expected match density %.3g/byte is "
                            "over the device ceiling %.2g -> native host "
                            "scanner", dens, FP_CEILING_PER_BYTE,
                        )
                    else:
                        try:
                            self.pairset = compile_pairset(
                                patterns, ignore_case=ignore_case
                            )
                            self.mode = "pairset"
                        except PairsetError as e:
                            log.info(
                                "short set not pairset-representable: %s", e
                            )
                if self.mode != "pairset" and long_pats:
                    try:
                        # Chip-aware pricing (VERDICT r3 item 1): the host
                        # confirm threads are shared across every chip this
                        # engine drives, so the tuner prices the confirm leg
                        # at the per-chip share from the start (the routed
                        # decomposition probe above used the same pricing).
                        if short_pats:
                            # A dense 1-byte member ("e", " ") defeats the
                            # filter architecture outright: the pairset
                            # sidecar would turn every occurrence into a
                            # device-reported candidate, so the collect
                            # path's O(candidates) coordinate fetch +
                            # confirm stream swamps the scan it was meant
                            # to hide behind.  Same ceiling as the FDR
                            # plan's own candidate-rate gate; the whole set
                            # then routes loudly to the native scanner
                            # below (the retune that might notice later is
                            # disabled for mixed sets by design).
                            short_dens = expected_match_density(
                                short_pats, ignore_case=ignore_case
                            )
                            if short_dens > FP_CEILING_PER_BYTE:
                                raise FdrError(
                                    f"mixed set's 1-byte members expect "
                                    f"{short_dens:.3g} matches/byte — over "
                                    f"the {FP_CEILING_PER_BYTE:.2g} device "
                                    f"candidate ceiling"
                                )
                        base_pricing = self._fdr_base_pricing()
                        # routed_fdr was probed under the same base
                        # pricing (chip count included) — reuse it as-is
                        self.fdr = routed_fdr or compile_fdr(
                            long_pats, ignore_case=ignore_case,
                            pricing=base_pricing,
                        )
                        confirm_pats = [
                            p for b in self.fdr.banks for p in b.patterns
                        ]
                        if short_pats:
                            # 1-byte members ride the exact pairset kernel
                            # ON DEVICE (a 1-byte set always factorizes:
                            # its columns are all-True, so rows collapse
                            # to <= 2 classes), OR'd into the FDR candidate
                            # words — the old per-segment host AC scan ran
                            # ~40x the device leg ON THE DISPATCH THREAD
                            # (0.2 s vs 5 ms per 64 MB segment); without
                            # a kernel backend the engine's DFA-bank/native
                            # fallback already covers the whole set.
                            self._fdr_pairset = compile_pairset(
                                short_pats, ignore_case=ignore_case
                            )
                            confirm_pats = (
                                confirm_pats + self._fdr_pairset.patterns
                            )
                        # Exact candidate confirm: bloom-filtered suffix
                        # probe + memcmp over the normalized members (native
                        # when available, ~4 ns/candidate —
                        # utils/native.ConfirmSet).  Runs
                        # per segment inside collect(), overlapped with the
                        # next segment's device scan — which is why the FDR
                        # tuner prices candidates at max(scan, confirm)
                        # rather than their sum (models/fdr.py).  Includes
                        # the short members, so the OR'd pairset matches
                        # confirm instead of being rejected.
                        from distributed_grep_tpu.utils.native import ConfirmSet

                        self._fdr_confirm = ConfirmSet(
                            confirm_pats, ignore_case=ignore_case,
                        )
                        self.mode = "fdr"
                        # Self-calibration stage 1 (VERDICT r2 item 3): a
                        # ~ms single-thread ConfirmSet probe at init catches
                        # order-of-magnitude mispricing (e.g. the Python-
                        # fallback confirm without the native lib) and
                        # recompiles the filter plan under measured pricing.
                        self._fdr_pats = long_pats
                        self._calibrate_fdr_confirm()
                    except FdrError as e:
                        log.info("pattern set FDR-ineligible: %s", e)
                # FDR-ineligible sets (density over the candidate ceiling,
                # short sets past the pairset class budget) must not
                # silently fall onto the XLA DFA-bank device path
                # (~0.1 GB/s — ~100x slower than the host's native MT
                # scanner).  Route to the native scanner loudly; keep the
                # device path only when the native lib is unavailable.
                if self.mode == "dfa":
                    self._route_native(
                        "pattern set ineligible for the FDR device filter"
                    )
        else:
            self.pattern = pattern
            try:
                self.table = compile_dfa(pattern, ignore_case=ignore_case, max_states=max_states)
                self.tables = [self.table]
                self.shift_and = sa_model
                if self.shift_and is not None:
                    self.mode = "shift_and"
                    # Rare-class device filter: check only the pattern's
                    # rarest byte-classes on device (fewer compares, the
                    # kernel's ALU bottleneck) — the span confirm pass
                    # already restores exact lines.  Disabled mid-scan if a
                    # corpus defeats the byte prior (see collect()).
                    self._sa_filtered = filtered_for_device(self.shift_and)
                else:
                    # compile_scan_model may return a bounded-repeat-relaxed
                    # FILTER automaton (fewer state words — models/nfa.py);
                    # its candidate lines then get the host confirm pass.
                    self.glushkov, self._nfa_filter = compile_scan_model(
                        pattern, ignore_case=ignore_case
                    )
                    if self._nfa_filter:
                        # exact automaton (may be None if over the position
                        # cap): the mid-scan fallback when a corpus defeats
                        # the relaxed filter's selectivity
                        from distributed_grep_tpu.models.nfa import (
                            try_compile_glushkov,
                        )

                        self.glushkov_exact = try_compile_glushkov(
                            pattern, ignore_case=ignore_case
                        )
                    else:
                        self.glushkov_exact = self.glushkov
                    self.mode = "nfa" if self.glushkov is not None else "dfa"
            except RegexError as e:
                # Outside the device subset (newline-consuming, state blowup,
                # unsupported syntax): host re fallback, like the reference.
                log.info("pattern %r -> host re fallback (%s)", pattern, e)
                flags = _re.IGNORECASE if ignore_case else 0
                from distributed_grep_tpu.models.dfa import (
                    expand_posix_classes,
                )

                # POSIX classes must expand before re sees them (re
                # misparses [[:digit:]]); matters for e.g. a \b pattern
                # whose body uses them — the rescue confirms candidate
                # lines with this matcher
                self._re_fallback = _re.compile(
                    expand_posix_classes(
                        pattern.encode("utf-8", "surrogateescape")
                        if isinstance(pattern, str) else bytes(pattern)
                    ), flags
                )
                self.mode = "re"
                if backend == "device":
                    # Rescue: a bounded repeat past the DFA expansion cap
                    # (e.g. {4,1000}) still compiles as a relaxed Glushkov
                    # FILTER (models/nfa.compile_scan_model widens {m,n}
                    # before building) — run it on device and confirm
                    # candidate lines with the exact re fallback (there is
                    # no DFA table to confirm with).  Without a Pallas
                    # backend the scan falls back to the per-line re loop.
                    try:
                        filt, is_filter = compile_scan_model(
                            pattern, ignore_case=ignore_case
                        )
                    except RegexError:
                        filt = None
                    if filt is None:
                        # \b/\B word boundaries (round 5): no exact
                        # automaton form at all, but the device filter
                        # strips zero-width assertions (language superset
                        # at the same end offsets) — '\berror\b' then
                        # scans as 'error' on the Pallas kernel and every
                        # candidate line is re-confirmed below, the same
                        # contract as the expansion-cap rescue.
                        from distributed_grep_tpu.models.nfa import (
                            compile_device_filter,
                        )

                        filt = compile_device_filter(
                            pattern, ignore_case=ignore_case
                        )
                    if filt is not None:
                        log.info(
                            "pattern %r rescued onto the device NFA filter "
                            "(%d positions, re-confirmed lines)",
                            pattern, filt.n_pos,
                        )
                        self.glushkov = filt
                        self.glushkov_exact = None
                        # always confirm: with no DFA oracle, even an
                        # "exact" Glushkov's stripe-boundary behavior is
                        # re-checked per line
                        self._nfa_filter = True
                        self.mode = "nfa"
        if self.mode == "dfa" and backend == "device" and self.tables:
            # Single patterns the bit-parallel kernels can't host exactly
            # ('$' accepts, > 128 Glushkov positions — e.g. a 200-char
            # literal) would otherwise run the per-byte XLA DFA device
            # path at ~0.1 GB/s.  First choice (round-5): a Glushkov
            # FILTER with the '$' dropped / body prefix-truncated
            # (models/nfa.compile_device_filter) — a candidate superset at
            # line granularity riding the same Pallas NFA kernel +
            # cand_words host-confirm contract as the relaxed-repeat
            # filters, which keeps everyday patterns like 'error$' on the
            # TPU.  Applies to mesh/interpret engines too (it IS the
            # device path — CI kernel coverage and the sharded step both
            # exercise it).  Only when no filter compiles: the native host
            # scanner (~3-25x the XLA DFA path) — same loud routing as
            # FDR-ineligible sets above, still excluding mesh/interpret
            # engines, which exist to run the device path.
            from distributed_grep_tpu.models.nfa import compile_device_filter

            filt = compile_device_filter(self.pattern, ignore_case=ignore_case)
            if filt is not None:
                log.info(
                    "pattern %r outside the exact device kernel subset -> "
                    "device NFA filter (%d positions; '$' dropped / prefix-"
                    "truncated), host-confirmed lines",
                    self.pattern, filt.n_pos,
                )
                self.glushkov = filt
                self.glushkov_exact = None  # no exact automaton exists here
                self._nfa_filter = True  # every candidate line is confirmed
                self.mode = "nfa"
            elif self.mesh is None and not self._interpret:
                self._route_native(
                    f"pattern {self.pattern!r} outside the device kernel "
                    f"subset"
                )
        if backend == "cpu" and self.mode != "re":
            self.mode = "native"  # host C scanner, same tables

    def _route_native(self, why: str) -> bool:
        """Loud device->host demotion (one policy, three callers: the
        FDR-ineligible set branch, the single-pattern device-subset
        branch, the FDR retune rejection): the native scanners give the
        exact same answers off the AC/DFA tables at ~3-100x the XLA DFA
        device path's ~0.1 GB/s.  No-op when the native lib is missing —
        the device path, slow as it is, beats a Python table walk."""
        from distributed_grep_tpu.utils.native import native_available

        if not native_available():
            return False
        log.warning(
            "%s -> native host scanner (the XLA DFA device path would "
            "run ~100x slower)", why,
        )
        self.mode = "native"
        return True

    # ------------------------------------------------- FDR self-calibration
    def _active_chip_count(self) -> int:
        """Chips whose scan streams share this host's confirm threads.

        Mesh mode: every device in the lane axes (plus the EP pattern axis
        when set — EP divides per-chip gather cost, so the scan leg scales
        with the full product) scans concurrently against ONE host confirm
        stream.  devices="all": every local chip round-robins segments.
        The reference's analogue is the per-worker fan-out cost model
        (coordinator.go:329-333) — one coordinator, many scanning workers."""
        if self.mesh is not None:
            axes = (
                (self.mesh_axis,) if isinstance(self.mesh_axis, str)
                else tuple(self.mesh_axis)
            )
            if self.pattern_axis is not None:
                axes = axes + (self.pattern_axis,)
            n = 1
            for a in axes:
                n *= int(self.mesh.shape[a])
            return n
        if self.devices == "all":
            # jax.local_devices() initializes the backend on first touch
            # and hangs in C (no exception) on a black-holed transport;
            # this path runs at CONSTRUCTION time (chip-aware FDR
            # pricing), so gate it behind the shared time-boxed deep
            # probe instead of calling it bare — after a healthy probe
            # local_devices() answers from jax's client cache.  On a
            # dead transport price at 1 chip; the scan-time wall and
            # retry-window un-demote own the rest of the story.
            if not self._device_responsive():
                return 1
            try:
                import jax

                return max(1, len(jax.local_devices()))
            except Exception:  # noqa: BLE001 — no backend: single stream
                return 1
        if self.devices:
            return max(1, len(list(self.devices)))
        return 1

    def _fdr_base_pricing(self):
        """default_pricing() with this engine's active chip count."""
        from dataclasses import replace as _replace

        from distributed_grep_tpu.models.fdr import default_pricing

        pricing = default_pricing()
        n_chips = self._active_chip_count()
        if n_chips > 1:
            pricing = _replace(pricing, n_chips=n_chips)
        return pricing

    # ---------------------------------------------- FDR self-calibration
    # (implementation in ops/device_scan.py — split out round 5; thin
    # delegates keep the engine surface and test hooks unchanged)
    def _calibrate_fdr_confirm(self) -> None:
        from distributed_grep_tpu.ops.device_scan import calibrate_fdr_confirm

        calibrate_fdr_confirm(self)

    def _swap_fdr_plan(self, pricing, reason: str) -> None:
        from distributed_grep_tpu.ops.device_scan import swap_fdr_plan

        swap_fdr_plan(self, pricing, reason)

    def _maybe_retune_fdr(self, n_bytes: int) -> None:
        from distributed_grep_tpu.ops.device_scan import maybe_retune_fdr

        maybe_retune_fdr(self, n_bytes)

    # ------------------------------------------------------------------ scan
    @property
    def stats(self) -> dict:
        """Per-thread scan counters: the thread that ran scan() reads its
        own scan's numbers (bench/CLI/retune all read from the scanning
        thread), and concurrent worker slots cannot clobber each other."""
        d = getattr(self._stats_local, "d", None)
        if d is None:
            d = {}
            self._stats_local.d = d
        return d

    @stats.setter
    def stats(self, value: dict) -> None:
        self._stats_local.d = value

    def _kernel_backend_ok(self) -> bool:
        """One gate for "a Pallas kernel can actually run here": a backend
        exists (real TPU, or interpret mode in CI) and no kernel has failed
        at runtime this engine.  Shared by every routing branch so the
        gates cannot silently diverge."""
        from distributed_grep_tpu.ops import pallas_scan

        return (
            pallas_scan.available() or self._interpret
        ) and not self._pallas_broken

    def scan(self, data: bytes, progress=None, corpus_key=None) -> ScanResult:
        """Scan one in-memory document.  ``progress`` (optional callable,
        called as ``progress()`` at work milestones and
        ``progress(grace_s=N)`` ahead of a possible silent compile) is how
        a runtime failure detector keeps a tight liveness window over long
        scans (runtime/worker.py wires it to the heartbeat RPC).

        ``corpus_key`` (ops/layout.CorpusKey, derived from a FRESH stat of
        the input's backing file(s)) opts this scan into the device corpus
        cache: the packed/padded segments stay HBM-resident under the key
        and a repeat scan of the same content skips the pack + upload
        path.  The caller asserts ``data`` IS the bytes the key stats
        describe — scan_file/scan_batch derive key and bytes together."""
        self._nl_local.stash = None
        # Span-pipeline telemetry (utils/spans.py): each scan becomes one
        # structured per-scan record — mode, bytes, duration, and the
        # engine.stats counters (candidates, confirm seconds, fallback
        # flags) that previously died with the process.  active() is one
        # thread-local read when the pipeline is off.
        t0 = _time_mod.perf_counter() if spans_mod.active() else None
        res = self._scan_impl(data, progress, corpus_key=corpus_key)
        # Nullable-at-'$' patterns (accept_eol at the line-start state,
        # e.g. '^$', '^ *$', 'x?$'): the empty match is valid at every
        # line's EOL — including EMPTY lines, which contain no byte for
        # the byte-level scanners to report on.  Scans attribute the
        # empty-line match to the '\n' PRECEDING the line, so they miss an
        # empty line at offset 0, and their padded trailing '\n'
        # symmetrically manufactures a match for a line that does not
        # exist when the data ends at a newline.  Post-processing owns
        # both edges for every backend: union in the empty lines, drop
        # anything past the last real line.  (Found by the round-4 wide
        # fuzz sweep, seed 3116.)
        if self.tables and any(bool(t.accept_eol[t.start]) for t in self.tables):
            stash = getattr(self._nl_local, "stash", None)
            nl = (
                stash[1] if stash is not None and stash[0] == len(data)
                # chunked scans stash per-piece indexes (wrong length) —
                # recompute over the full buffer then
                else lines_mod.newline_index(data)
            )
            n_lines = nl.size + (0 if not data or data.endswith(b"\n") else 1)
            ml = res.matched_lines[res.matched_lines <= n_lines]
            ml = np.union1d(ml, lines_mod.empty_line_numbers(data, nl))
            res = ScanResult(
                ml.astype(np.int64), int(ml.size), res.bytes_scanned
            )
        cc = model_cache_counters()
        if cc:
            # cross-job model-cache telemetry rides engine.stats (and from
            # there the scan_record piggyback readers): stamped only when
            # the cache has ever been touched, so cache-free processes
            # keep their exact stats shape
            self.stats.update(cc)
        from distributed_grep_tpu.ops.layout import corpus_cache_counters

        ccorp = corpus_cache_counters()
        if ccorp:
            # same contract for the device corpus cache (hits/misses/
            # evictions + the bytes_resident gauge): nonzero-only
            self.stats.update(ccorp)
        import sys as _sys

        idx_mod = _sys.modules.get("distributed_grep_tpu.index.summary")
        if idx_mod is not None:
            # shard-index telemetry (index_shards_pruned/bytes_skipped/
            # maybe_scans/summaries_built), nonzero-only: sys.modules-
            # gated so index-free processes never import the tier just
            # to report nothing
            self.stats.update(idx_mod.index_counters())
        fol_mod = _sys.modules.get("distributed_grep_tpu.runtime.follow")
        if fol_mod is not None:
            # streaming-tier telemetry (follow_wakes/suffix_bytes_scanned/
            # stream_dropped_records), same nonzero-only sys.modules-gated
            # contract — rides engine.stats onto the heartbeat piggyback
            self.stats.update(fol_mod.follow_counters())
            # fused follow tier (round 21): follow_fused_queries/wakes/
            # suffix_bytes_saved — separate dict so the =0 no-op holds
            self.stats.update(fol_mod.follow_fused_counters())
        if t0 is not None:
            # after the EOL fix-up: the record's match count must equal the
            # ScanResult the caller actually receives
            spans_mod.scan_record(
                mode=self.mode, n_bytes=len(data),
                seconds=_time_mod.perf_counter() - t0,
                stats=self.stats, matches=res.n_matches,
            )
        return res

    def _scan_impl(self, data: bytes, progress=None, corpus_key=None) -> ScanResult:
        if self.mode == "re":
            return self._host_scan(self._scan_re, data, progress)
        if self._approx_all_lines or (
            self.tables and any(t.accept[t.start] for t in self.tables)
        ):
            # Pattern matches the empty string -> every line matches (grep
            # semantics); also sidesteps empty-match bookkeeping on device.
            n_lines = lines_mod.count_lines(data)
            return ScanResult(np.arange(1, n_lines + 1, dtype=np.int64), n_lines, len(data))
        if self.mode == "native":
            return self._host_scan(self._scan_native, data, progress)
        # The first-touch responsiveness wall runs BEFORE any branch that
        # touches jax (_kernel_backend_ok/_accel_backend included): a
        # wedged transport hangs the first jax call in C with no
        # exception, wherever it happens (round-4 review finding).
        if (
            self._device_broken
            and not self._device_demotion_permanent  # deterministic per-
            # pattern failures don't heal with the transport
            and DEVICE_RETRY_S > 0
            and not self._interpret
            and self._device_responsive()  # shared verdict: deep-probes a
            # False verdict at most once per window PROCESS-wide, else
            # answers from the cache instantly
        ):
            # The device came back: un-demote.  The kernel-level flags
            # reset too — their failures were co-temporal with the
            # outage; a genuinely broken kernel re-flags within one scan.
            log.warning(
                "device backend responsive again -> leaving host-degraded "
                "mode (retry window %.0fs)", DEVICE_RETRY_S,
            )
            spans_mod.instant("device_recovered", cat="engine",
                              retry_window_s=DEVICE_RETRY_S)
            self._device_broken = False
            self._pallas_broken = False
            self._fdr_broken = False
        if (
            not self._device_probed
            and not self._device_broken
            and self._host_scanner() is not None
        ):
            if not self._device_responsive():
                log.warning(
                    "device backend unresponsive after %.0fs -> exact "
                    "host engines for this engine", DEVICE_PROBE_S,
                )
                self._mark_device_broken()
            # AFTER the verdict: a concurrent scan that reads this flag
            # early just re-enters _device_responsive and waits on the
            # probe lock for the shared verdict
            self._device_probed = True
        if self._device_broken:
            scanner = self._host_scanner()
            if scanner is None:  # device dead AND no host route: fail fast
                raise RuntimeError(
                    "device backend is broken and no exact host engine "
                    "exists for this pattern"
                )
            res = self._host_scan(scanner, data, progress)
            self.stats["device_fallback"] = True  # degraded-mode marker
            return res
        if self.mode == "pairset" and not self._kernel_backend_ok():
            # no kernel backend: the exact AC banks are the same
            # answer on host (native MT scanner when available)
            return self._host_scan(self._scan_native, data, progress)
        if self.mode == "nfa" and not self.tables:
            # DFA-less rescue (expansion-cap bounded repeats): the only
            # device engine is the Pallas NFA filter — without it (no TPU,
            # over budget, broken at runtime) there are no DFA banks to
            # fall back on, so the scan is the per-line re loop, like the
            # un-rescued mode.
            from distributed_grep_tpu.ops import pallas_nfa

            if not (
                self._kernel_backend_ok()
                and pallas_nfa.eligible(self.glushkov)
            ):
                return self._host_scan(self._scan_re, data, progress)
        if self._small_for_device(len(data)):
            # Host OR pending-batch (round 6): a sub-threshold input that
            # arrives through plain scan() takes the exact host engines —
            # round-trip-latency-bound on a real accelerator (~ms over
            # PCIe, ~100 ms through a tunnel) while native memmem / AC-DFA
            # banks, or the re loop for the DFA-less NFA rescue, finish in
            # <= low ms.  The same input arriving through scan_batch()
            # instead JOINS a pending packed batch (ops/layout.BatchPacker)
            # and reaches the kernels as part of one amortized dispatch —
            # "host always" is no longer the only small-input story.
            # XLA-on-CPU "devices" are not gated (dispatch is ~µs there,
            # and the CI suite's device-path coverage runs on them).
            res = self._host_scan(self._host_scanner(), data, progress)
            self.stats["small_host_scan"] = True  # AFTER: scanners reset stats
            return res
        return self._scan_device(data, progress=progress, corpus_key=corpus_key)

    def _small_for_device(self, n_bytes: int) -> bool:
        """True when a PLAIN scan() of this size should reroute to the
        exact host engines rather than pay its own device dispatch.
        scan_batch's pack-vs-solo split uses the size threshold alone:
        packing amortizes dispatch overhead on every backend (interpret
        engines and XLA-on-CPU included), so it is not gated on
        _accel_backend the way the solo-host reroute is.  Probes the
        backend (resolving _accel_cached) — callers run AFTER the
        responsiveness wall; pre-wall callers use _small_route_cached."""
        return self._accel_backend() and self._small_route_cached(n_bytes)

    def _small_route_cached(self, n_bytes: int) -> bool:
        """_small_for_device's verdict WITHOUT the backend probe: reads
        the cached _accel_backend answer only, so it is safe BEFORE the
        responsiveness wall (an unresolved flag reads False — for the
        corpus-cache opt-in that only costs one uncached scan, never a
        wrong answer or a hang)."""
        return (
            n_bytes < self.device_min_bytes
            and not self._interpret  # CI interpret engines exist to
            # exercise the kernels — never reroute them
            and self.mesh is None  # a mesh engine EXISTS to run the
            # sharded path (and dryrun_multichip asserts its psum
            # telemetry on tiny shapes — driver contract)
            and self.mode != "approx"  # the host approx oracle is a ~MB/s
            # Python recurrence; the device wins at any size
            and self._host_scanner() is not None
            and bool(self._accel_cached)
        )

    def _device_responsive(self) -> bool:
        """Shared device verdict (see _device_probe_state): probes on
        first use, and re-probes a False verdict at most once per
        DEVICE_RETRY_S window — outages are usually transient, and the
        deep probe is what can actually observe both the wedge and the
        recovery.  Interpret engines skip the wall: their CPU backend
        cannot wedge."""
        if self._interpret:
            return True
        with _device_probe_lock:
            v = _device_probe_state["verdict"]
            stale = (
                v is False
                and DEVICE_RETRY_S > 0
                and _time_mod.monotonic() - _device_probe_state["at"]
                >= DEVICE_RETRY_S
            )
            if v is None or stale:
                v = _probe_device_blocking()
                _device_probe_state.update(
                    verdict=v, at=_time_mod.monotonic()
                )
            return v

    def _mark_device_broken(self, transport_evidence: bool = True) -> None:
        """Demote this engine to its exact host scanners.

        ``transport_evidence=True`` (stall wall, failed first-touch probe)
        additionally reports process-wide sickness — those failures can
        only come from the device transport, so every engine's next probe
        should be the deep retry — and leaves the demotion eligible for
        the DEVICE_RETRY_S un-demote when the transport heals.  A generic
        exhausted-routes failure (``False``) may be a deterministic
        per-pattern defect on a HEALTHY device: it keeps the old permanent
        per-engine demotion and must not poison the shared verdict (a
        poisoned verdict would demote unrelated engines, then flip-flop
        every retry window: deep probe succeeds, this engine un-demotes,
        fails deterministically again, re-poisons — round-4 review)."""
        self._device_broken = True
        spans_mod.instant("device_demoted", cat="engine",
                          transport_evidence=bool(transport_evidence))
        if transport_evidence:
            _report_device_sick()  # process-wide: starts the shared retry window
        else:
            self._device_demotion_permanent = True

    def _host_scanner(self):
        """The exact host engine for this pattern, or None if no host
        route exists (today every engine that reaches _scan_device has
        one — approx mode sets self.approx, sets compile AC banks,
        single patterns set tables or _re_fallback — but callers guard
        on this return rather than re-encoding that knowledge): the
        native scanners when tables exist (AC/DFA banks, memmem for
        literals) or the approx host recurrence; the re loop for the
        DFA-less NFA rescue."""
        if self.tables or self.approx is not None:
            return self._scan_native
        if self._re_fallback is not None:
            return self._scan_re
        return None

    def _accel_backend(self) -> bool:
        """True when jax's default backend is a real accelerator (tpu /
        tunneled tpu / gpu) — the regime where per-scan dispatch latency,
        not throughput, prices small inputs.  Cached: the answer cannot
        change within a process."""
        cached = self._accel_cached
        if cached is None:
            try:
                import jax

                cached = jax.default_backend() != "cpu"
            except Exception:  # noqa: BLE001 — no jax: nothing to gate
                cached = False
            self._accel_cached = cached
        return cached

    def _corpus_budget(self) -> int:
        """Effective device-corpus-cache byte budget for this engine's
        scans (0 = caching off).  Resolution order: the explicit
        ``corpus_bytes=`` construction arg, the DGREP_CORPUS_BYTES env
        knob (ONE parse, ops/layout.env_corpus_bytes), then the backend
        default — OFF on CPU (CI and plain host runs keep their exact
        pre-cache behavior), DEFAULT_CORPUS_BYTES_ACCEL on real
        accelerators (the service regime the cache exists for).  Mesh
        engines and explicit devices= LISTS always answer 0: resident
        segments are committed to specific devices, so sharing them
        across engines pinned to different sets would defeat the
        caller's pinning — the same bypass verdict as the model cache
        (the symbolic devices="all", the grep_tpu default, stays
        cacheable: every engine resolves it to the same local set and
        the round-robin device assignment is deterministic)."""
        if self.mesh is not None:
            return 0
        if self.devices is not None and not isinstance(self.devices, str):
            return 0
        if self.corpus_bytes is not None:
            return max(0, self.corpus_bytes)
        from distributed_grep_tpu.ops.layout import (
            DEFAULT_CORPUS_BYTES_ACCEL,
            env_corpus_bytes,
        )

        env = env_corpus_bytes()
        if env is not None:
            return env
        return DEFAULT_CORPUS_BYTES_ACCEL if self._accel_backend() else 0

    def _corpus_opt_in(self) -> bool:
        """Cheap, jax-FREE opt-in check for the corpus-cache paths that
        run at scan_file/scan_batch ENTRY — i.e. before the
        responsiveness wall and on engines (mode "re"/"native") that
        never touch jax at all.  The explicit arg and the env knob
        answer directly; the backend-default leg answers True only when
        a previous scan ALREADY probed the backend as an accelerator
        (_accel_cached) — it never probes itself, so a black-holed
        tunnel cannot hang the entry path (the round-4 wall invariant)
        and host-only engines keep their zero-jax contract.  Cost: the
        first scan of an accelerator process runs uncached (the cache
        is empty then anyway); the second threads keys and populates.
        _corpus_budget() stays the authoritative resolution — called
        from ops/device_scan, past the wall."""
        if self.mesh is not None or (
            self.devices is not None and not isinstance(self.devices, str)
        ):
            return False
        if self.corpus_bytes is not None:
            return self.corpus_bytes > 0
        from distributed_grep_tpu.ops.layout import env_corpus_bytes

        env = env_corpus_bytes()
        if env is not None:
            return env > 0
        return bool(self._accel_cached)

    # ------------------------------------------------------- shard index
    def _index_requirements(self):
        """This query's required-literal trigram requirements
        (index.plan.QueryRequirements), or None — index off
        (DGREP_INDEX=0) or ineligible (empty-match patterns, members
        under 3 bytes, approx mode, patterns outside the parser subset).
        The derivation is resolved once per engine; the env switch is
        re-read per call so the kill-switch works on cached engines.
        jax-free (index + models/dfa are numpy-only): safe at the
        scan_file/scan_batch entries, before the responsiveness wall."""
        from distributed_grep_tpu.index import summary as index_summary

        if not index_summary.env_index_enabled():
            return None
        if self._index_req is False:
            from distributed_grep_tpu.index import plan as index_plan

            pat, pats, ic, me = self._index_query
            try:
                self._index_req = index_plan.requirements_for_query(
                    pattern=pat,
                    patterns=list(pats) if pats is not None else None,
                    ignore_case=ic, max_errors=me,
                )
            except Exception:  # noqa: BLE001 — derivation must never
                # break a scan: ineligible just means "scan everything"
                self._index_req = None
        return self._index_req

    def _index_publish_enabled(self) -> bool:
        """Whether this scan should BUILD summaries at all: only when a
        reuse surface exists — the persistent store is attached (the
        service threads <work_root>/index through the app) or the corpus
        cache is opted in (the in-process warm-query regime).  A one-shot
        CLI job has neither: building summaries its process will never
        consult would tax every cold local run for nothing.  Lookups and
        prunes stay ungated — they only fire when summaries already
        exist."""
        from distributed_grep_tpu.index import summary as index_summary

        return (
            index_summary.attached_store() is not None
            or self._corpus_opt_in()
        )

    def _index_pruned(self, key) -> "ScanResult":
        """Stamp one engine-side prune (counters + span instant + this
        thread's stats) and return the exact empty result — the summary
        proved no line of the shard can match, so "zero matched lines"
        is the true answer, for every caller semantics."""
        from distributed_grep_tpu.index import summary as index_summary

        index_summary.record_prune(key.n_bytes)
        spans_mod.instant("index:prune", cat="engine", bytes=key.n_bytes)
        self.stats = {}
        self.stats.update(index_summary.index_counters())
        return ScanResult(np.zeros(0, dtype=np.int64), 0, 0)

    def _index_publish(self, key, data: bytes) -> None:
        """Publish ``data``'s summary under ``key`` (memory + attached
        store) and mirror it onto the corpus-cache entry when one is
        resident.  Called AFTER the scan over ``data`` succeeded — the
        CorpusCache publish discipline — from the already-resident host
        bytes, so the build never sits on the cold read path."""
        from distributed_grep_tpu.index import summary as index_summary

        s = index_summary.publish_summary(key, data)
        if s is not None:
            from distributed_grep_tpu.ops.layout import corpus_cache

            corpus_cache().attach_summary(key, s)

    # A host-routed scan of a large in-memory split proceeds in
    # newline-aligned pieces with a progress stamp between pieces — the
    # same per-chunk exactness scan_file relies on (every engine mode is
    # exact over a chunk that starts at a line start), and what keeps a
    # tight failure-detector window honest over maps the device never
    # sees (native MT / re fallback routes, round-4 review finding: these
    # paths previously emitted no heartbeats at all, so a multi-GB
    # whole-bytes map was swept and re-executed forever).
    _HOST_CHUNK = 1 << 26

    def _host_scan(self, scanner, data: bytes, progress=None) -> ScanResult:
        if progress is None or len(data) <= int(1.5 * self._HOST_CHUNK):
            res = scanner(data)
            if progress is not None:
                progress()
            return res
        matched: list = []
        n_matches = 0
        end_offsets = 0
        lines_before = 0
        pos = 0
        while pos < len(data):
            end = min(pos + self._HOST_CHUNK, len(data))
            if end < len(data):
                cut = data.rfind(b"\n", pos, end)
                if cut >= pos:
                    end = cut + 1
                else:  # one line longer than the chunk: extend to its end
                    nxt = data.find(b"\n", end)
                    end = len(data) if nxt < 0 else nxt + 1
            piece = data[pos:end]
            res = scanner(piece)
            if res.matched_lines.size:
                matched.append(res.matched_lines + lines_before)
            n_matches += res.n_matches
            end_offsets += int(self.stats.get("end_offsets", 0))
            lines_before += lines_mod.count_lines(piece)
            pos = end
            progress()
        ml = (
            np.concatenate(matched) if matched else np.zeros(0, dtype=np.int64)
        )
        self.stats = {"end_offsets": end_offsets}
        return ScanResult(ml, n_matches, len(data))

    def _reader_pool(self):
        """The calling thread's persistent one-slot read-ahead pool —
        PROCESS-wide per scanning thread (see _thread_reader_pool), so
        constructing engines in a loop (fuzz sweeps, a worker
        reconfiguring per job) reuses one reader instead of accumulating
        pools, and concurrent worker slots never queue reads behind each
        other."""
        return _thread_reader_pool()

    def scan_file(self, path, chunk_bytes: int | None = None, emit=None,
                  progress=None, stop_after_match: bool = False,
                  stop=None, emit_chunk=None) -> ScanResult:
        """Stream a file of any size through the scanner: chunks are cut at
        newline boundaries (partial tail lines carry into the next chunk),
        so no line — and hence no grep match — ever spans a chunk, and host
        memory stays bounded by one chunk regardless of file size.  The
        reference reads whole files and cannot exceed worker RAM
        (worker.go:72-76); this is the end-to-end long-context path
        (SURVEY.md §5).

        ``emit(line_no, line_bytes)`` is called per matched line while the
        chunk is still in memory — collecting output costs O(matches), not
        a second pass.  Line numbers in the result are file-global.  A
        single line longer than chunk_bytes is accumulated whole (a line
        must fit in memory; grep semantics need the full line anyway).

        ``emit_chunk(lines_before, buf, matched_lines, nl_index)`` is the
        columnar alternative (round 5): called once per chunk that has
        matches, with the chunk-LOCAL 1-based matched line numbers and
        the chunk's newline index — the grep apps build one LineBatch per
        chunk from it (runtime/columnar.py) instead of paying a Python
        callback per matched line.

        Disk reads are pipelined (VERDICT r3 item 4): a one-slot reader
        thread fetches chunk i+1 while chunk i scans — the same shape as
        the device-feed double-buffer, one level up — so a disk-bound
        corpus pays max(read, scan) per chunk instead of their sum.
        Residual stall is recorded in stats["read_wait_seconds"] (~0 when
        the scan hides the read); host memory stays bounded by TWO chunks.

        ``stop_after_match=True`` stops reading after the first chunk that
        contains any matched line (GNU grep -q/-l stop at the first match;
        chunk granularity keeps the exactness machinery untouched).  The
        result then reports only the lines seen so far — presence, not a
        total count.  ``stop`` generalizes it: a zero-arg callable checked
        after each chunk's emits — return True to end the stream (callers
        whose emit applies a further filter, e.g. the -w/-x confirm,
        decide presence themselves).
        """
        import time as _time

        chunk_target = chunk_bytes or max(self.segment_bytes, 1 << 26)
        matched: list[int] = []
        n_matches = 0
        total = 0
        end_offsets = 0  # summed across chunks (per-chunk stats reset)
        read_wait = 0.0
        lines_before = 0
        carry = b""

        def scan_piece(buf: bytes, key=None) -> None:
            """One newline-bounded piece through scan(): match collection,
            per-line / columnar emit, file-global line accounting — shared
            by the streamed loop below and the corpus-cache warm path."""
            nonlocal n_matches, total, end_offsets, lines_before
            res = self.scan(buf, progress=progress, corpus_key=key)
            total += len(buf)
            n_matches += res.n_matches
            end_offsets += self.stats.get("end_offsets", 0)
            # scan() clears the thread's nl stash at entry and the host
            # scan modes re-stash this buffer's index — a length-matching
            # stash is therefore THIS scan's, never a stale collision;
            # reuse it instead of a second full newline pass over the
            # chunk (round 8: emit AND line accounting both need it)
            stash = getattr(self._nl_local, "stash", None)
            nl_idx = (
                stash[1] if stash is not None and stash[0] == len(buf)
                else None
            )
            if res.matched_lines.size:
                if emit is not None:
                    if nl_idx is None:
                        nl_idx = lines_mod.newline_index(buf)
                    for ln in res.matched_lines.tolist():
                        s, e = lines_mod.line_span(nl_idx, ln, len(buf))
                        emit(lines_before + ln, buf[s:e])
                elif emit_chunk is not None:
                    if nl_idx is None:
                        nl_idx = lines_mod.newline_index(buf)
                    emit_chunk(lines_before, buf, res.matched_lines, nl_idx)
                matched.extend((res.matched_lines + lines_before).tolist())
            if nl_idx is not None:
                # chunks are newline-terminated except possibly the final
                # one: reuse the index instead of re-counting
                lines_before += len(nl_idx) + (0 if buf.endswith(b"\n") else 1)
            else:
                lines_before += lines_mod.count_lines(buf)
            if progress is not None:
                progress()  # one work milestone per streamed chunk

        # Device corpus cache (round 7, ops/layout.CorpusCache): a
        # single-chunk file whose host bytes AND packed device segments
        # are already resident serves this scan with zero file reads and
        # zero uploads; a cold single-chunk scan threads its content key
        # so the NEXT query over unchanged bytes is warm.  Multi-chunk
        # files stream cold: their chunk cuts are content-dependent, and
        # the service regime this cache targets (log/code search) is many
        # files under the 64 MB chunk target, not one giant file.
        from distributed_grep_tpu.ops.layout import file_content_key

        # Shard index (distributed_grep_tpu/index): the query's required-
        # literal set vs this shard's trigram summary — "cannot match"
        # returns the exact empty result WITHOUT opening the file; a
        # maybe (or no summary yet) scans, and a successful whole-file
        # scan publishes the summary for the next query.  The lookup is
        # jax-free and runs before the responsiveness wall, like the
        # corpus opt-in.
        idx_req = self._index_requirements()
        idx_key = None
        idx_pub = False  # publish after a successful whole-file scan
        if idx_req is not None:
            from distributed_grep_tpu.index import summary as index_summary

            # lock-free routing gate: derive the key (realpath + stat)
            # only when a lookup could answer or a publish could land —
            # a summary-free one-shot process pays nothing per file
            if index_summary.may_route() or self._index_publish_enabled():
                idx_key = file_content_key(path)
            if idx_key is not None:
                summ = index_summary.lookup_summary(idx_key)
                if summ is not None:
                    if not idx_req.may_match(summ):
                        return self._index_pruned(idx_key)
                    index_summary.record_maybe()
                    spans_mod.instant("index:maybe", cat="engine")
                else:
                    # single-chunk shards only — the corpus-cache regime:
                    # multi-chunk cuts are content-dependent and the
                    # target workload is many files under the chunk
                    # target; and only when a reuse surface exists
                    # (_index_publish_enabled — one-shot jobs skip the
                    # build entirely)
                    idx_pub = (
                        0 < idx_key.n_bytes <= chunk_target
                        and self._index_publish_enabled()
                    )
        idx_whole: bytes | None = None  # the whole keyed bytes, once in hand

        corpus_k = None
        if self._corpus_opt_in():
            from distributed_grep_tpu.ops.layout import corpus_cache

            # one fresh stat serves both tiers when the index already took
            # it — key identity and validators must describe the same
            # snapshot for the publish below to be sound
            k = idx_key if idx_key is not None else file_content_key(path)
            # _small_route_cached: on a real accelerator a sub-
            # device_min_bytes solo file host-routes and can never
            # populate — skip the key/stat/lock work outright rather
            # than pay a guaranteed-miss lookup per query (reads the
            # CACHED backend flag only; safe pre-wall)
            if (
                k is not None and 0 < k.n_bytes <= chunk_target
                and not self._small_route_cached(k.n_bytes)
            ):
                corpus_k = k
                ent = corpus_cache().lookup(k)
                if ent is not None and len(ent.data) == k.n_bytes:
                    # warm: the revalidated entry's host bytes stand in
                    # for the disk read (stat drift would have evicted
                    # it) — the file is never opened.  Counted at the
                    # cache (host-routed engines never reach the
                    # resident_segments verdict in scan_device)
                    corpus_cache().count_host_hit()
                    scan_piece(ent.data, k)
                    if idx_pub:
                        # scan succeeded over the entry's (revalidated)
                        # bytes: backfill the summary the index missed
                        self._index_publish(k, ent.data)
                    self.stats["end_offsets"] = end_offsets
                    self.stats["read_wait_seconds"] = 0.0
                    return ScanResult(
                        np.asarray(matched, dtype=np.int64), n_matches, total
                    )

        class _Ready:
            """Future-like wrapper for data already in hand (the first,
            synchronous read, and the EOF sentinel)."""

            def __init__(self, v: bytes):
                self._v = v

            def result(self) -> bytes:
                return self._v

        # The one-slot reader thread exists to overlap disk with scan —
        # pointless for files that fit in a single chunk.
        # BufferedReader.read(n) returns short only at EOF, so a full
        # block is the one case where more data may follow: the pool is
        # touched lazily at the first full block.  The pool itself is
        # PERSISTENT per scanning thread (round 6, _reader_pool): the old
        # per-file ThreadPoolExecutor paid a thread spawn + join per
        # multi-chunk file — measured real overhead on a 2,000-file
        # grep -r (round-5 note).
        pending = None  # the in-flight read future, if any

        def submit_read():
            nonlocal pending
            pending = self._reader_pool().submit(f.read, chunk_target)
            return pending

        key = None  # set by the whole-file unsplit branch only
        try:
            f = open(path, "rb")
            t0 = _time.perf_counter()
            nxt = _Ready(f.read(chunk_target))
            read_wait += _time.perf_counter() - t0  # the synchronous first
            # read is genuine stall: keep stats[read_wait_seconds] honest
            while True:
                t0 = _time.perf_counter()
                block = nxt.result()
                read_wait += _time.perf_counter() - t0
                if block:
                    # enqueue the NEXT read now; it overlaps this chunk's
                    # scan (short block = EOF: no read, no thread)
                    nxt = (
                        submit_read() if len(block) == chunk_target
                        else _Ready(b"")
                    )
                    buf = carry + block
                    whole_k = corpus_k if corpus_k is not None else (
                        idx_key if idx_pub else None
                    )
                    if (
                        whole_k is not None and total == 0
                        and len(buf) == whole_k.n_bytes
                        and file_content_key(path) == whole_k
                    ):
                        # The WHOLE single-chunk file is in hand and a
                        # fresh re-stat agrees: scan it UNSPLIT (the
                        # warm-serve path above proves whole-file-as-
                        # one-piece is exact).  The newline cut below
                        # would otherwise orphan an un-terminated tail
                        # into carry and leave the corpus key
                        # unthreaded on BOTH pieces — a no-trailing-
                        # newline file (common in code search) would
                        # never populate the cache.  The index-publish
                        # leg takes the same branch (same exactness
                        # argument) even when the corpus cache is off.
                        carry, final = b"", True
                        key = corpus_k  # the re-stat above just
                        # confirmed buf IS the keyed bytes; only the
                        # corpus key threads through scan() — the index
                        # summary publishes after the scan succeeds
                        if idx_pub:
                            idx_whole = buf
                    else:
                        cut = buf.rfind(b"\n")
                        if cut < 0:
                            carry = buf  # line longer than the chunk:
                            continue     # keep growing
                        carry, buf = buf[cut + 1 :], buf[: cut + 1]
                        final = False
                else:
                    buf, carry, final = carry, b"", True
                if buf:
                    # key is corpus_k ONLY when the unsplit branch above
                    # confirmed (fresh re-stat) that buf is the whole
                    # keyed file in one piece — every other piece,
                    # including a live-append tail that outgrew the
                    # stat, scans uncached
                    scan_piece(buf, key)
                    if idx_whole is not None:
                        # the scan over the whole keyed bytes SUCCEEDED:
                        # publish the shard summary (from the bytes
                        # already in hand — never an extra read)
                        self._index_publish(idx_key, idx_whole)
                        idx_whole = None
                    if (stop_after_match and n_matches) or (
                        stop is not None and stop()
                    ):
                        break  # presence settled: skip the rest of the file
                if final:
                    break
        finally:
            # The in-flight read must not outlive the file handle: cancel
            # a still-queued read, await one already running (bounded by a
            # single chunk read — what the old per-file pool shutdown also
            # waited for).  The pool itself stays alive for the next file.
            if pending is not None and not pending.cancel():
                try:
                    pending.result()
                except Exception:  # noqa: BLE001 — handle closes next
                    pass
            try:
                f.close()
            except NameError:
                pass  # open() itself failed
        self.stats["end_offsets"] = end_offsets
        self.stats["read_wait_seconds"] = read_wait
        return ScanResult(np.asarray(matched, dtype=np.int64), n_matches, total)

    # ------------------------------------------------- live-append suffix
    def scan_file_suffix(self, path, offset: int = 0, *, final: bool = False,
                         max_bytes: int | None = None, progress=None):
        """Scan the LIVE-APPEND suffix of ``path`` from ``offset`` — which
        MUST be a line start (the streaming tier's cursor invariant) — up
        to the last complete line.  Returns ``(res, consumed, data)``:
        the ScanResult over the suffix (matched_lines are suffix-local,
        1-based), the byte length actually consumed (the caller's cursor
        advance), and the scanned bytes (line text extraction happens
        while they are in hand).

        The partial tail line past the last newline is NOT consumed —
        the line-carry: the next wake re-reads it from the same offset,
        extended by whatever arrived since, so the emitted line set is
        byte-identical to a one-shot scan over the final file state.
        ``final=True`` (stream teardown / idle exit) includes an
        unterminated tail, matching the one-shot scanners' missing-
        trailing-newline behavior.  Exactness at every append boundary
        rides the DFA "'\\n' column == start state" invariant: the
        buffer begins at a line start and ends at a line boundary, so
        every kernel family scans it exactly like the same lines inside
        a whole-file scan (the same argument as cross-file batching).

        Live-append stat handling: the suffix NEVER threads a corpus key
        (appending content has no stable validator tuple — the cache's
        stale-never-served contract) and never consults the shard index
        (a stale trigram summary must not prune a standing query).
        ``max_bytes`` bounds one call's read (catch-up over a huge
        existing file proceeds in bounded steps; a capped read is cut at
        its last newline even under ``final``, and the caller simply
        continues from the advanced offset) — EXCEPT for a single line
        larger than the window: the read extends until a newline (or
        EOF) lands, because a newline-free full window would otherwise
        consume 0 bytes forever and permanently stall the cursor behind
        the giant line (memory is bounded by that one line, the same
        bound materializing it for emit needs anyway)."""
        cap = max_bytes or max(self.segment_bytes, 1 << 26)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(cap)
            # window_full: the last read filled its request, so the file
            # may extend beyond what we hold — the tail past the last
            # newline is then NEVER consumable, even under ``final``
            window_full = len(data) == cap
            if window_full and data.rfind(b"\n") < 0:
                while True:  # each chunk is newline-probed exactly once
                    more = f.read(cap)
                    if not more:
                        window_full = False
                        break
                    data += more
                    window_full = len(more) == cap
                    if not window_full or more.rfind(b"\n") >= 0:
                        break
        if not final or window_full:
            cut = data.rfind(b"\n")
            data = data[: cut + 1] if cut >= 0 else b""
        if not data:
            return (
                ScanResult(np.zeros(0, dtype=np.int64), 0, 0), 0, b""
            )
        res = self.scan(data, progress=progress)
        # per-scan suffix accounting (the module-level follow counters
        # aggregate across wakes; scan()'s tail merge may later overwrite
        # this key with the monotonic global — both are telemetry-only)
        self.stats["suffix_bytes_scanned"] = len(data)
        return res, len(data), data

    # ------------------------------------------------- cross-file batching
    def scan_batch(self, items, progress=None, emit=None,
                   index_prune: bool = False):
        """Scan many inputs, packing small ones into shared dispatches.

        ``items`` is an iterable of ``(name, data)`` where ``data`` is the
        input's bytes (or a filesystem path, read whole — callers with
        splits at or above device_min_bytes should stream those through
        scan_file themselves).  Inputs below device_min_bytes accumulate
        in a BatchPacker (ops/layout.py) and flush as ONE scan over the
        packed newline-terminated buffer whenever the next input would
        overflow ``batch_bytes``; larger inputs flush the pending batch
        (order is preserved) and scan solo.  Exactness at file granularity
        rides the two invariants the codebase already pins: every DFA
        '\\n' column is the start state (file boundaries are line starts,
        so every kernel family is exact there) and the host
        confirm/stitch pass owns stripe/segment boundaries — see the
        layout-module notes.

        Returns ``[(name, ScanResult)]`` in input order; matched_lines are
        per-file 1-based, bytes_scanned is the ORIGINAL blob length.
        ``emit(name, data, result)``, when given, is called per input
        while its blob is still in memory (the grep apps build their
        output records there).

        Telemetry lands in ``engine.stats`` after the call —
        ``batched_files``, ``batch_dispatches``, ``solo_dispatches``,
        ``dispatches_saved`` (= batched_files - batch_dispatches) and
        ``batch_fill_ratio`` (mean packed-buffer fill vs batch_bytes) —
        and each packed flush emits a ``scan:batch`` span on the span
        pipeline (utils/spans.py), so trace-export shows packed
        dispatches on the worker rows.

        Path items participate in the device corpus cache (round 7) when
        a byte budget is in force: solo files and packed windows thread
        content keys through scan(), and a repeat call over unchanged
        files serves host bytes AND device segments from the cache —
        zero reads, zero uploads.  A warm packed window is recognized
        BEFORE any member is read (the cache's window index maps a
        window's first member file to its stored member list; fresh
        stats of every member must match), so the whole window re-scans
        without touching the filesystem."""
        from distributed_grep_tpu.ops.layout import (
            BatchPacker,
            batch_content_key,
            corpus_cache,
            file_content_key,
            packed_size,
        )

        cap = max(0, int(self.batch_bytes))
        packer = BatchPacker(cap) if cap > 0 else None
        use_corpus = self._corpus_opt_in()  # jax-free (pre-wall entry)
        cache = corpus_cache() if use_corpus else None
        # Shard index (distributed_grep_tpu/index): ``index_prune=True``
        # is the CALLER's assertion that per-item emits with empty data
        # and the (exact) empty result are equivalent to its real
        # semantics — true for print/count consumers, FALSE for invert
        # (the complement of nothing is nothing, not every line), so the
        # grep app passes ``not invert``.  Pruned path items are never
        # opened; cold-read members publish their summaries after the
        # flush's scan succeeds, and warm packed windows prune whole
        # (with their real cached member blobs — exact for every
        # consumer).
        idx_req = self._index_requirements()
        idx_on = idx_req is not None
        idx_pub_ok = idx_on and self._index_publish_enabled()
        if idx_on:
            from distributed_grep_tpu.index import summary as index_summary

            # lock-free routing gate (see scan_file): without a possible
            # lookup answer or a publish surface, skip all per-member
            # key/stat/lock work
            idx_on = index_summary.may_route() or idx_pub_ok
        pk_keys: list = []  # member content keys, parallel to the packer
        pk_pub: list = []  # (key, bytes) members to index-publish, ditto
        out: list = []
        read_wait = 0.0  # member-open stall; stamped like scan_file's so
        # path items (worker map_batch_paths handover — the read happens
        # HERE, inside map:compute, same shape as the map_path branch)
        # keep disk wait visible in engine stats / the span piggyback
        bstats = {
            "batched_files": 0, "batch_dispatches": 0,
            "solo_dispatches": 0, "fill_sum": 0.0,
        }

        def handle(name, data, res) -> None:
            if emit is not None:
                emit(name, data, res)
            out.append((name, res))

        def scan_packed(batch, names, win_key) -> None:
            """One packed window through scan() + demux + per-file emit —
            shared by the cold flush and the warm-window path (which
            reuses the CACHED PackedBatch: demux tables and member blobs
            come from the entry, not a re-read + re-pack)."""
            t0 = _time_mod.perf_counter()
            t0_wall = _time_mod.time()
            res = self.scan(batch.data, progress=progress,
                            corpus_key=win_key)
            if cache is not None and win_key is not None:
                # record the demux tables + member blobs behind the
                # entry this scan just published (no-op if it was not
                # admitted) — what makes the next call's warm window
                # possible without re-reading members
                cache.attach_batch(win_key, batch)
                if idx_on and index_summary.lookup_summary(win_key) is None:
                    # window-level summary (packed-window pruning on the
                    # warm path): built from batch.data, so boundary-
                    # spanning trigrams only ADD bits — over-approximate,
                    # never unsound.  Corpus-cache regimes only: without
                    # a resident window there is no warm-window scan to
                    # prune.
                    self._index_publish(win_key, batch.data)
            per_file = batch.demux(res.matched_lines)
            bstats["batched_files"] += len(batch)
            bstats["batch_dispatches"] += 1
            bstats["fill_sum"] += len(batch.data) / cap
            if spans_mod.active():
                spans_mod.complete(
                    "scan:batch", t0_wall,
                    _time_mod.perf_counter() - t0, cat="engine",
                    mode=self.mode, files=len(batch),
                    bytes=len(batch.data), matches=res.n_matches,
                    fill_ratio=round(len(batch.data) / cap, 6),
                )
            # member_blobs(): as-stored on a fresh pack, transient
            # slices of batch.data on a cache-slimmed warm window
            for name, blob, lines in zip(names, batch.member_blobs(),
                                         per_file):
                handle(name, blob, ScanResult(
                    lines.astype(np.int64), int(lines.size), len(blob)
                ))

        def flush() -> None:
            nonlocal pk_keys, pk_pub
            if packer is None:
                return
            keys, pk_keys = pk_keys, []
            pubs, pk_pub = pk_pub, []
            batch = packer.pack()
            if batch is None:
                return
            if len(batch) == 1:
                # nothing amortized: scan the original blob (no synthesized
                # terminator in bytes_scanned, no demux) and count it solo
                bstats["solo_dispatches"] += 1
                handle(batch.names[0], batch.blobs[0],
                       self.scan(batch.blobs[0], progress=progress,
                                 corpus_key=keys[0] if keys else None))
            else:
                win_key = batch_content_key(keys) if use_corpus else None
                scan_packed(batch, batch.names, win_key)
            # the members' scan succeeded: publish the summaries staged
            # at read time (per-member keys — what the service planner
            # prunes with)
            for ent in pubs:
                if ent is not None:
                    self._index_publish(*ent)

        def match_window(i, stored) -> list | None:
            """Fresh member keys when ``items[i:...]`` are path items for
            exactly the stored window's member files, in order — else
            None (the cold path then handles item i normally)."""
            ids = stored.identity[1]
            if i + len(ids) > len(items):
                return None
            keys = []
            for (_nm, d), ident in zip(items[i:i + len(ids)], ids):
                if isinstance(d, (bytes, bytearray, memoryview)):
                    return None
                k = file_content_key(d)
                if k is None or k.identity != ident:
                    return None
                keys.append(k)
            return keys

        items = list(items)  # the warm-window probe needs lookahead
        i = 0
        while i < len(items):
            name, data = items[i]
            is_blob = isinstance(data, (bytes, bytearray, memoryview))
            fk = None
            if (use_corpus or idx_on) and not is_blob:
                fk = file_content_key(data)
            if use_corpus and not is_blob and fk is not None \
                    and packer is not None:
                stored = cache.window_for(fk)
                keys = (
                    match_window(i, stored)
                    if stored is not None else None
                )
                if keys is not None:
                    wk = batch_content_key(keys)
                    ent = cache.lookup(wk)
                    if (
                        ent is not None and ent.batch is not None
                        # the ENGINE's cap governs warm content too:
                        # a window packed under a larger budget is
                        # not re-served once batch_bytes shrinks
                        # (per-dispatch memory bound; the cold path
                        # re-packs at the new granularity and the
                        # oversized entry ages out via LRU)
                        and len(ent.batch.data) <= cap
                    ):
                        wsum = None
                        if idx_on:
                            wsum = (
                                ent.summary
                                if ent.summary is not None
                                else index_summary.lookup_summary(wk)
                            )
                        if wsum is not None and not idx_req.may_match(wsum):
                            # whole warm window pruned: members emit their
                            # REAL cached blobs with the (exact) empty
                            # result the summary proves — sound for every
                            # consumer incl. invert, and no union scan is
                            # dispatched
                            flush()
                            index_summary.record_prune(wk.n_bytes)
                            spans_mod.instant("index:prune", cat="engine",
                                              bytes=wk.n_bytes)
                            names_w = [nm for nm, _ in
                                       items[i:i + len(keys)]]
                            for nm, blob in zip(names_w,
                                                ent.batch.member_blobs()):
                                handle(nm, blob, ScanResult(
                                    np.zeros(0, dtype=np.int64), 0,
                                    len(blob),
                                ))
                            i += len(keys)
                            continue
                        if wsum is not None:
                            # consulted and could not rule the query
                            # out: the warm-window scan is a maybe (the
                            # counter the dense-regime telemetry reads)
                            index_summary.record_maybe()
                        flush()  # order-preserving, like a solo input
                        cache.count_host_hit()
                        scan_packed(
                            ent.batch,
                            [nm for nm, _ in items[i:i + len(keys)]],
                            wk,
                        )
                        if idx_pub_ok:
                            # backfill per-MEMBER summaries from the
                            # cached blobs (the warm path never iterates
                            # members, so a corpus-warm daemon would
                            # otherwise starve the planner of the
                            # per-file summaries it prunes with)
                            for mk, blob in zip(
                                keys, ent.batch.member_blobs()
                            ):
                                if index_summary.lookup_summary(mk) is None:
                                    self._index_publish(mk, blob)
                        i += len(keys)
                        continue
            i += 1
            idx_missing = False  # publish this member after its scan
            if idx_on and fk is not None:
                summ = index_summary.lookup_summary(fk)
                if summ is None:
                    idx_missing = idx_pub_ok
                elif not idx_req.may_match(summ):
                    if index_prune:
                        # "cannot match", and the caller declared empty-
                        # data emits exact: the file is never opened
                        flush()  # order-preserving, like a solo input
                        index_summary.record_prune(fk.n_bytes)
                        spans_mod.instant("index:prune", cat="engine",
                                          bytes=fk.n_bytes)
                        handle(name, b"", ScanResult(
                            np.zeros(0, dtype=np.int64), 0, 0
                        ))
                        continue
                    # caller needs the bytes (invert): scan as usual —
                    # still exact, the index just saves nothing here
                else:
                    index_summary.record_maybe()
            if not is_blob:
                ent = (
                    cache.lookup(fk)
                    if cache is not None and fk is not None else None
                )
                if ent is not None and len(ent.data) == fk.n_bytes:
                    data = ent.data  # warm host bytes: no disk read
                    cache.count_host_hit()
                else:
                    t_r = _time_mod.perf_counter()
                    with open(_os.fspath(data), "rb") as f:
                        data = f.read()
                    read_wait += _time_mod.perf_counter() - t_r
                    if fk is not None and (
                        len(data) != fk.n_bytes
                        or file_content_key(items[i - 1][1]) != fk
                    ):
                        fk = None  # changed between stat and read: uncached
            data = bytes(data)
            small = len(data) < self.device_min_bytes
            if packer is None or not small or packed_size(data) > cap:
                flush()  # order-preserving: pending smalls go first
                bstats["solo_dispatches"] += 1
                handle(name, data,
                       self.scan(data, progress=progress, corpus_key=fk))
                if idx_missing and fk is not None:
                    # the solo scan succeeded: publish this shard's summary
                    self._index_publish(fk, data)
                continue
            if not packer.fits(data):
                flush()
            packer.add(name, data)
            pk_keys.append(fk)
            pk_pub.append(
                (fk, data) if idx_missing and fk is not None else None
            )
        flush()
        # AFTER the last scan (each scan resets the thread's stats dict):
        # the batch counters describe the whole scan_batch call.
        st = self.stats
        st["batched_files"] = bstats["batched_files"]
        st["batch_dispatches"] = bstats["batch_dispatches"]
        st["solo_dispatches"] = bstats["solo_dispatches"]
        st["dispatches_saved"] = (
            bstats["batched_files"] - bstats["batch_dispatches"]
        )
        st["batch_fill_ratio"] = (
            round(bstats["fill_sum"] / bstats["batch_dispatches"], 6)
            if bstats["batch_dispatches"] else 0.0
        )
        st["read_wait_seconds"] = read_wait
        if idx_on:
            # re-stamp AFTER the last flush: members pruned after the
            # final dispatch would otherwise miss this call's stats (the
            # scan()-tail merge only sees counters as of its own scan)
            st.update(index_summary.index_counters())
        return out

    # ---------------------------------------------------------- host engines
    def _scan_re(self, data: bytes) -> ScanResult:
        self.stats = {}  # no device/telemetry legs on the re loop; also
        # clears a failed Pallas attempt's partial counters on rescan
        matched = []
        lines = data.split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()  # trailing '\n' closes the last line (grep -n);
            # also keeps scan_file's per-chunk line accounting exact
        for i, line in enumerate(lines, start=1):
            if self._re_fallback.search(line):
                matched.append(i)
        return ScanResult(np.asarray(matched, dtype=np.int64), len(matched), len(data))

    def _native_literal(self) -> bytes | None:
        """The pattern as one exact byte string, when it is one (every
        shift-and symbol a singleton class) — the memmem fast path."""
        if self.shift_and is None:
            return None
        out = []
        for ranges in self.shift_and.sym_ranges:
            if len(ranges) != 1 or ranges[0][0] != ranges[0][1]:
                return None
            out.append(ranges[0][0])
        return bytes(out)

    def _scan_native(self, data: bytes) -> ScanResult:
        lit = self._native_literal()
        if self.approx is not None:
            # host oracle (python recurrence) — correct, not a perf path;
            # the device XLA/Pallas cores are the fast approx engines
            offsets = approx_scan_reference(self.approx, data)
        elif lit is not None:
            # pure literal: native memmem scan (GB/s) instead of the
            # table-driven DFA walk (~0.3 GB/s single-thread)
            from distributed_grep_tpu.utils import native as native_mod

            offsets = native_mod.literal_scan(data, lit).astype(np.int64)
        elif self.tables:
            offsets = np.unique(np.concatenate(
                [reference_scan(t, data) for t in self.tables]
            ))
        else:
            offsets = np.zeros(0, dtype=np.int64)
        nl = lines_mod.newline_index(data)
        self._nl_local.stash = (len(data), nl)  # reused by scan()'s EOL leg
        # offsets are sorted on every branch above (literal_scan emits in
        # ascending order, np.unique sorts): one native linear merge
        lns = lines_mod.unique_match_lines(offsets, nl)
        self.stats = {"end_offsets": int(offsets.size)}
        return ScanResult(lns.astype(np.int64), int(lns.size), len(data))

    def _host_line_matcher(self, line: bytes) -> bool:
        if self.approx is not None:
            return approx_line_matches(self.approx, line)
        if not self.tables and self._re_fallback is not None:
            # DFA-less NFA rescue (expansion-cap patterns): re is the oracle
            return self._re_fallback.search(line) is not None
        return any(reference_scan(t, line).size > 0 for t in self.tables)

    def _device_tables(self, dev=None) -> list[tuple]:
        """Per-bank device-resident scan tables, uploaded once per engine
        per device (multi-chip round-robin needs operands colocated with
        the compute device — call under jax.default_device(dev)).

        Each entry is ("stride", args) when the k-byte-stride composition
        pays (chunk/k scan steps, one gather each — see models/dfa
        StrideTable) or ("plain", args) for the per-byte core ('$' accepts,
        or class counts whose composed table would blow the budget)."""
        if self._dev_tables is None:
            self._dev_tables = {}
        if dev not in self._dev_tables:
            import jax.numpy as jnp

            tabs = []
            for t in self.tables:
                k = choose_stride(t)
                if k > 1:
                    st = build_stride_table(t, k)
                    tabs.append(("stride", (
                        jnp.asarray(st.trans_k.reshape(-1)),
                        jnp.asarray(st.byte_to_cls.astype(np.int32)),
                        jnp.int32(st.start),
                        st.k,
                        st.n_classes,
                    )))
                else:
                    tabs.append(("plain", (
                        jnp.asarray(t.trans.astype(np.int32).reshape(-1)),
                        jnp.asarray(t.byte_to_cls.astype(np.int32)),
                        jnp.asarray(t.accept),
                        jnp.asarray(t.accept_eol),
                        jnp.int32(t.start),
                        t.n_classes,
                    )))
            self._dev_tables[dev] = tabs
        return self._dev_tables[dev]

    def _fdr_device_tables(self, dev=None) -> list:
        """Per-bank FDR reach tables, uploaded once per engine per device
        (call under jax.default_device(dev))."""
        if self._fdr_dev_tables is None:
            self._fdr_dev_tables = {}
        if dev not in self._fdr_dev_tables:
            import jax.numpy as jnp

            from distributed_grep_tpu.ops import pallas_fdr

            self._fdr_dev_tables[dev] = [
                jnp.asarray(pallas_fdr.bank_device_tables(b)) for b in self.fdr.banks
            ]
        return self._fdr_dev_tables[dev]

    def _pairset_device_tables(self, dev=None):
        """Pairset scan tables, uploaded once per engine per device (an
        engine has at most one pairset model: the whole-set one in mode
        "pairset", or the short-member sidecar in mode "fdr")."""
        model = self.pairset if self.pairset is not None else self._fdr_pairset
        if self._pairset_dev_tables is None:
            self._pairset_dev_tables = {}
        if dev not in self._pairset_dev_tables:
            import jax.numpy as jnp

            from distributed_grep_tpu.ops import pallas_pairset

            self._pairset_dev_tables[dev] = jnp.asarray(
                pallas_pairset.device_tables(model)
            )
        return self._pairset_dev_tables[dev]

    def _fdr_ep_tables(self, pattern_axis):
        """Stacked pattern-axis-sharded FDR tables, built + uploaded once
        per plan (reset alongside _fdr_dev_tables on retune) — the EP
        analogue of _fdr_device_tables."""
        if self._fdr_ep_dev_tables is None:
            from distributed_grep_tpu.parallel import sharded_kernels as shk

            self._fdr_ep_dev_tables = shk.fdr_pattern_tables(
                self.fdr, self.mesh, pattern_axis
            )
        return self._fdr_ep_dev_tables

    # --------------------------------------------------------- device engine
    def _scan_device(self, data: bytes, progress=None,
                     corpus_key=None) -> ScanResult:
        """Per-segment device dispatch (ops/device_scan.py — split out
        round 5; the orchestration is the engine's, moved)."""
        from distributed_grep_tpu.ops.device_scan import scan_device

        return scan_device(self, data, progress=progress,
                           corpus_key=corpus_key)

def make_engine(
    pattern: str | None = None, patterns: list[str] | None = None, **kw
) -> GrepEngine:
    return GrepEngine(pattern, patterns=patterns, **kw)

"""Host-side line machinery: packed match bits -> line numbers, plus exact
stitching of lines that span stripe/segment boundaries.

The device scan starts every stripe from the start state.  By the
newline-reset property that is exact for every byte *after* the stripe's
first newline; the stripe's head partial line may have false negatives
(matches spanning the boundary) and — for '^'/'$' patterns — false
positives (the device treats the stripe start as a line start and the
stripe tail as a line end).  The fix is exact and local: every line that
contains a stripe boundary is re-scanned on the host with the native DFA
scanner, and the host verdict *replaces* the device verdict for that line.
This is the long-context analogue of carrying block state in ring
attention (SURVEY.md §5): instead of carrying, we re-derive the tiny
boundary-dependent region from its true line start.
"""

from __future__ import annotations

from typing import Callable, Iterable

import numpy as np

from distributed_grep_tpu.ops.layout import Layout
from distributed_grep_tpu.utils import native

NL = 0x0A


def match_offsets_from_packed(packed: np.ndarray, layout: Layout) -> np.ndarray:
    """(chunk, lanes//8) packed bits -> sorted absolute end offsets (i+1),
    clamped to the real document length."""
    bits = np.unpackbits(packed, axis=1, bitorder="little")  # (chunk, lanes)
    c_idx, l_idx = np.nonzero(bits)
    offsets = l_idx.astype(np.int64) * layout.chunk + c_idx + 1
    offsets = offsets[offsets <= layout.n_real]
    offsets.sort()
    return offsets


def line_of_offsets(offsets: np.ndarray, nl_index: np.ndarray) -> np.ndarray:
    """1-based line number containing each match end offset (i+1 convention):
    the match's last byte is at offset-1."""
    return np.searchsorted(nl_index, offsets - 1, side="right") + 1


def unique_match_lines(offsets: np.ndarray, nl_index: np.ndarray) -> np.ndarray:
    """Sorted unique 1-based line numbers of SORTED match end offsets —
    ``np.unique(line_of_offsets(...))`` as one native linear merge when
    libdgrep is available (both arrays are sorted, so the searchsorted +
    sort-based-unique pair is two avoidable O(n log n) passes on the
    match-dense path; the fallback is bit-identical)."""
    if offsets.size == 0:
        return np.zeros(0, dtype=np.int64)
    out = native.unique_lines_native(nl_index, offsets)
    if out is not None:
        return out
    return np.unique(line_of_offsets(offsets, nl_index)).astype(np.int64)


def line_span(nl_index: np.ndarray, line_no: int, n_bytes: int) -> tuple[int, int]:
    """[start, end) byte range of 1-based line line_no (end excludes '\\n')."""
    start = 0 if line_no == 1 else int(nl_index[line_no - 2]) + 1
    end = int(nl_index[line_no - 1]) if line_no - 1 < len(nl_index) else n_bytes
    return start, end


def boundary_lines(
    boundaries: Iterable[int], nl_index: np.ndarray, n_bytes: int
) -> set[int]:
    """1-based line numbers containing any of the given byte positions."""
    out = set()
    for p in boundaries:
        if 0 < p < n_bytes:
            out.add(int(np.searchsorted(nl_index, p, side="right")) + 1)
    return out


def stitch_lines(
    device_lines: set[int],
    data: bytes,
    nl_index: np.ndarray,
    boundaries: Iterable[int],
    host_line_matcher: Callable[[bytes], bool],
) -> set[int]:
    """Replace the device verdict with the host verdict on every line that
    contains a stripe/segment boundary."""
    suspects = boundary_lines(boundaries, nl_index, len(data))
    if not suspects:
        return device_lines
    result = set(device_lines) - suspects
    for line_no in suspects:
        start, end = line_span(nl_index, line_no, len(data))
        if host_line_matcher(data[start:end]):
            result.add(line_no)
    return result


def newline_index(data: bytes) -> np.ndarray:
    """Byte offsets of every '\\n' (native fast path)."""
    return native.newline_index(data).astype(np.int64)


def empty_line_numbers(data: bytes, nl_index: np.ndarray | None = None) -> np.ndarray:
    """Sorted 1-based numbers of zero-length lines.

    A line is empty iff its '\\n' sits at the line's start offset —
    position 0 for line 1, or immediately after the previous '\\n'.  The
    fragment after the last '\\n' is a line only when non-empty
    (count_lines semantics), so it is never reported here.  Pass an
    already-computed ``newline_index(data)`` to skip the native pass."""
    nl = newline_index(data) if nl_index is None else nl_index
    if nl.size == 0:
        return np.zeros(0, dtype=np.int64)
    out = (np.nonzero(np.diff(nl) == 1)[0] + 2).astype(np.int64)
    if nl[0] == 0:
        out = np.concatenate([np.ones(1, np.int64), out])
    return out


def count_lines(data: bytes) -> int:
    """Line count with grep -n semantics: a trailing '\\n' closes the last
    line rather than opening an empty one; empty input has zero lines."""
    if not data:
        return 0
    return data.count(b"\n") + (0 if data.endswith(b"\n") else 1)

"""Pallas TPU kernel: agrep approximate (<= k edit errors) byte scan.

Same shell as ops/pallas_scan.py (lanes x chunk tiles, range-compare
B-masks, time-packed uint32 match words, VMEM scratch carried across chunk
blocks) but the per-byte step is the Wu-Manber k-error recurrence from
models/approx.py: k+1 uint32 state rows per lane, ~6 extra VPU ops per
error level — so k=1..3 stays within a small factor of the exact
shift-and kernel's throughput instead of paying a DFA-product blowup.

Newlines reset the rows to their seeds before the match check (grep line
semantics: an errorful match never spans or consumes '\n'); stripe starts
use the same seeds, and boundary lines get the usual exact host re-scan
(models/approx.scan_reference is the oracle the engine stitches with).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.approx import MAX_ERRORS, ApproxModel
from distributed_grep_tpu.ops import pallas_scan
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    MAX_TOTAL_RANGES,
    SUBLANES,
    available,
    validate_unroll,
)

NL = 0x0A


def eligible(model: ApproxModel) -> bool:
    return model.base.total_ranges <= MAX_TOTAL_RANGES and model.k <= MAX_ERRORS


def _kernel(data_ref, out_ref, state_ref, *, sym_ranges, match_bit, k, steps,
            unroll=8):
    from jax.experimental import pallas as pl  # deferred: import cost

    validate_unroll(unroll)
    ci = pl.program_id(1)
    seeds = [jnp.uint32((1 << j) - 1) for j in range(k + 1)]

    @pl.when(ci == 0)
    def _init():
        for j in range(k + 1):
            state_ref[j] = jnp.full((SUBLANES, LANE_COLS), seeds[j], jnp.uint32)

    zero = jnp.uint32(0)
    one = jnp.uint32(1)
    # symbols sharing a byte-class share one compare (same dedup as the
    # shift-and kernel: repeated letters are the norm in real patterns)
    groups: dict[tuple, int] = {}
    for j, ranges in enumerate(sym_ranges):
        groups[tuple(ranges)] = groups.get(tuple(ranges), 0) | (1 << j)
    range_groups = tuple(groups.items())
    n_inner = 32 // unroll

    def word_body(w, carry):
        def sub_body(sx, inner):
            word, *R = inner
            for tt in range(unroll):
                b = data_ref[w * 32 + sx * unroll + tt].astype(jnp.int32)
                bmask = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
                for ranges, mask in range_groups:
                    hit = None
                    for lo, hi in ranges:
                        r = (b >= lo) & (b <= hi) if lo != hi else (b == lo)
                        hit = r if hit is None else (hit | r)
                    bmask = bmask | jnp.where(hit, jnp.uint32(mask), zero)
                new = [((R[0] << one) | one) & bmask]
                for j in range(1, k + 1):
                    new.append(
                        (((R[j] << one) | one) & bmask)
                        | R[j - 1]
                        | (R[j - 1] << one)
                        | (new[j - 1] << one)
                        | seeds[j]
                    )
                nl_m = zero - (b == NL).astype(jnp.uint32)  # all-ones at '\n'
                R = [(nl_m & seeds[j]) | (~nl_m & new[j]) for j in range(k + 1)]
                m = (R[k] & jnp.uint32(match_bit)) != 0
                bit = jnp.uint32(1 << tt) << (sx * jnp.uint32(unroll))
                word = word | jnp.where(m, bit, zero)
            return (word, *R)

        word0 = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        if n_inner == 1:
            out = sub_body(0, (word0, *carry))
        else:
            out = jax.lax.fori_loop(0, n_inner, sub_body, (word0, *carry))
        word, *R = out
        out_ref[w] = word
        return tuple(R)

    carry0 = tuple(state_ref[j] for j in range(k + 1))
    final = jax.lax.fori_loop(0, steps // 32, word_body, carry0)
    for j in range(k + 1):
        state_ref[j] = final[j]


@functools.partial(
    jax.jit,
    static_argnames=("sym_ranges", "match_bit", "k", "chunk", "lane_blocks", "interpret", "unroll"),
)
def _approx_pallas(data, *, sym_ranges, match_bit, k, chunk, lane_blocks, interpret=False, unroll=8):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    kernel = functools.partial(
        _kernel, sym_ranges=sym_ranges, match_bit=match_bit, k=k, steps=steps, unroll=unroll)
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[pltpu.VMEM((k + 1, SUBLANES, LANE_COLS), jnp.uint32)],
        interpret=interpret,
    )(data)


def approx_scan_words(
    arr_cl: np.ndarray, model: ApproxModel, interpret: bool | None = None
) -> jnp.ndarray:
    """Run the kernel; time-packed match words in the shared Pallas
    convention (decode via ops/sparse.offsets_from_sparse_words)."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0"
        )
    if not eligible(model):
        raise ValueError("model exceeds the pallas approx budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = pallas_scan.as_tiles(arr_cl, lane_blocks)
    if interpret is None:
        interpret = not available()
    return _approx_pallas(
        data,
        sym_ranges=tuple(tuple(r) for r in model.base.sym_ranges),
        match_bit=int(model.match_bit),
        k=model.k,
        chunk=chunk,
        lane_blocks=lane_blocks,
        interpret=interpret,
    )


def approx_scan(
    arr_cl: np.ndarray, model: ApproxModel, interpret: bool | None = None
) -> np.ndarray:
    """Dense-output wrapper (tests): packed bits in the scan_jnp convention."""
    from distributed_grep_tpu.ops.pallas_scan import _unpack_words_to_lane_bits

    chunk, lanes = arr_cl.shape
    words = approx_scan_words(arr_cl, model, interpret)
    return _unpack_words_to_lane_bits(np.asarray(words), chunk, lanes)

"""XLA scan engines: DFA table scan and Shift-And, lane-parallel.

Both engines take the column-major stripe array (chunk, lanes) uint8 from
ops/layout.py and return packed match bits (chunk, lanes/8) uint8 — bit k of
out[c, g] is "a match ends at byte (c, lane g*8+k)".  Device->host transfer
is input/8; offset decoding happens on the host (ops/lines.py).

Design notes (TPU-first):

* The per-byte recurrence is sequential along a stripe but vectorized over
  lanes: one lax.scan over the chunk axis, each step doing O(lanes) VPU work.
* The byte->class and byte->B-mask table lookups are hoisted out of the scan
  as ONE whole-array gather (XLA lowers a 256-entry table gather fine on
  TPU); the in-loop DFA gather indexes the [n_states*n_classes] flat table.
* '$' accepts (accept_eol) are evaluated against a pre-shifted
  next-byte-is-newline plane, so anchors cost nothing in the loop.
* Everything is shapes-static, branch-free, jit-compiled once per
  (layout, model) signature.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.dfa import DfaTable
from distributed_grep_tpu.models.shift_and import ShiftAndModel

NL = 0x0A


def _pack_lane_bits(match: jnp.ndarray) -> jnp.ndarray:
    """(chunk, lanes) bool -> (chunk, lanes//8) uint8, bit k = lane g*8+k."""
    c, l = match.shape
    assert l % 8 == 0, "lanes must be a multiple of 8 for bit packing"
    bits = match.reshape(c, l // 8, 8).astype(jnp.uint8)
    powers = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], dtype=jnp.uint8)
    return (bits * powers).sum(axis=-1, dtype=jnp.uint8)


def dfa_scan_body(
    data_cl: jnp.ndarray,  # (chunk, lanes) uint8
    trans_flat: jnp.ndarray,  # (n_states * n_classes,) int32
    byte_to_cls: jnp.ndarray,  # (256,) int32
    accept: jnp.ndarray,  # (n_states,) bool
    accept_eol: jnp.ndarray,  # (n_states,) bool
    init: jnp.ndarray,  # (lanes,) int32 initial state per lane
    n_classes: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Shared DFA scan recurrence -> (final_states, match bool (chunk, lanes)).

    The single source of truth for scan semantics (the end-of-stripe
    next-byte-is-'\\n' rule, '$' accepts, the transition step) — both the
    single-chip core below and parallel/sharded_scan's shard_map body call
    this, so the two paths cannot drift.
    """
    chunk, lanes = data_cl.shape
    # Hoisted table lookups: one gather for the whole array.
    cls = byte_to_cls[data_cl.astype(jnp.int32)]  # (chunk, lanes) int32
    # next byte within the same stripe is the next row; the final row's
    # successor is the next stripe's first byte — treat it as '\n' (stripe
    # tails are re-checked by the host stitcher anyway, and real documents
    # are padded with '\n').
    nl_next = jnp.concatenate(
        [data_cl[1:] == NL, jnp.ones((1, lanes), dtype=bool)], axis=0
    )

    def step(states, inputs):
        cls_row, nl_row = inputs
        nxt = trans_flat[states * n_classes + cls_row]
        match = accept[nxt] | (accept_eol[nxt] & nl_row)
        return nxt, match

    return jax.lax.scan(step, init, (cls, nl_next))


@partial(jax.jit, static_argnames=("n_classes",))
def _dfa_scan_core(
    data_cl: jnp.ndarray,
    trans_flat: jnp.ndarray,
    byte_to_cls: jnp.ndarray,
    accept: jnp.ndarray,
    accept_eol: jnp.ndarray,
    start: jnp.ndarray,  # () int32
    n_classes: int,
) -> jnp.ndarray:
    lanes = data_cl.shape[1]
    init = jnp.full((lanes,), start, dtype=jnp.int32)
    _, match = dfa_scan_body(
        data_cl, trans_flat, byte_to_cls, accept, accept_eol, init, n_classes
    )
    return _pack_lane_bits(match)


@partial(jax.jit, static_argnames=("k", "n_classes"))
def _dfa_stride_core(
    data_cl: jnp.ndarray,  # (chunk, lanes) uint8, chunk % k == 0
    trans_k_flat: jnp.ndarray,  # (n_states * n_classes**k,) int32 packed
    byte_to_cls: jnp.ndarray,  # (256,) int32
    start: jnp.ndarray,  # () int32
    k: int,
    n_classes: int,
) -> jnp.ndarray:
    """k-byte-stride DFA scan (models/dfa.StrideTable): chunk/k lax.scan
    steps of one gather each; per-byte match positions recovered exactly
    from the packed accept bitmaps."""
    chunk, lanes = data_cl.shape
    cols = n_classes**k
    cls = byte_to_cls[data_cl.astype(jnp.int32)]  # (chunk, lanes)
    cls_k = cls.reshape(chunk // k, k, lanes)
    idx = cls_k[:, 0, :]
    for t in range(1, k):  # first byte of the stride is the most significant
        idx = idx * n_classes + cls_k[:, t, :]

    init = jnp.full((lanes,), start, dtype=jnp.int32)

    def step(states, idx_row):
        entry = trans_k_flat[states * cols + idx_row]
        return entry >> k, entry & ((1 << k) - 1)

    _, bitmaps = jax.lax.scan(step, init, idx)  # (chunk//k, lanes) int32
    t = jnp.arange(k, dtype=bitmaps.dtype)
    match = ((bitmaps[:, None, :] >> t[None, :, None]) & 1).astype(bool)
    return _pack_lane_bits(match.reshape(chunk, lanes))


def dfa_scan_stride(data_cl, stride_table) -> jnp.ndarray:
    """Run the stride engine; same packed-bit output convention as dfa_scan."""
    assert data_cl.shape[0] % stride_table.k == 0, "stride k must divide chunk"
    return _dfa_stride_core(
        jnp.asarray(data_cl),
        jnp.asarray(stride_table.trans_k.reshape(-1)),
        jnp.asarray(stride_table.byte_to_cls.astype(np.int32)),
        jnp.int32(stride_table.start),
        stride_table.k,
        stride_table.n_classes,
    )


def dfa_scan(data_cl: np.ndarray, table: DfaTable) -> jnp.ndarray:
    """Run the DFA engine; returns packed match bits as a device array
    (decode sparsely via sparse_nonzero + ops/sparse, or np.asarray for
    the dense path)."""
    return _dfa_scan_core(
        jnp.asarray(data_cl),
        jnp.asarray(table.trans.astype(np.int32).reshape(-1)),
        jnp.asarray(table.byte_to_cls.astype(np.int32)),
        jnp.asarray(table.accept),
        jnp.asarray(table.accept_eol),
        jnp.int32(table.start),
        table.n_classes,
    )


@jax.jit
def _shift_and_core(
    data_cl: jnp.ndarray,  # (chunk, lanes) uint8
    b_table: jnp.ndarray,  # (256,) uint32
    match_bit: jnp.ndarray,  # () uint32
) -> jnp.ndarray:
    # One whole-array gather for B[byte]; the scan is then pure VPU
    # shift/and/or — no gathers in the loop at all.
    b_all = b_table[data_cl.astype(jnp.int32)]  # (chunk, lanes) uint32
    lanes = data_cl.shape[1]
    init = jnp.zeros((lanes,), dtype=jnp.uint32)

    def step(s, b_row):
        s = ((s << jnp.uint32(1)) | jnp.uint32(1)) & b_row
        return s, (s & match_bit) != 0

    _, match = jax.lax.scan(step, init, b_all)
    return _pack_lane_bits(match)


def shift_and_scan(data_cl: np.ndarray, model: ShiftAndModel) -> jnp.ndarray:
    """Packed match bits as a device array (see dfa_scan)."""
    return _shift_and_core(
        jnp.asarray(data_cl),
        jnp.asarray(model.b_table),
        jnp.uint32(model.match_bit),
    )


# ----------------------------------------------------- sparse result fetch
# grep matches are sparse; host<->device links may be slow (PCIe, or the
# axon tunnel in this environment at ~MB/s).  Instead of downloading the
# dense packed-bit plane (input/8 bytes), count the nonzero packed bytes on
# device (4-byte transfer), then gather exactly those bytes + their indices
# (a few KB for realistic match densities).


@jax.jit
def count_nonzero_bytes(packed: jnp.ndarray) -> jnp.ndarray:
    return jnp.count_nonzero(packed)


@partial(jax.jit, static_argnames=("k",))
def gather_nonzero_bytes(packed: jnp.ndarray, k: int):
    flat = packed.reshape(-1)
    idx = jnp.nonzero(flat, size=k, fill_value=0)[0]
    return idx, flat[idx]


def sparse_nonzero(packed_dev) -> tuple[np.ndarray, np.ndarray]:
    """(indices, values) of nonzero bytes in a device packed-bit array."""
    nnz = int(count_nonzero_bytes(packed_dev))
    if nnz == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.uint8)
    # Round k up to limit jit specializations.
    k = 1 << max(6, (nnz - 1).bit_length())
    idx, vals = gather_nonzero_bytes(packed_dev, k)
    idx = np.asarray(idx)[:nnz].astype(np.int64)
    vals = np.asarray(vals)[:nnz]
    return idx, vals


@partial(jax.jit, static_argnames=("k",))
def _approx_scan_core(
    data_cl: jnp.ndarray,  # (chunk, lanes) uint8
    b_table: jnp.ndarray,  # (256,) uint32
    match_bit: jnp.ndarray,  # () uint32
    k: int,
) -> jnp.ndarray:
    """agrep <= k-error scan (models/approx.py recurrence), lane-parallel:
    k+1 uint32 rows per lane, newline-reset before the match check so
    errorful matches never span lines."""
    b_all = b_table[data_cl.astype(jnp.int32)]  # (chunk, lanes) uint32
    is_nl = data_cl == NL
    lanes = data_cl.shape[1]
    seeds = [jnp.uint32((1 << j) - 1) for j in range(k + 1)]
    init = tuple(jnp.full((lanes,), s, dtype=jnp.uint32) for s in seeds)

    def step(R, inputs):
        b_row, nl_row = inputs
        new = [((R[0] << jnp.uint32(1)) | jnp.uint32(1)) & b_row]
        for j in range(1, k + 1):
            new.append(
                (((R[j] << jnp.uint32(1)) | jnp.uint32(1)) & b_row)
                | R[j - 1]
                | (R[j - 1] << jnp.uint32(1))
                | (new[j - 1] << jnp.uint32(1))
                | seeds[j]
            )
        new = [jnp.where(nl_row, seeds[j], new[j]) for j in range(k + 1)]
        return tuple(new), (new[k] & match_bit) != 0

    _, match = jax.lax.scan(step, init, (b_all, is_nl))
    return _pack_lane_bits(match)


def approx_scan(data_cl: np.ndarray, model) -> jnp.ndarray:
    """Packed match bits for the approximate model (see dfa_scan)."""
    return _approx_scan_core(
        jnp.asarray(data_cl),
        jnp.asarray(model.base.b_table),
        jnp.uint32(model.match_bit),
        model.k,
    )

"""Cross-query scan fusion: ONE device scan answers K grep queries.

The service regime (runtime/service.py) sees a STREAM of jobs, and at
"millions of users" the query mix over a hot corpus is the common case —
K tenants grepping the same warm shards previously paid K full scans.
This module is the engine half of the fusion layer (runtime/fusion.py is
the planning half): a ``FusedScanner`` takes K query specs, compiles ONE
union engine, runs ONE dispatch per chunk/packed window through the
existing pipeline (device kernels, cross-file batching, the device
corpus cache — all unchanged), and then restores each query's EXACT
result with a per-query confirm over the shared candidate lines.

Correctness rides the repo's core invariant: device filters may
over-approximate, because the per-line host confirm restores exactness.
The union engine's matched lines are a SUPERSET of every member query's
matched lines —

* alternation: a line matching query k matches the union branch k;
* ignore-case mixes: the union compiles with ``ignore_case=True`` when
  ANY member asks for it — a deliberate over-approximation for the
  case-sensitive members (more candidates, never fewer);
* empty-match members make the union match the empty string too, so the
  engine's match-everything leg reports every line;

— and the per-query confirm is an EXACT host engine (backend="cpu":
native AC/DFA banks, memmem, or the re loop) scanned over a compact slab
of only the candidate lines.  Slab line i is candidate line i verbatim
(newline-terminated, so per-line semantics — '^', '$', empty lines —
are preserved), which makes the mapping back to source line numbers pure
arithmetic.  Each query's fused result is therefore bit-identical to a
solo scan of that query (pinned across kernel families in
tests/test_fuse.py).

Fusion is a FAST PATH, never a correctness dependency: any spec the
union builder cannot host (empty patterns, backreference-bearing
regexes, approx queries) raises ``FuseError`` and the caller falls back
to per-query solo scans.
"""

from __future__ import annotations

import re as _re
from dataclasses import dataclass

import numpy as np

from distributed_grep_tpu.ops import lines as lines_mod
from distributed_grep_tpu.ops.engine import GrepEngine, ScanResult, cached_engine
from distributed_grep_tpu.utils import lockdep


class FuseError(ValueError):
    """These specs cannot share one union scan — scan them solo."""


@dataclass(frozen=True)
class QuerySpec:
    """One fused query: exactly one of pattern/patterns, plus its case
    flag.  ``patterns`` members are literal strings (grep -F semantics);
    ``pattern`` is a regex in the engine dialect."""

    pattern: str | None = None
    patterns: tuple[str, ...] | None = None
    ignore_case: bool = False

    @staticmethod
    def normalize(spec) -> "QuerySpec":
        """Accept a QuerySpec or a (pattern, patterns, ignore_case)
        tuple (the shape runtime/fusion.query_spec emits)."""
        if isinstance(spec, QuerySpec):
            s = spec
        else:
            pat, pats, ic = spec
            s = QuerySpec(
                pattern=pat,
                patterns=tuple(pats) if pats is not None else None,
                ignore_case=bool(ic),
            )
        if (s.pattern is None) == (s.patterns is None):
            raise FuseError("spec needs exactly one of pattern/patterns")
        if s.pattern is not None and not s.pattern:
            # the empty pattern matches everything; a solo scan answers it
            # without scanning — fusing it would only grow the union
            raise FuseError("empty pattern is not fusable")
        if s.patterns is not None and (
            not s.patterns or any(p == "" for p in s.patterns)
        ):
            raise FuseError("empty literal in pattern set is not fusable")
        return s


def union_engine_args(specs: list[QuerySpec]) -> dict:
    """Construction args of the UNION engine for these specs.

    All-literal-set specs merge into one pattern set (the FDR/pairset/
    AC-bank machinery is already a multi-literal union engine — and
    exactly what the model cache keys on); any regex member switches to
    one alternation pattern, literals re.escape'd into branches (the
    engine dialect parses escaped metacharacters and ``(?:``, the same
    forms the CLI's -e/-F joins already emit).  ``ignore_case`` is the
    OR over members — a superset for case-sensitive members, which the
    per-query confirm undoes."""
    ic_any = any(s.ignore_case for s in specs)
    if all(s.patterns is not None for s in specs):
        merged: list[str] = []
        seen: set[str] = set()
        for s in specs:
            for p in s.patterns:  # type: ignore[union-attr]
                if p not in seen:
                    seen.add(p)
                    merged.append(p)
        return {"patterns": merged, "ignore_case": ic_any}
    # Backreference guard (the documented FuseError, enforced for direct
    # API users too — the service planner pre-filters via the same
    # helper): joining a group-number-sensitive regex into an alternation
    # silently repoints its groups, breaking the union-superset invariant
    # the whole design rests on.  runtime/fusion is deliberately
    # ops-free, so the import runs this direction.
    from distributed_grep_tpu.runtime.fusion import has_backref

    branches: list[str] = []
    for s in specs:
        if s.patterns is not None:
            branches.extend(_re.escape(p) for p in s.patterns)
        else:
            if has_backref(s.pattern):  # type: ignore[arg-type]
                raise FuseError(
                    f"pattern {s.pattern!r} uses backreferences — it "
                    f"cannot join an alternation union"
                )
            branches.append(s.pattern)  # type: ignore[arg-type]
    return {
        "pattern": "(?:" + "|".join(f"(?:{b})" for b in branches) + ")",
        "ignore_case": ic_any,
    }


# ----------------------------------------------------- fusion telemetry
# Module-level counters, the model-cache/corpus-cache contract: {} while
# untouched (zero-activity processes never grow stats/piggyback keys),
# merged into the worker heartbeat piggyback by
# runtime/worker._engine_cache_counters (sys.modules-gated there).
_fuse_stats_lock = lockdep.make_lock("fuse-stats")
_fuse_stats = {
    "fused_queries": 0,     # query-scans answered by shared dispatches
    "fused_dispatches": 0,  # union scan passes that served K >= 2 queries
    "fused_dispatches_saved": 0,  # (K-1) x passes co-queries did not pay
    "fusion_bytes_saved": 0,  # (K-1) x bytes each fused pass scanned once
}


def fusion_counters() -> dict:
    with _fuse_stats_lock:
        if not any(_fuse_stats.values()):
            return {}
        return dict(_fuse_stats)


def fusion_counters_clear() -> None:
    with _fuse_stats_lock:
        for k in _fuse_stats:
            _fuse_stats[k] = 0


def _count_fusion(n_queries: int, dispatches: int, n_bytes: int) -> None:
    if n_queries < 2:
        return
    with _fuse_stats_lock:
        _fuse_stats["fused_queries"] += n_queries
        _fuse_stats["fused_dispatches"] += dispatches
        _fuse_stats["fused_dispatches_saved"] += (n_queries - 1) * dispatches
        _fuse_stats["fusion_bytes_saved"] += (n_queries - 1) * n_bytes


class FusedScanner:
    """K queries, one scan.  Construction compiles the union engine and
    one exact CPU confirm engine per query, all through the cross-job
    model cache (a warm daemon re-fusing the same tenant mix pays zero
    compiles).  ``engine_opts`` are the SHARED engine kwargs (backend,
    devices, interpret, batch_bytes, ...) — the planner guarantees the
    fused jobs agree on them (runtime/fusion.fusion_key)."""

    def __init__(self, specs, **engine_opts):
        self.specs = [QuerySpec.normalize(s) for s in specs]
        if not self.specs:
            raise FuseError("no specs")
        if engine_opts.get("mesh") is not None or engine_opts.get("max_errors"):
            raise FuseError("mesh/approx engines are not fusable")
        try:
            args = union_engine_args(self.specs)
            self.union, self._union_verdict = cached_engine(
                args.get("pattern"),
                patterns=args.get("patterns"),
                ignore_case=args["ignore_case"],
                **engine_opts,
            )
        except FuseError:
            raise
        except Exception as e:  # noqa: BLE001 — union outside every engine subset
            raise FuseError(f"union engine construction failed: {e}") from e
        # Exact per-query confirm oracles: host engines (native AC/DFA
        # banks / memmem / re loop) — never a device dispatch, and tiny
        # relative to the scan they replace (they see candidate lines
        # only).  Cached: the specs are exactly solo jobs' patterns, so
        # a tenant's own solo resubmit shares the object.
        self.confirms: list[GrepEngine] = []
        try:
            for s in self.specs:
                eng, _ = cached_engine(
                    s.pattern,
                    patterns=list(s.patterns) if s.patterns is not None else None,
                    ignore_case=s.ignore_case,
                    backend="cpu",
                )
                self.confirms.append(eng)
        except Exception as e:  # noqa: BLE001
            raise FuseError(f"confirm engine construction failed: {e}") from e

    # ------------------------------------------------------------ confirm
    def _confirm_all(self, data: bytes, union_res: ScanResult
                     ) -> tuple[list[ScanResult], np.ndarray | None]:
        """Each query's exact ScanResult from the union scan's candidate
        lines, plus the newline index used (None when none was needed):
        gather the candidates into a newline-terminated slab (slab line
        i == candidate i) and scan it with each query's exact host
        engine — per-line semantics are position-invariant, so the slab
        verdicts ARE the per-line verdicts of a solo scan.  The newline
        index is REUSED from the union engine's per-scan stash when the
        lengths match (a host-mode union scan just indexed this exact
        buffer) and handed back to the caller — K participants' record
        builds must not each re-pay a full pass (measured: the newline
        passes alone cost more than the union scan on selective
        queries)."""
        cl = union_res.matched_lines
        n = len(data)
        if cl.size == 0:
            return [
                ScanResult(np.zeros(0, dtype=np.int64), 0, n)
                for _ in self.specs
            ], None
        from distributed_grep_tpu.runtime.columnar import (
            gather_ranges,
            line_spans,
        )

        arr = np.frombuffer(data, dtype=np.uint8)
        stash = getattr(self.union._nl_local, "stash", None)
        nl = (
            stash[1] if stash is not None and stash[0] == n
            else lines_mod.newline_index(data)
        )
        starts, ends = line_spans(cl, nl, n)
        # include each line's '\n' (the final line may not have one —
        # the slab scan still counts it as a line, like the source scan)
        slab, _offsets = gather_ranges(arr, starts, np.minimum(ends + 1, n))
        out: list[ScanResult] = []
        for eng in self.confirms:
            sub = eng.scan(slab)
            ml = cl[sub.matched_lines - 1].astype(np.int64)
            out.append(ScanResult(ml, int(ml.size), n))
        return out, nl

    # --------------------------------------------------------------- scan
    def scan(self, data: bytes, progress=None, corpus_key=None
             ) -> list[ScanResult]:
        """One in-memory document, K exact results — one union scan
        (device corpus cache included via ``corpus_key``), K slab
        confirms."""
        union_res = self.union.scan(data, progress=progress,
                                    corpus_key=corpus_key)
        results, _nl = self._confirm_all(data, union_res)
        _count_fusion(len(self.specs), 1, len(data))
        return results

    def scan_suffix(self, path, offset: int = 0, *, final: bool = False,
                    max_bytes: int | None = None):
        """One live-append suffix, K exact results (the fused follow
        tier's per-(file, wake) entry): one union suffix scan through
        the ``GrepEngine.scan_file_suffix`` contract — cut at the last
        newline, partial tail carried, ``offset`` MUST be a line start —
        then the PR 11 candidate-line-slab confirm per member.  Returns
        ``(results, consumed, data)``: per-spec suffix-LOCAL ScanResults
        (matched_lines 1-based within ``data``), the shared cursor
        advance, and the scanned bytes.  Exactness is the same two-step
        argument as scan/scan_batch: the union suffix result is a
        superset of every member's (alternation + OR'd ignore_case), and
        the per-line confirm slab is position-invariant — so each
        member's fused suffix result is bit-identical to its own solo
        ``scan_file_suffix`` over the same (offset, bytes) window.

        Telemetry: the fused-wake counters live follow-side
        (runtime/follow.follow_fused_counters — the group runner knows
        wake/member attribution); this entry does NOT bump the batch
        ``fused_*`` counters, so batch-fusion telemetry keeps meaning."""
        union_res, consumed, data = self.union.scan_file_suffix(
            path, offset, final=final, max_bytes=max_bytes
        )
        if consumed == 0:
            empty = [
                ScanResult(np.zeros(0, dtype=np.int64), 0, 0)
                for _ in self.specs
            ]
            return empty, 0, data
        results, _nl = self._confirm_all(data, union_res)
        return results, consumed, data

    def scan_batch(self, items, progress=None, emit=None):
        """Many inputs through the union engine's packed batching — one
        dispatch per DGREP_BATCH_BYTES window serves every query.  Items
        are (name, bytes-or-path) like GrepEngine.scan_batch (path items
        ride the corpus cache: a warm window re-scans with zero reads).

        Returns ``[per-spec [(name, ScanResult)] ]`` in input order;
        ``emit(index, name, data, results_per_spec, nl_index)`` is
        called per input while its bytes are in memory (the fused grep
        app builds each participant's records there; ``nl_index`` is
        this input's newline index when the confirm pass computed one —
        K record builds share it instead of re-indexing per
        participant)."""
        outs: list[list] = [[] for _ in self.specs]
        pos = [0]
        total_bytes = [0]

        def on_item(name, data, union_res) -> None:
            results, nl = self._confirm_all(data, union_res)
            i = pos[0]
            pos[0] += 1
            total_bytes[0] += len(data)
            for k, res in enumerate(results):
                outs[k].append((name, res))
            if emit is not None:
                emit(i, name, data, results, nl)

        self.union.scan_batch(items, progress=progress, emit=on_item)
        # dispatch accounting AFTER the call (scan_batch stamps its batch
        # counters into the union engine's thread stats at return)
        st = self.union.stats
        dispatches = int(st.get("batch_dispatches", 0)) + int(
            st.get("solo_dispatches", 0)
        )
        _count_fusion(len(self.specs), max(1, dispatches), total_bytes[0])
        return outs

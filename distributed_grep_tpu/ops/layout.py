"""Stripe layout: document bytes -> (chunk, lanes) device array.

The scan is lane-parallel: the document is cut into ``lanes`` contiguous
stripes, each lane scans its stripe sequentially (lax.scan over the chunk
axis), and all lanes run as one vector op per byte step.  Because the DFA
resets to line-start on '\\n', every lane can start from the start state;
the only error is each stripe's first partial line, which lines.py
re-scans exactly on the host.

Padding uses '\\n' bytes: the pattern can never consume '\\n', so padding
can't create matches inside real lines; phantom empty padding lines are
clamped away by lines.py (they sit past the real data's last offset).

Layout is column-major for the scan: array[c, l] = byte c of stripe l, so
lax.scan iterates the leading axis with unit-stride vectors.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NL = 0x0A


@dataclass(frozen=True)
class Layout:
    lanes: int
    chunk: int  # bytes per lane
    n_real: int  # real (unpadded) document length

    @property
    def padded(self) -> int:
        return self.lanes * self.chunk

    def offset_of(self, c: int, l: int) -> int:
        """Absolute byte offset of array position (chunk c, lane l)."""
        return l * self.chunk + c

    def stripe_starts(self) -> np.ndarray:
        """Absolute offsets where a lane's stripe begins (boundary fix-ups)."""
        return np.arange(1, self.lanes, dtype=np.int64) * self.chunk


def choose_layout(
    n_bytes: int,
    target_lanes: int = 1024,
    min_chunk: int = 256,
    lane_multiple: int = 8,
    chunk_multiple: int = 8,
    quantize_chunk: bool = False,
) -> Layout:
    """Pick (lanes, chunk) for a document: enough lanes to fill the VPU,
    chunks long enough that the sequential scan amortizes its step cost.
    lane_multiple/chunk_multiple let kernels impose tile shapes (the Pallas
    path needs lanes % 4096 == 0 and chunk % 512 == 0).

    ``quantize_chunk`` rounds the chunk UP to a 4-mantissa-bit grid, so a
    job over arbitrarily-sized splits produces O(log) distinct padded
    shapes instead of one per ``chunk_multiple``-byte size step — every
    distinct shape jit-specializes the scan kernel (~20-40 s through a
    tunneled TPU), so the engine bounds compiles at the cost of <= 1/8
    extra '\\n' padding on tail segments (scanned at kernel speed, and
    full 64 MB segments land exactly on the grid unchanged)."""
    if n_bytes <= 0:
        return Layout(lanes=lane_multiple, chunk=chunk_multiple, n_real=max(0, n_bytes))
    lanes = max(lane_multiple, target_lanes // lane_multiple * lane_multiple)
    while lanes > lane_multiple and (n_bytes + lanes - 1) // lanes < min_chunk:
        lanes = max(lane_multiple, lanes // 2 // lane_multiple * lane_multiple)
    chunk = (n_bytes + lanes - 1) // lanes
    if quantize_chunk:
        q = 1 << max(0, chunk.bit_length() - 4)
        chunk = (chunk + q - 1) // q * q
    chunk = (chunk + chunk_multiple - 1) // chunk_multiple * chunk_multiple
    return Layout(lanes=lanes, chunk=chunk, n_real=n_bytes)


def to_device_array(data: bytes, layout: Layout) -> np.ndarray:
    """Pad with '\\n' and reshape column-major: result[c, l] = data[l*chunk+c]."""
    buf = np.full(layout.padded, NL, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    return np.ascontiguousarray(buf.reshape(layout.lanes, layout.chunk).T)

"""Stripe layout: document bytes -> (chunk, lanes) device array.

The scan is lane-parallel: the document is cut into ``lanes`` contiguous
stripes, each lane scans its stripe sequentially (lax.scan over the chunk
axis), and all lanes run as one vector op per byte step.  Because the DFA
resets to line-start on '\\n', every lane can start from the start state;
the only error is each stripe's first partial line, which lines.py
re-scans exactly on the host.

Padding uses '\\n' bytes: the pattern can never consume '\\n', so padding
can't create matches inside real lines; phantom empty padding lines are
clamped away by lines.py (they sit past the real data's last offset).

Layout is column-major for the scan: array[c, l] = byte c of stripe l, so
lax.scan iterates the leading axis with unit-stride vectors.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NL = 0x0A


@dataclass(frozen=True)
class Layout:
    lanes: int
    chunk: int  # bytes per lane
    n_real: int  # real (unpadded) document length

    @property
    def padded(self) -> int:
        return self.lanes * self.chunk

    def offset_of(self, c: int, l: int) -> int:
        """Absolute byte offset of array position (chunk c, lane l)."""
        return l * self.chunk + c

    def stripe_starts(self) -> np.ndarray:
        """Absolute offsets where a lane's stripe begins (boundary fix-ups)."""
        return np.arange(1, self.lanes, dtype=np.int64) * self.chunk


def choose_layout(
    n_bytes: int,
    target_lanes: int = 1024,
    min_chunk: int = 256,
    lane_multiple: int = 8,
    chunk_multiple: int = 8,
    quantize_chunk: bool = False,
) -> Layout:
    """Pick (lanes, chunk) for a document: enough lanes to fill the VPU,
    chunks long enough that the sequential scan amortizes its step cost.
    lane_multiple/chunk_multiple let kernels impose tile shapes (the Pallas
    path needs lanes % 4096 == 0 and chunk % 512 == 0).

    ``quantize_chunk`` rounds the chunk UP to a 4-mantissa-bit grid, so a
    job over arbitrarily-sized splits produces O(log) distinct padded
    shapes instead of one per ``chunk_multiple``-byte size step — every
    distinct shape jit-specializes the scan kernel (~20-40 s through a
    tunneled TPU), so the engine bounds compiles at the cost of <= 1/8
    extra '\\n' padding on tail segments (scanned at kernel speed, and
    full 64 MB segments land exactly on the grid unchanged)."""
    if n_bytes <= 0:
        return Layout(lanes=lane_multiple, chunk=chunk_multiple, n_real=max(0, n_bytes))
    lanes = max(lane_multiple, target_lanes // lane_multiple * lane_multiple)
    while lanes > lane_multiple and (n_bytes + lanes - 1) // lanes < min_chunk:
        lanes = max(lane_multiple, lanes // 2 // lane_multiple * lane_multiple)
    chunk = (n_bytes + lanes - 1) // lanes
    if quantize_chunk:
        q = 1 << max(0, chunk.bit_length() - 4)
        chunk = (chunk + q - 1) // q * q
    chunk = (chunk + chunk_multiple - 1) // chunk_multiple * chunk_multiple
    return Layout(lanes=lanes, chunk=chunk, n_real=n_bytes)


def to_device_array(data: bytes, layout: Layout) -> np.ndarray:
    """Pad with '\\n' and reshape column-major: result[c, l] = data[l*chunk+c]."""
    buf = np.full(layout.padded, NL, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    return np.ascontiguousarray(buf.reshape(layout.lanes, layout.chunk).T)


# ----------------------------------------------------- cross-file batching
#
# The many-small-files regime (grep -r over a source tree): every file
# below device_min_bytes would pay a full dispatch round-trip on its own,
# so the scan never reaches the kernels at all.  Packing many
# newline-terminated blobs into ONE buffer amortizes a single dispatch
# across all of them — exactly Hyperscan's one-database-many-payloads
# amortization and MapReduce's small-inputs-into-splits batching.
#
# Why the packed scan is exact at file granularity: every blob is
# terminated with '\n' in the packed buffer (synthesized when the file
# lacks one — which adds no line: grep -n counts the unterminated tail as
# a line already), so no line ever spans a file boundary.  Every DFA
# table's '\n' column is the start state (the invariant stripe/segment
# boundaries already rely on), '^' sees a true line start at each file's
# first byte, '$' sees a true line end at each file's last line, the
# approx recurrence resets its rows at '\n' (an errorful match can never
# span a newline), and the filter families' host confirm/stitch pass
# operates per line — lines are bit-identical to the per-file layout, so
# the per-file verdicts are too.  Demux is pure line arithmetic over the
# cumulative per-file line counts.


# The default packing window, shared by every site that opts into
# batching (GrepEngine's cap fallback, the CLI's cfg.batch_bytes): one
# constant, so the "one packed dispatch per window" contract cannot
# drift between direct engine users and CLI jobs.  32 MB ≈ half a scan
# segment: big enough to amortize dispatch across thousands of small
# files, small enough that a batch never adds a second segment compile.
DEFAULT_BATCH_BYTES = 32 << 20


DEFAULT_DEVICE_MIN_BYTES = 1 << 20


def env_device_min_bytes(fallback: int = DEFAULT_DEVICE_MIN_BYTES) -> int:
    """Parse the DGREP_DEVICE_MIN_BYTES override, ONE way for its two
    readers (GrepEngine's small-input host branch and the map-split
    planner's "small file" bound, runtime/job.plan_map_splits): unset or
    unparseable -> ``fallback``.  A divergent parse would let the planner
    batch files the engine then refuses to treat as small — same failure
    mode env_batch_bytes below guards for the packing window."""
    import os

    env = os.environ.get("DGREP_DEVICE_MIN_BYTES")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass  # malformed override: both readers fall back identically
    return fallback


def env_batch_bytes(fallback: int = DEFAULT_BATCH_BYTES) -> int:
    """Parse the DGREP_BATCH_BYTES override, ONE way for its two readers
    (GrepEngine's packing cap and JobConfig.effective_batch_bytes — the
    map-split planner): unset or unparseable -> ``fallback``, else the
    clamped integer (0 disables).  A divergent parse would let the
    planner hand out batched splits whose worker engines then crash on
    the same env var."""
    import os

    env = os.environ.get("DGREP_BATCH_BYTES")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass  # malformed override: both readers fall back identically
    return fallback


@dataclass
class PackedBatch:
    """One packed scan buffer plus the per-file offset tables to demux it."""

    data: bytes  # concatenated newline-terminated blobs
    names: list  # caller-supplied per-file identifiers, input order
    blobs: list  # the ORIGINAL blobs (no synthesized terminator)
    # cumulative tables, length len(names)+1 with [0] == 0:
    byte_starts: np.ndarray  # packed byte offset where each file begins
    # (demux below is pure LINE arithmetic — byte_starts exists for
    # diagnostics and future byte-addressed consumers like -o/-b)
    line_starts: np.ndarray  # packed line count before each file begins

    def __len__(self) -> int:
        return len(self.names)

    def demux(self, matched_lines: np.ndarray) -> list[np.ndarray]:
        """Split packed-buffer 1-based matched line numbers (sorted, as a
        ScanResult carries them) into per-file LOCAL 1-based line arrays,
        input order.  File i owns global lines
        (line_starts[i], line_starts[i+1]]."""
        matched = np.asarray(matched_lines, dtype=np.int64)
        splits = np.searchsorted(matched, self.line_starts, side="right")
        return [
            matched[splits[i] : splits[i + 1]] - self.line_starts[i]
            for i in range(len(self.names))
        ]


def packed_size(blob: bytes) -> int:
    """Bytes `blob` occupies in a packed buffer: its length plus the
    synthesized '\\n' terminator when it lacks one.  Empty blobs occupy
    zero bytes — appending a terminator would manufacture a phantom empty
    line that '^$'-style patterns would match."""
    if not blob:
        return 0
    return len(blob) + (0 if blob.endswith(b"\n") else 1)


class BatchPacker:
    """Accumulate small newline-terminated blobs for one packed dispatch.

    ``add`` never splits a blob across batches: callers check ``fits``
    first and flush (``pack``) when the next blob would overflow
    ``max_bytes``.  ``pack`` returns the PackedBatch and resets the packer
    for the next round."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._names: list = []
        self._blobs: list = []
        self._total = 0  # packed bytes including synthesized terminators

    def __len__(self) -> int:
        return len(self._names)

    def fits(self, blob: bytes) -> bool:
        """Whether `blob` joins the CURRENT batch: always true for the
        first blob (a blob is never split), else capacity-bounded."""
        return not self._names or self._total + packed_size(blob) <= self.max_bytes

    def add(self, name, blob: bytes) -> None:
        self._names.append(name)
        self._blobs.append(blob)
        self._total += packed_size(blob)

    def pack(self) -> PackedBatch | None:
        """Build the packed buffer + offset tables; None when empty."""
        if not self._names:
            return None
        names, blobs = self._names, self._blobs
        self._names, self._blobs, self._total = [], [], 0
        pieces: list[bytes] = []
        byte_starts = np.zeros(len(names) + 1, dtype=np.int64)
        line_starts = np.zeros(len(names) + 1, dtype=np.int64)
        pos = 0
        lines = 0
        for i, blob in enumerate(blobs):
            byte_starts[i] = pos
            line_starts[i] = lines
            if blob:
                pieces.append(blob)
                n = packed_size(blob)
                if n > len(blob):
                    pieces.append(b"\n")
                pos += n
                # grep -n line count: every packed blob ends with '\n', so
                # the count is exactly its newline count
                lines += blob.count(b"\n") + (0 if blob.endswith(b"\n") else 1)
        byte_starts[-1] = pos
        line_starts[-1] = lines
        return PackedBatch(
            data=b"".join(pieces), names=names, blobs=blobs,
            byte_starts=byte_starts, line_starts=line_starts,
        )

"""Stripe layout: document bytes -> (chunk, lanes) device array.

The scan is lane-parallel: the document is cut into ``lanes`` contiguous
stripes, each lane scans its stripe sequentially (lax.scan over the chunk
axis), and all lanes run as one vector op per byte step.  Because the DFA
resets to line-start on '\\n', every lane can start from the start state;
the only error is each stripe's first partial line, which lines.py
re-scans exactly on the host.

Padding uses '\\n' bytes: the pattern can never consume '\\n', so padding
can't create matches inside real lines; phantom empty padding lines are
clamped away by lines.py (they sit past the real data's last offset).

Layout is column-major for the scan: array[c, l] = byte c of stripe l, so
lax.scan iterates the leading axis with unit-stride vectors.
"""

from __future__ import annotations

from collections import OrderedDict as _OrderedDict
from dataclasses import dataclass, field

import numpy as np

from distributed_grep_tpu.utils import lockdep as _lockdep

NL = 0x0A


@dataclass(frozen=True)
class Layout:
    lanes: int
    chunk: int  # bytes per lane
    n_real: int  # real (unpadded) document length

    @property
    def padded(self) -> int:
        return self.lanes * self.chunk

    def offset_of(self, c: int, l: int) -> int:
        """Absolute byte offset of array position (chunk c, lane l)."""
        return l * self.chunk + c

    def stripe_starts(self) -> np.ndarray:
        """Absolute offsets where a lane's stripe begins (boundary fix-ups)."""
        return np.arange(1, self.lanes, dtype=np.int64) * self.chunk


def choose_layout(
    n_bytes: int,
    target_lanes: int = 1024,
    min_chunk: int = 256,
    lane_multiple: int = 8,
    chunk_multiple: int = 8,
    quantize_chunk: bool = False,
) -> Layout:
    """Pick (lanes, chunk) for a document: enough lanes to fill the VPU,
    chunks long enough that the sequential scan amortizes its step cost.
    lane_multiple/chunk_multiple let kernels impose tile shapes (the Pallas
    path needs lanes % 4096 == 0 and chunk % 512 == 0).

    ``quantize_chunk`` rounds the chunk UP to a 4-mantissa-bit grid, so a
    job over arbitrarily-sized splits produces O(log) distinct padded
    shapes instead of one per ``chunk_multiple``-byte size step — every
    distinct shape jit-specializes the scan kernel (~20-40 s through a
    tunneled TPU), so the engine bounds compiles at the cost of <= 1/8
    extra '\\n' padding on tail segments (scanned at kernel speed, and
    full 64 MB segments land exactly on the grid unchanged)."""
    if n_bytes <= 0:
        return Layout(lanes=lane_multiple, chunk=chunk_multiple, n_real=max(0, n_bytes))
    lanes = max(lane_multiple, target_lanes // lane_multiple * lane_multiple)
    while lanes > lane_multiple and (n_bytes + lanes - 1) // lanes < min_chunk:
        lanes = max(lane_multiple, lanes // 2 // lane_multiple * lane_multiple)
    chunk = (n_bytes + lanes - 1) // lanes
    if quantize_chunk:
        q = 1 << max(0, chunk.bit_length() - 4)
        chunk = (chunk + q - 1) // q * q
    chunk = (chunk + chunk_multiple - 1) // chunk_multiple * chunk_multiple
    return Layout(lanes=lanes, chunk=chunk, n_real=n_bytes)


def to_device_array(data: bytes, layout: Layout) -> np.ndarray:
    """Pad with '\\n' and reshape column-major: result[c, l] = data[l*chunk+c]."""
    buf = np.full(layout.padded, NL, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    return np.ascontiguousarray(buf.reshape(layout.lanes, layout.chunk).T)


# ----------------------------------------------------- cross-file batching
#
# The many-small-files regime (grep -r over a source tree): every file
# below device_min_bytes would pay a full dispatch round-trip on its own,
# so the scan never reaches the kernels at all.  Packing many
# newline-terminated blobs into ONE buffer amortizes a single dispatch
# across all of them — exactly Hyperscan's one-database-many-payloads
# amortization and MapReduce's small-inputs-into-splits batching.
#
# Why the packed scan is exact at file granularity: every blob is
# terminated with '\n' in the packed buffer (synthesized when the file
# lacks one — which adds no line: grep -n counts the unterminated tail as
# a line already), so no line ever spans a file boundary.  Every DFA
# table's '\n' column is the start state (the invariant stripe/segment
# boundaries already rely on), '^' sees a true line start at each file's
# first byte, '$' sees a true line end at each file's last line, the
# approx recurrence resets its rows at '\n' (an errorful match can never
# span a newline), and the filter families' host confirm/stitch pass
# operates per line — lines are bit-identical to the per-file layout, so
# the per-file verdicts are too.  Demux is pure line arithmetic over the
# cumulative per-file line counts.


# The default packing window, shared by every site that opts into
# batching (GrepEngine's cap fallback, the CLI's cfg.batch_bytes): one
# constant, so the "one packed dispatch per window" contract cannot
# drift between direct engine users and CLI jobs.  32 MB ≈ half a scan
# segment: big enough to amortize dispatch across thousands of small
# files, small enough that a batch never adds a second segment compile.
DEFAULT_BATCH_BYTES = 32 << 20


DEFAULT_DEVICE_MIN_BYTES = 1 << 20


def env_device_min_bytes(fallback: int = DEFAULT_DEVICE_MIN_BYTES) -> int:
    """Parse the DGREP_DEVICE_MIN_BYTES override, ONE way for its two
    readers (GrepEngine's small-input host branch and the map-split
    planner's "small file" bound, runtime/job.plan_map_splits): unset or
    unparseable -> ``fallback``.  A divergent parse would let the planner
    batch files the engine then refuses to treat as small — same failure
    mode env_batch_bytes below guards for the packing window."""
    import os

    env = os.environ.get("DGREP_DEVICE_MIN_BYTES")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass  # malformed override: both readers fall back identically
    return fallback


def env_batch_bytes(fallback: int = DEFAULT_BATCH_BYTES) -> int:
    """Parse the DGREP_BATCH_BYTES override, ONE way for its two readers
    (GrepEngine's packing cap and JobConfig.effective_batch_bytes — the
    map-split planner): unset or unparseable -> ``fallback``, else the
    clamped integer (0 disables).  A divergent parse would let the
    planner hand out batched splits whose worker engines then crash on
    the same env var."""
    import os

    env = os.environ.get("DGREP_BATCH_BYTES")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass  # malformed override: both readers fall back identically
    return fallback


@dataclass
class PackedBatch:
    """One packed scan buffer plus the per-file offset tables to demux it."""

    data: bytes  # concatenated newline-terminated blobs
    names: list  # caller-supplied per-file identifiers, input order
    blobs: list | None  # the ORIGINAL blobs (no synthesized terminator);
    # None on cache-slimmed copies (without_blobs) — member bytes then
    # reconstruct on demand as slices of ``data``
    # cumulative tables, length len(names)+1 with [0] == 0:
    byte_starts: np.ndarray  # packed byte offset where each file begins
    # (demux below is pure LINE arithmetic — byte_starts exists for
    # diagnostics and future byte-addressed consumers like -o/-b)
    line_starts: np.ndarray  # packed line count before each file begins
    blob_lens: np.ndarray | None = None  # ORIGINAL member byte lengths,
    # set on slimmed copies (a packed piece is the original bytes plus a
    # possibly-synthesized '\n' — the packed span alone cannot tell
    # whether the final newline was original)

    def __len__(self) -> int:
        return len(self.names)

    def member_blobs(self) -> list:
        """The ORIGINAL member blobs: as stored, or (slimmed copies)
        reconstructed as transient slices of ``data`` — alive only for
        the scan that asked, never pinned."""
        if self.blobs is not None:
            return self.blobs
        return [
            self.data[int(s) : int(s) + int(n)]
            for s, n in zip(self.byte_starts[:-1], self.blob_lens)
        ]

    def without_blobs(self) -> "PackedBatch":
        """Copy for cache residency that does NOT pin the member blobs
        (they would double a cached window's host footprint alongside
        ``data``); records the original lengths so member_blobs() can
        slice them back out."""
        if self.blobs is None:
            return self
        return PackedBatch(
            data=self.data, names=self.names, blobs=None,
            byte_starts=self.byte_starts, line_starts=self.line_starts,
            blob_lens=np.asarray(
                [len(b) for b in self.blobs], dtype=np.int64
            ),
        )

    def demux(self, matched_lines: np.ndarray) -> list[np.ndarray]:
        """Split packed-buffer 1-based matched line numbers (sorted, as a
        ScanResult carries them) into per-file LOCAL 1-based line arrays,
        input order.  File i owns global lines
        (line_starts[i], line_starts[i+1]]."""
        matched = np.asarray(matched_lines, dtype=np.int64)
        splits = np.searchsorted(matched, self.line_starts, side="right")
        return [
            matched[splits[i] : splits[i + 1]] - self.line_starts[i]
            for i in range(len(self.names))
        ]


def packed_size(blob: bytes) -> int:
    """Bytes `blob` occupies in a packed buffer: its length plus the
    synthesized '\\n' terminator when it lacks one.  Empty blobs occupy
    zero bytes — appending a terminator would manufacture a phantom empty
    line that '^$'-style patterns would match."""
    if not blob:
        return 0
    return len(blob) + (0 if blob.endswith(b"\n") else 1)


class BatchPacker:
    """Accumulate small newline-terminated blobs for one packed dispatch.

    ``add`` never splits a blob across batches: callers check ``fits``
    first and flush (``pack``) when the next blob would overflow
    ``max_bytes``.  ``pack`` returns the PackedBatch and resets the packer
    for the next round."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._names: list = []
        self._blobs: list = []
        self._total = 0  # packed bytes including synthesized terminators

    def __len__(self) -> int:
        return len(self._names)

    def fits(self, blob: bytes) -> bool:
        """Whether `blob` joins the CURRENT batch: always true for the
        first blob (a blob is never split), else capacity-bounded."""
        return not self._names or self._total + packed_size(blob) <= self.max_bytes

    def add(self, name, blob: bytes) -> None:
        self._names.append(name)
        self._blobs.append(blob)
        self._total += packed_size(blob)

    def pack(self) -> PackedBatch | None:
        """Build the packed buffer + offset tables; None when empty."""
        if not self._names:
            return None
        names, blobs = self._names, self._blobs
        self._names, self._blobs, self._total = [], [], 0
        pieces: list[bytes] = []
        byte_starts = np.zeros(len(names) + 1, dtype=np.int64)
        line_starts = np.zeros(len(names) + 1, dtype=np.int64)
        pos = 0
        lines = 0
        for i, blob in enumerate(blobs):
            byte_starts[i] = pos
            line_starts[i] = lines
            if blob:
                pieces.append(blob)
                n = packed_size(blob)
                if n > len(blob):
                    pieces.append(b"\n")
                pos += n
                # grep -n line count: every packed blob ends with '\n', so
                # the count is exactly its newline count
                lines += blob.count(b"\n") + (0 if blob.endswith(b"\n") else 1)
        byte_starts[-1] = pos
        line_starts[-1] = lines
        return PackedBatch(
            data=b"".join(pieces), names=names, blobs=blobs,
            byte_starts=byte_starts, line_starts=line_starts,
        )


# ------------------------------------------------ device corpus cache
#
# The service regime (runtime/service.py: log search / code search, many
# queries over the same corpus) repeats the whole data path per query:
# read from disk, pack/pad the stripe layout on host, upload segments to
# HBM — while the scan kernel itself is ~12% of a dense job's wall
# (BASELINE round 6).  The model cache (ops/engine.cached_engine) answers
# "same pattern"; this cache answers "same data": packed/padded device
# segments stay resident across queries, keyed by content identity +
# the layout parameters they were packed under, so a warm query scans
# the resident arrays directly — no file read, no to_device_array pack,
# no upload.  The layout quantizer (choose_layout(quantize_chunk=True))
# bounds distinct padded shapes to O(log), so resident shards are
# reusable across engines and their jit keys converge.
#
# Correctness: the content key carries a FRESH os.stat of every member
# (realpath + size + mtime_ns + inode, taken by the caller in the same
# call that scans), and lookups revalidate the stored entry against it —
# an in-place modification changes size or mtime_ns, an atomic
# replacement (mv/rename, even one that preserves size AND mtime, e.g.
# `cp -p` + mv or a timestamp-preserving tar extract) changes the
# inode; either way revalidation fails and evicts the entry, so stale
# bytes can never be served.  Entries also keep the
# HOST bytes (the confirm/stitch pass and matched-line emit read them),
# so the real footprint is ~2x the device budget; DGREP_CORPUS_BYTES
# budgets the DEVICE-resident bytes and LRU-evicts whole entries beyond
# it.  Pattern-dependent state never enters an entry — FDR retunes and
# model-cache invalidations leave corpus entries alone by construction.

# Default device budget when jax's default backend is a real accelerator
# and neither DGREP_CORPUS_BYTES nor the engine's corpus_bytes= is set.
# On CPU backends the default is OFF (0): CI and plain host runs keep
# their exact pre-cache behavior unless a budget is asked for.
DEFAULT_CORPUS_BYTES_ACCEL = 1 << 30


def env_corpus_bytes() -> int | None:
    """Parse the DGREP_CORPUS_BYTES override, ONE way for every reader
    (the engine's budget resolution — ops/engine._corpus_budget): unset
    or unparseable -> None (the engine then sizes by backend: 0 on CPU,
    DEFAULT_CORPUS_BYTES_ACCEL on accelerators), else the clamped
    integer (0 disables)."""
    import os

    env = os.environ.get("DGREP_CORPUS_BYTES")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass  # malformed override behaves as unset, everywhere
    return None


@dataclass(frozen=True)
class CorpusKey:
    """Content identity of one cacheable input: a file, or a packed
    batch window over several files.  ``validators`` carry the stat
    snapshot (size, mtime_ns, inode) per member, taken at key-derivation
    time — lookups compare them against the cached entry (hit-time stat
    revalidation; the inode catches a same-size, mtime-preserving atomic
    replacement that size+mtime alone would miss)."""

    identity: tuple  # ("file", realpath) | ("pack", (realpath, ...))
    validators: tuple  # ((size, mtime_ns, ino), ...), one per member

    @property
    def n_bytes(self) -> int:
        return sum(v[0] for v in self.validators)


def file_content_key(path) -> CorpusKey | None:
    """CorpusKey for a filesystem path from a FRESH stat, or None when
    the path cannot be statted (the scan then proceeds uncached)."""
    import os

    try:
        real = os.path.realpath(os.fspath(path))
        st = os.stat(real)
    except OSError:
        return None
    return CorpusKey(
        identity=("file", real),
        validators=(
            (int(st.st_size), int(st.st_mtime_ns), int(st.st_ino)),
        ),
    )


def batch_content_key(member_keys) -> CorpusKey | None:
    """CorpusKey for a packed batch window: the ordered member file
    identities, validators concatenated.  None when any member lacks a
    key (mixed bytes/path windows stay uncached)."""
    keys = list(member_keys)
    if not keys or any(k is None for k in keys):
        return None
    return CorpusKey(
        identity=("pack", tuple(k.identity for k in keys)),
        validators=tuple(v for k in keys for v in k.validators),
    )


@dataclass
class ResidentCorpus:
    """One cached input: host bytes + per-layout-sig device segments.

    ``variants`` maps a layout signature (segment size + the
    choose_layout kwargs the device scan packed under — computed in
    ops/device_scan from the SAME values its prepare step uses, so the
    key cannot drift from the layout) to the resident segment list
    [(seg_start, Layout, device_array, device)].  ``batch`` optionally
    holds the PackedBatch whose .data these bytes are (scan_batch demux
    tables + original member blobs, so a warm packed window emits
    per-file records without re-reading members)."""

    key: CorpusKey
    data: bytes
    variants: dict = field(default_factory=dict)
    batch: PackedBatch | None = None
    device_bytes: int = 0
    # Shard-index summary (distributed_grep_tpu/index): the trigram bloom
    # of ``data``, attached by the engine AFTER the scan that built it
    # succeeded — resident next to the bytes it summarizes, so a warm
    # entry answers "can this query match here?" without any store read.
    summary: bytes | None = None


def _segments_nbytes(segments) -> int:
    total = 0
    for _start, lay, arr, _dev in segments:
        total += int(getattr(arr, "nbytes", lay.padded))
    return total


class CorpusCache:
    """Process-global LRU of ResidentCorpus entries, byte-budgeted over
    their DEVICE-resident segment bytes.  Thread-safe: lookups/puts run
    under one lock (dict surgery only — no I/O, no device work; the
    stat that feeds revalidation happens at key derivation, outside)."""

    def __init__(self):
        self._lock = _lockdep.make_lock("corpus-cache")
        self._entries: "_OrderedDict[tuple, ResidentCorpus]" = _OrderedDict()
        self._bytes = 0
        # first-member file identity -> packed-window entry identity:
        # scan_batch's warm-window probe (recognize a cached window from
        # its first upcoming path item BEFORE reading any member)
        self._windows: dict = {}
        self._stats = {
            "corpus_cache_hits": 0,
            "corpus_cache_misses": 0,
            "corpus_cache_evictions": 0,
            # host-bytes serves (scan_file / scan_batch warm paths):
            # counted separately from the device-variant hits above —
            # a host-routed engine (mode "re"/"native", or a demoted
            # device engine) serves ent.data without ever reaching
            # scan_device's resident_segments verdict, and would
            # otherwise read as an idle cache in /status while doing
            # real work; on a device engine a warm scan increments BOTH
            # (host bytes served + resident segments served)
            "corpus_cache_host_hits": 0,
        }
        # lock-free counters() fast path: False until the cache is first
        # touched (verdict counted or entry published).  engine.scan()
        # polls counters() once per scan — on hosts where the cache is
        # permanently off that poll must not take a process-global lock
        # per chunk per thread.  Plain attribute: CPython reads/writes
        # are atomic, and the worst case of a stale False is one scan's
        # telemetry reading {} at the exact moment of first touch —
        # indistinguishable from ordering the scans the other way.
        self._touched = False

    # ------------------------------------------------------------- internals
    def _evict_locked(self, identity) -> None:
        ent = self._entries.pop(identity, None)
        if ent is not None:
            self._bytes -= ent.device_bytes
            self._stats["corpus_cache_evictions"] += 1
            if ent.key.identity[0] == "pack":
                first = ent.key.identity[1][0]
                if self._windows.get(first) == identity:
                    del self._windows[first]

    def _lookup_locked(self, key: CorpusKey) -> ResidentCorpus | None:
        ent = self._entries.get(key.identity)
        if ent is None:
            return None
        if ent.key.validators != key.validators:
            # hit-time stat revalidation: the caller's key carries a
            # fresh stat — any size/mtime_ns/inode drift means the
            # content changed and the resident bytes are stale
            self._evict_locked(key.identity)
            return None
        self._entries.move_to_end(key.identity)
        return ent

    # --------------------------------------------------------------- lookups
    def lookup(self, key: CorpusKey | None) -> ResidentCorpus | None:
        """Revalidated entry for ``key`` (LRU-touched), or None.  Does
        NOT count hit/miss — the per-scan verdict is counted once, at
        the segment-variant level (resident_segments), so a warm
        scan_file's data lookup + its device-variant hit read as ONE
        hit, not two."""
        if key is None:
            return None
        with self._lock:
            return self._lookup_locked(key)

    def resident_segments(self, key: CorpusKey, sig: tuple):
        """The resident segment list for (key, layout sig) or None;
        counts the scan-level hit/miss verdict."""
        with self._lock:
            self._touched = True
            ent = self._lookup_locked(key)
            segs = None if ent is None else ent.variants.get(sig)
            if segs is None:
                self._stats["corpus_cache_misses"] += 1
            else:
                self._stats["corpus_cache_hits"] += 1
            return segs

    def count_host_hit(self) -> None:
        """Record one warm host-bytes serve (scan_file / scan_batch read
        ent.data instead of the disk).  Separate from the hit/miss
        verdict: on device engines the same scan ALSO reaches
        resident_segments, and host-routed engines never do — one
        counter per distinct event keeps both visible without
        double-counting either."""
        with self._lock:
            self._touched = True
            self._stats["corpus_cache_host_hits"] += 1

    # ------------------------------------------------------------------ puts
    def put_segments(
        self, key: CorpusKey, sig: tuple, data: bytes, segments, budget: int
    ) -> None:
        """Insert/replace the (key, sig) variant and LRU-evict whole
        entries until device bytes fit ``budget``.  A variant whose OWN
        device bytes exceed the whole budget is DECLINED outright: it
        could never stay resident, and admitting it would LRU-evict
        every smaller tenant before it evicted itself.  This is the
        authoritative check — the caller's raw-input gate (ops/
        device_scan) under-counts padding, so the raw<=budget<padded
        band lands here.  (A stale same-key entry left behind by a
        decline is caught by the next lookup's revalidation.)"""
        new_bytes = _segments_nbytes(segments)
        if new_bytes > max(0, budget):
            return
        with self._lock:
            self._touched = True
            ent = self._entries.get(key.identity)
            if ent is not None and ent.key.validators != key.validators:
                self._evict_locked(key.identity)
                ent = None
            if ent is None:
                ent = ResidentCorpus(key=key, data=data)
                self._entries[key.identity] = ent
            old = ent.variants.get(sig)
            if old is not None:  # concurrent same-key scans: last wins
                delta = _segments_nbytes(old)
                ent.device_bytes -= delta
                self._bytes -= delta
            ent.variants[sig] = list(segments)
            ent.device_bytes += new_bytes
            self._bytes += new_bytes
            self._entries.move_to_end(key.identity)
            cap = max(0, budget)
            if self._bytes > cap and len(ent.variants) > 1:
                # Over-budget with sibling variants on THIS entry (the
                # same content packed under another layout sig — e.g. a
                # Pallas family vs the DFA banks): drop the siblings
                # before any whole-entry eviction.  The LRU loop below
                # would otherwise reach this just-touched entry last
                # and wipe it INCLUDING the variant just built —
                # alternating engine families would thrash the cache to
                # a permanent miss.
                for other in [s for s in ent.variants if s != sig]:
                    delta = _segments_nbytes(ent.variants.pop(other))
                    ent.device_bytes -= delta
                    self._bytes -= delta
                    self._stats["corpus_cache_evictions"] += 1
                    if self._bytes <= cap:
                        break
            while self._bytes > cap and self._entries:
                oldest = next(iter(self._entries))
                self._evict_locked(oldest)

    def attach_batch(self, key: CorpusKey | None, batch: PackedBatch) -> None:
        """Record the PackedBatch behind an entry's bytes (scan_batch
        warm demux + member blobs) and index the window by its first
        member; no-op when the entry was not admitted (host-scanned
        window, over-budget, no key).  Stored SLIMMED (without_blobs):
        the member blobs would pin a second full host copy of the
        window alongside entry.data — warm scans slice them back out
        of the packed bytes transiently instead."""
        if key is None:
            return
        slim = batch.without_blobs()
        with self._lock:
            ent = self._entries.get(key.identity)
            if ent is not None and ent.key.validators == key.validators:
                ent.batch = slim
                if key.identity[0] == "pack":
                    # last wins on collision (same first file packed into
                    # a different window, e.g. a changed batch cap) — the
                    # probe's membership revalidation makes a stale index
                    # row a clean miss, never a wrong answer
                    self._windows[key.identity[1][0]] = key.identity

    def attach_summary(self, key: CorpusKey | None, summary: bytes) -> None:
        """Record the shard-index trigram summary behind an entry's bytes
        (same no-op-when-absent contract as attach_batch: a window that
        was never admitted simply keeps its summary in the index tier's
        own cache/store)."""
        if key is None:
            return
        with self._lock:
            ent = self._entries.get(key.identity)
            if ent is not None and ent.key.validators == key.validators:
                ent.summary = summary

    def window_for(self, member_key: CorpusKey | None) -> CorpusKey | None:
        """The STORED key of a cached packed window whose first member is
        ``member_key``'s file, or None.  The caller re-derives fresh keys
        for every member and looks the window up with those — this only
        answers "which files would I need" without touching the disk."""
        if member_key is None:
            return None
        with self._lock:
            wid = self._windows.get(member_key.identity)
            ent = self._entries.get(wid) if wid is not None else None
            if ent is None or ent.batch is None:
                return None
            return ent.key

    # ------------------------------------------------------------- telemetry
    def counters(self) -> dict:
        """Copy of the counters + the bytes_resident gauge, or {} when
        the cache was never touched (zero-activity processes never grow
        stats/piggyback keys — same contract as model_cache_counters).
        The never-touched answer is LOCK-FREE: engine.scan() polls this
        once per scan, and on hosts with the cache permanently off that
        poll must not serialize worker threads on a process-global
        mutex."""
        if not self._touched:
            return {}
        with self._lock:
            if not any(self._stats.values()) and not self._entries:
                return {}
            out = dict(self._stats)
            out["corpus_cache_bytes_resident"] = self._bytes
            return out

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._windows.clear()
            self._bytes = 0
            for k in self._stats:
                self._stats[k] = 0
            self._touched = False


_corpus_cache = CorpusCache()


def corpus_cache() -> CorpusCache:
    """The process-global corpus cache (cross-job by design, like the
    compiled-model cache — a service process WANTS shards shared)."""
    return _corpus_cache


def corpus_cache_counters() -> dict:
    return _corpus_cache.counters()


def corpus_cache_clear() -> None:
    """Drop every resident entry and zero the counters (tests)."""
    _corpus_cache.clear()

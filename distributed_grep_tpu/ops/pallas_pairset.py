"""Pallas TPU kernel: exact 1-2-byte literal-set scan (models/pairset.py).

Same shell as ops/pallas_fdr.py (lanes x chunk tiles, time-packed uint32
match words, VMEM carry across chunk blocks), but the per-byte step is the
exact row-partition pair check — no bucket pipeline, no confirm:

    rc  = rowcls[cls_byte]        256-domain lane lookup (2 gathers)
    w   = words[word_byte]        256-domain lane lookup (2 gathers)
    hit = (w >> rc) & 1           exact pair/single membership

(cls_byte, word_byte) = (prev, cur) or (cur, prev) per the model's
orientation.  The prev carry is seeded '\\n' at stripe starts: members
never contain newlines, so stripe heads can only UNDER-report (engine
boundary stitching restores boundary-spanning pairs) — the output words
are otherwise EXACT match-end offsets, decoded with the standard
ops/sparse helpers.

4 gathers/byte puts this in the same measured class as a small FDR plan
(~40-60 GB/s/chip; kernel_compare.py `pairset` entry) — the device
engine for the all-short sets the engine previously had to route to the
native host scanner.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.pairset import NL, PairsetModel
from distributed_grep_tpu.ops import pallas_scan
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    SUBLANES,
    available,
    validate_unroll,
)

UNROLL = 32  # probed on v5e (2026-07-30, interleaved A/B x3): full unroll
# wins at every trial (49.5-54.7 GB/s vs 45.6-53.5 at unroll=8) — this
# kernel has no pipeline carries to pressure registers (unlike the
# gather-heavy FDR plans that prefer 4), so the word loop flattens best


def eligible(model: PairsetModel) -> bool:
    return model.n_classes <= 32  # construction guarantees it; guard anyway


def device_tables(model: PairsetModel) -> np.ndarray:
    """(4, SUBLANES, LANE_COLS) uint32: [rowcls_lo, rowcls_hi, words_lo,
    words_hi] — each 256-entry table split into two 128-lane subtables
    broadcast across sublanes (the kernel's lane-gather unit)."""
    rows = [
        model.rowcls[:128], model.rowcls[128:],
        model.words[:128], model.words[128:],
    ]
    sub = np.stack([r.astype(np.uint32) for r in rows])
    tiles = np.broadcast_to(sub[:, None, :], (4, SUBLANES, LANE_COLS))
    return np.ascontiguousarray(tiles)


def _kernel(data_ref, tabs_ref, out_ref, prev_ref, *, steps, transposed,
            fold_case, unroll):
    from jax.experimental import pallas as pl  # deferred: import cost

    validate_unroll(unroll)
    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        # '\n' seed: stripe heads under-report boundary-spanning pairs
        # (stitched on host) and never false-positive
        prev_ref[...] = jnp.full_like(prev_ref, jnp.uint32(NL))

    zero = jnp.uint32(0)
    n_inner = 32 // unroll

    def lookup(tab_lo, tab_hi, idx):
        lo = idx & (LANE_COLS - 1)
        hi = idx >> 7
        g0 = jnp.take_along_axis(tab_lo, lo, axis=1)
        g1 = jnp.take_along_axis(tab_hi, lo, axis=1)
        m1 = zero - hi.astype(jnp.uint32)  # all-ones where idx >= 128
        return (g0 & ~m1) | (g1 & m1)

    def word_body(w, carry):
        def sub_body(s, inner):
            prev_b, word = inner
            for tt in range(unroll):
                b = data_ref[w * 32 + s * unroll + tt].astype(jnp.int32)
                if fold_case:
                    b = jnp.where((b >= 65) & (b <= 90), b + 32, b)
                cls_idx, word_idx = (
                    (b, prev_b) if transposed else (prev_b, b)
                )
                rc = lookup(tabs_ref[0], tabs_ref[1], cls_idx)
                wv = lookup(tabs_ref[2], tabs_ref[3], word_idx)
                hit = (wv >> rc) & jnp.uint32(1)
                bit = jnp.uint32(1 << tt) << (s * jnp.uint32(unroll))
                word = word | jnp.where(hit != 0, bit, zero)
                prev_b = b
            return (prev_b, word)

        prev_b = carry
        word0 = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        if n_inner == 1:
            prev_b, word = sub_body(0, (prev_b, word0))
        else:
            prev_b, word = jax.lax.fori_loop(0, n_inner, sub_body, (prev_b, word0))
        out_ref[w] = word
        return prev_b

    final = jax.lax.fori_loop(
        0, steps // 32, word_body, prev_ref[...].astype(jnp.int32)
    )
    prev_ref[...] = final.astype(jnp.uint32)


@functools.partial(
    jax.jit,
    static_argnames=("chunk", "lane_blocks", "transposed", "fold_case",
                     "interpret", "unroll"),
)
def _pairset_pallas(data, tabs, *, chunk, lane_blocks, transposed,
                    fold_case=False, interpret=False, unroll=UNROLL):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    kernel = functools.partial(
        _kernel, steps=steps, transposed=transposed, fold_case=fold_case,
        unroll=unroll,
    )
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (4, SUBLANES, LANE_COLS),
                lambda li, ci: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32)],
        interpret=interpret,
    )(data, tabs)


def pairset_scan_words(
    arr_cl: np.ndarray,
    model: PairsetModel,
    dev_tables=None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Run the exact short-set scan; returns time-packed MATCH words (not
    candidates) in the shared device convention — decode end offsets via
    ops/sparse.offsets_from_sparse_words.  ``dev_tables`` lets the engine
    upload device_tables(model) once and reuse across segments."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0"
        )
    if not eligible(model):
        raise ValueError("pairset model outside the kernel budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = pallas_scan.as_tiles(arr_cl, lane_blocks)
    if dev_tables is None:
        dev_tables = jnp.asarray(device_tables(model))
    if interpret is None:
        interpret = not available()
    return _pairset_pallas(
        data,
        dev_tables,
        chunk=chunk,
        lane_blocks=lane_blocks,
        transposed=model.transposed,
        fold_case=model.ignore_case,
        interpret=interpret,
    )

"""Pallas TPU kernel: bit-parallel Glushkov NFA scan (general regex).

Same shell as ops/pallas_scan.py (layout, grid, time-packed uint32 match
words, VMEM state scratch carried across chunk blocks) but the per-byte
recurrence is the position-automaton step from models/nfa.py:

    reached = init_float                       (unanchored Sigma* restart)
            | (prev_nl ? init_anchor : 0)      ('^' starts, line-start only)
            | ((D & chain_src) << 1)           (concat runs — one shift/word)
            | OR_specials (D[p] ? follow[p] : 0)
    D       = reached & B[byte]
    match   = (D & final) != 0

B[byte] comes from one of two modes, chosen by measured cost crossover
(use_gather_b): per-class range compares for small/simple patterns, or
per-state-word 256-entry tables fetched with 128-lane ``take_along_axis``
gathers (the ops/pallas_fdr.py primitive) for class-heavy patterns, where
compare counts scale with the alphabet but the gather cost is fixed per
word.  Either way general regex (alternations, classes, bounded repeats,
'^') runs at Pallas speeds instead of the XLA lax.scan DFA path's
~0.1 GB/s (the gap that motivated this kernel;
benchmarks/kernel_compare.py).

The select trick: a per-position select is (0 - ((D >> j) & 1)) & mask —
an all-ones/all-zero uint32 mask from one bit, avoiding jnp.where's
bool plumbing in the hot loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.nfa import GlushkovModel
from distributed_grep_tpu.ops import pallas_scan
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    SUBLANES,
    available,
    validate_unroll,
)

NL = 0x0A
# Compare/select budget per byte step; beyond this the unrolled kernel body
# compiles slowly and the XLA DFA path (or host) is the better engine.
MAX_COST = 160


def _b_cost_compare(model: GlushkovModel) -> int:
    return model.total_ranges + sum(len(pw) for pw in model.cls_pos_words)


def _b_cost_gather(model: GlushkovModel) -> int:
    # per word: two 128-entry lane gathers + select — but a gather is worth
    # several plain VPU ops.  Calibrated on v5e (2026-07-30): the 8-word
    # alternation (compare cost 54) ran 33 -> 116 GB/s with gathers, while
    # compare cost 19 ran 34 -> 26 (compare wins).
    return 12 * model.n_words


# measured crossover: compare-B wins at compare cost 19, gather-B at 54
GATHER_B_THRESHOLD = 32


def use_gather_b(model: GlushkovModel) -> bool:
    """Fetch B[byte] from per-word 256-entry tables via lane gathers when
    the per-class range compares get expensive — alternation-heavy patterns
    have many classes (compares scale with them), while the gather cost is
    fixed per state word (the same primitive ops/pallas_fdr.py rides)."""
    return _b_cost_compare(model) > max(GATHER_B_THRESHOLD, _b_cost_gather(model))


def kernel_cost(model: GlushkovModel) -> int:
    """Rough per-byte op count — eligibility metric.  Mirrors the dispatch:
    charge the B-mode the kernel will actually run."""
    b_cost = _b_cost_gather(model) if use_gather_b(model) else _b_cost_compare(model)
    special_cost = sum(2 + len(f) for _, _, f in model.specials)
    return b_cost + special_cost + 4 * model.n_words


def unroll_for(model: GlushkovModel) -> int:
    """Byte-steps per fori sub-block.  v5e probe (2026-07-30, config-4
    1-word filter model, slope-timed): full unroll wins for single-word
    compare-B models (65/71/69/73 GB/s at 4/8/16/32) — their live state is
    a couple of vregs, like the shift-and kernel.  Multi-word and gather-B
    models keep the round-2 probed 16 (register pressure)."""
    return 32 if (model.n_words == 1 and not use_gather_b(model)) else 16


def eligible(model: GlushkovModel) -> bool:
    return kernel_cost(model) <= MAX_COST


def build_b_tables(model: GlushkovModel) -> np.ndarray:
    """(n_words * 2, SUBLANES, LANE_COLS) uint32 — per state word, the
    256-entry B[byte] table split into lo/hi 128-lane subtables, broadcast
    across sublanes (the ops/pallas_fdr.py table convention)."""
    full = np.zeros((model.n_words, 256), dtype=np.uint32)
    for ranges, pos_words in zip(model.cls_ranges, model.cls_pos_words):
        for wi, m in pos_words:
            for lo, hi in ranges:
                full[wi, lo : hi + 1] |= np.uint32(m)
    sub = full.reshape(model.n_words * 2, LANE_COLS)
    tiles = np.broadcast_to(
        sub[:, None, :], (model.n_words * 2, SUBLANES, LANE_COLS)
    )
    return np.ascontiguousarray(tiles)


# Byte steps unrolled per fori iteration.  v5e sweep (2026-07-30): the
# 2-word config-4 kernel runs ~10% faster at unroll=16 than fully unrolled
# (33.4/32.6 vs 30.3 GB/s over repeated runs); 1-word kernels show no
# consistent preference above the tunnel noise.  Same register-pressure
# effect the FDR kernel showed (ops/pallas_fdr.unroll_for).
def _kernel(data_ref, *refs, plan, steps, gather_b, unroll=16):
    from jax.experimental import pallas as pl  # deferred: import cost

    validate_unroll(unroll)

    if gather_b:
        tabs_ref, out_ref, d_ref, nl_ref = refs
    else:
        out_ref, d_ref, nl_ref = refs
    (n_words, classes, chain_src, specials, init_float, init_anchor,
     final_words, anchored) = plan
    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        d_ref[...] = jnp.zeros_like(d_ref)
        nl_ref[...] = jnp.ones_like(nl_ref)  # stripe start = line start

    zero = jnp.uint32(0)

    n_inner = 32 // unroll

    def word_body(w, carry):
        def sub_body(sx, inner):
            word, *d, prev_nl = inner
            for tt in range(unroll):
                b = data_ref[w * 32 + sx * unroll + tt].astype(jnp.int32)  # (32, 128)
                if gather_b:
                    # ---- B[byte] per state word, via 128-lane table gathers
                    lo_idx = b & 127
                    hi_sel = zero - (b >= 128).astype(jnp.uint32)  # all-ones hi
                    bmask = []
                    for wi in range(n_words):
                        g_lo = jnp.take_along_axis(tabs_ref[wi * 2], lo_idx, axis=1)
                        g_hi = jnp.take_along_axis(tabs_ref[wi * 2 + 1], lo_idx, axis=1)
                        bmask.append((g_hi & hi_sel) | (g_lo & ~hi_sel))
                else:
                    # ---- B[byte] per state word, via per-class range compares
                    bmask = [zero] * n_words
                    for ranges, pos_words in classes:
                        hit = None
                        for lo, hi in ranges:
                            r = (b >= lo) & (b <= hi) if lo != hi else (b == lo)
                            hit = r if hit is None else (hit | r)
                        hit_m = zero - hit.astype(jnp.uint32)  # all-ones where hit
                        for wi, m in pos_words:
                            bmask[wi] = bmask[wi] | (hit_m & jnp.uint32(m))
                # ---- reached = init | chains | specials
                reached = [jnp.full((SUBLANES, LANE_COLS), f, dtype=jnp.uint32)
                           for f in init_float]
                if anchored:
                    nl_m = zero - prev_nl  # all-ones after a newline
                    for wi in range(n_words):
                        if init_anchor[wi]:
                            reached[wi] = reached[wi] | (nl_m & jnp.uint32(init_anchor[wi]))
                for wi in range(n_words):
                    if chain_src[wi]:
                        reached[wi] = reached[wi] | (
                            (d[wi] & jnp.uint32(chain_src[wi])) << jnp.uint32(1)
                        )
                for wp, jp, flist in specials:
                    bit = (d[wp] >> jnp.uint32(jp)) & jnp.uint32(1)
                    sel = zero - bit
                    for wi, m in flist:
                        reached[wi] = reached[wi] | (sel & jnp.uint32(m))
                # ---- step + match
                d = [reached[wi] & bmask[wi] for wi in range(n_words)]
                acc = d[0] & jnp.uint32(final_words[0])
                for wi in range(1, n_words):
                    acc = acc | (d[wi] & jnp.uint32(final_words[wi]))
                word = word | jnp.where(acc != 0, jnp.uint32(1 << tt) << (sx * jnp.uint32(unroll)), zero)
                if anchored:
                    prev_nl = (b == NL).astype(jnp.uint32)
            return (word, *d, prev_nl)

        *d, prev_nl = carry
        word0 = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        if n_inner == 1:
            out = sub_body(0, (word0, *d, prev_nl))
        else:
            out = jax.lax.fori_loop(0, n_inner, sub_body, (word0, *d, prev_nl))
        word, *d, prev_nl = out
        out_ref[w] = word
        return (*d, prev_nl)

    carry0 = tuple(d_ref[wi] for wi in range(n_words)) + (nl_ref[...],)
    final_carry = jax.lax.fori_loop(0, steps // 32, word_body, carry0)
    for wi in range(n_words):
        d_ref[wi] = final_carry[wi]
    nl_ref[...] = final_carry[-1]


@functools.partial(
    jax.jit,
    static_argnames=(
        "plan", "chunk", "lane_blocks", "gather_b", "interpret", "unroll"
    ),
)
def _nfa_pallas(data, b_tabs=None, *, plan, chunk, lane_blocks, gather_b=False,
                interpret=False, unroll=16):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    n_words = plan[0]
    kernel = functools.partial(_kernel, plan=plan, steps=steps, gather_b=gather_b, unroll=unroll)
    in_specs = [
        pl.BlockSpec(
            (steps, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        )
    ]
    args = (data,)
    if gather_b:
        in_specs.append(
            pl.BlockSpec(
                (n_words * 2, SUBLANES, LANE_COLS),
                lambda li, ci: (0, 0, 0),
                memory_space=pltpu.VMEM,
            )
        )
        args = (data, b_tabs)
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[
            pltpu.VMEM((n_words, SUBLANES, LANE_COLS), jnp.uint32),
            pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32),
        ],
        interpret=interpret,
    )(*args)


def nfa_scan_words(
    arr_cl: np.ndarray, model: GlushkovModel, interpret: bool | None = None
) -> jnp.ndarray:
    """Run the kernel; returns time-packed match words as a DEVICE array
    (chunk//32, lane_blocks*32, 128) uint32 — the exact convention of
    pallas_scan.shift_and_scan_words, so sparse decode
    (ops/sparse.offsets_from_sparse_words) is shared."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0"
        )
    if not eligible(model):
        raise ValueError("pattern exceeds the pallas NFA cost budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = pallas_scan.as_tiles(arr_cl, lane_blocks)
    if interpret is None:
        interpret = not available()
    gather_b = use_gather_b(model)
    return _nfa_pallas(
        data,
        jnp.asarray(build_b_tables(model)) if gather_b else None,
        plan=model.kernel_plan(),
        chunk=chunk,
        lane_blocks=lane_blocks,
        gather_b=gather_b,
        interpret=interpret,
        unroll=unroll_for(model),
    )


def nfa_scan(
    arr_cl: np.ndarray, model: GlushkovModel, interpret: bool | None = None
) -> np.ndarray:
    """Dense-output wrapper (tests): packed bits in the scan_jnp convention."""
    from distributed_grep_tpu.ops.pallas_scan import _unpack_words_to_lane_bits

    chunk, lanes = arr_cl.shape
    words = nfa_scan_words(arr_cl, model, interpret)
    return _unpack_words_to_lane_bits(np.asarray(words), chunk, lanes)

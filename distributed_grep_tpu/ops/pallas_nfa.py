"""Pallas TPU kernel: bit-parallel Glushkov NFA scan (general regex).

Same shell as ops/pallas_scan.py (layout, grid, time-packed uint32 match
words, VMEM state scratch carried across chunk blocks) but the per-byte
recurrence is the position-automaton step from models/nfa.py:

    reached = init_float                       (unanchored Sigma* restart)
            | (prev_nl ? init_anchor : 0)      ('^' starts, line-start only)
            | ((D & chain_src) << 1)           (concat runs — one shift/word)
            | OR_specials (D[p] ? follow[p] : 0)
    D       = reached & B[byte]                (B via per-class range compares)
    match   = (D & final) != 0

Everything is uint32 tile bit-ops and compares — no gathers, so general
regex (alternations, classes, bounded repeats, '^') runs at Pallas speeds
instead of the XLA lax.scan DFA path's ~0.1 GB/s (the gap that motivated
this kernel; benchmarks/kernel_compare.py).

The select trick: a per-position select is (0 - ((D >> j) & 1)) & mask —
an all-ones/all-zero uint32 mask from one bit, avoiding jnp.where's
bool plumbing in the hot loop.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.nfa import GlushkovModel
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    SUBLANES,
    available,
)

NL = 0x0A
# Compare/select budget per byte step; beyond this the unrolled kernel body
# compiles slowly and the XLA DFA path (or host) is the better engine.
MAX_COST = 160


def kernel_cost(model: GlushkovModel) -> int:
    """Rough per-byte op count — eligibility metric."""
    b_cost = model.total_ranges + sum(len(pw) for pw in model.cls_pos_words)
    special_cost = sum(2 + len(f) for _, _, f in model.specials)
    return b_cost + special_cost + 4 * model.n_words


def eligible(model: GlushkovModel) -> bool:
    return kernel_cost(model) <= MAX_COST


def _kernel(data_ref, out_ref, d_ref, nl_ref, *, plan, steps):
    from jax.experimental import pallas as pl  # deferred: import cost

    (n_words, classes, chain_src, specials, init_float, init_anchor,
     final_words, anchored) = plan
    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        d_ref[...] = jnp.zeros_like(d_ref)
        nl_ref[...] = jnp.ones_like(nl_ref)  # stripe start = line start

    zero = jnp.uint32(0)

    def word_body(w, carry):
        *d, prev_nl = carry
        word = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        for t in range(32):
            b = data_ref[w * 32 + t].astype(jnp.int32)  # (32, 128)
            # ---- B[byte] per state word, via per-class range compares
            bmask = [zero] * n_words
            for ranges, pos_words in classes:
                hit = None
                for lo, hi in ranges:
                    r = (b >= lo) & (b <= hi) if lo != hi else (b == lo)
                    hit = r if hit is None else (hit | r)
                hit_m = zero - hit.astype(jnp.uint32)  # all-ones where hit
                for wi, m in pos_words:
                    bmask[wi] = bmask[wi] | (hit_m & jnp.uint32(m))
            # ---- reached = init | chains | specials
            reached = [jnp.full((SUBLANES, LANE_COLS), f, dtype=jnp.uint32)
                       for f in init_float]
            if anchored:
                nl_m = zero - prev_nl  # all-ones after a newline
                for wi in range(n_words):
                    if init_anchor[wi]:
                        reached[wi] = reached[wi] | (nl_m & jnp.uint32(init_anchor[wi]))
            for wi in range(n_words):
                if chain_src[wi]:
                    reached[wi] = reached[wi] | (
                        (d[wi] & jnp.uint32(chain_src[wi])) << jnp.uint32(1)
                    )
            for wp, jp, flist in specials:
                bit = (d[wp] >> jnp.uint32(jp)) & jnp.uint32(1)
                sel = zero - bit
                for wi, m in flist:
                    reached[wi] = reached[wi] | (sel & jnp.uint32(m))
            # ---- step + match
            d = [reached[wi] & bmask[wi] for wi in range(n_words)]
            acc = d[0] & jnp.uint32(final_words[0])
            for wi in range(1, n_words):
                acc = acc | (d[wi] & jnp.uint32(final_words[wi]))
            word = word | jnp.where(acc != 0, jnp.uint32(1 << t), zero)
            if anchored:
                prev_nl = (b == NL).astype(jnp.uint32)
        out_ref[w] = word
        return (*d, prev_nl)

    carry0 = tuple(d_ref[wi] for wi in range(n_words)) + (nl_ref[...],)
    final_carry = jax.lax.fori_loop(0, steps // 32, word_body, carry0)
    for wi in range(n_words):
        d_ref[wi] = final_carry[wi]
    nl_ref[...] = final_carry[-1]


@functools.partial(
    jax.jit, static_argnames=("plan", "chunk", "lane_blocks", "interpret")
)
def _nfa_pallas(data, *, plan, chunk, lane_blocks, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    n_words = plan[0]
    kernel = functools.partial(_kernel, plan=plan, steps=steps)
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[
            pltpu.VMEM((n_words, SUBLANES, LANE_COLS), jnp.uint32),
            pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32),
        ],
        interpret=interpret,
    )(data)


def nfa_scan_words(
    arr_cl: np.ndarray, model: GlushkovModel, interpret: bool | None = None
) -> jnp.ndarray:
    """Run the kernel; returns time-packed match words as a DEVICE array
    (chunk//32, lane_blocks*32, 128) uint32 — the exact convention of
    pallas_scan.shift_and_scan_words, so sparse decode
    (ops/sparse.offsets_from_sparse_words) is shared."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0"
        )
    if not eligible(model):
        raise ValueError("pattern exceeds the pallas NFA cost budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = np.ascontiguousarray(
        arr_cl.reshape(chunk, lane_blocks * SUBLANES, LANE_COLS)
    )
    if interpret is None:
        interpret = not available()
    return _nfa_pallas(
        jnp.asarray(data),
        plan=model.kernel_plan(),
        chunk=chunk,
        lane_blocks=lane_blocks,
        interpret=interpret,
    )


def nfa_scan(
    arr_cl: np.ndarray, model: GlushkovModel, interpret: bool | None = None
) -> np.ndarray:
    """Dense-output wrapper (tests): packed bits in the scan_jnp convention."""
    from distributed_grep_tpu.ops.pallas_scan import _unpack_words_to_lane_bits

    chunk, lanes = arr_cl.shape
    words = nfa_scan_words(arr_cl, model, interpret)
    return _unpack_words_to_lane_bits(np.asarray(words), chunk, lanes)

"""Pallas TPU kernel: Shift-And byte scan, gather-free, bit-packed output.

The hot loop the reference runs per line on a Raspberry Pi CPU
(application/grep.go:20-30) becomes a VPU-resident bit-parallel scan:

* Input bytes live in HBM as (chunk, 32, 128) uint8 — 4096 lanes per grid
  block, each lane a contiguous document stripe; blocks of rows are DMA'd
  to VMEM by pallas_call's grid machinery (double-buffered by the
  compiler).
* Per byte step the kernel computes the Shift-And B-mask from the byte
  value with **range compares** (the pattern's per-symbol byte sets as
  (lo, hi) ranges, baked into the kernel as compile-time constants) — no
  table gather, which Pallas TPU does not have — then performs
  ``s = ((s << 1) | 1) & B`` on a (32, 128) uint32 state tile.
* Match bits are packed on the fly, 32 byte-steps per uint32 word, so the
  HBM write traffic is input/32 and the host transfer is tiny.
* The lane state persists in VMEM scratch across sequential grid steps
  along the chunk axis (TPU grids execute sequentially, innermost last),
  so a stripe longer than one block carries its automaton state exactly.

Grid: (lane_blocks, chunk_blocks); chunk innermost.  The engine sizes the
layout so lanes % 4096 == 0 and chunk % (32 * CHUNK_BLOCK_WORDS) == 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.shift_and import ShiftAndModel

SUBLANES = 32  # uint8 tile sublanes; 32*128 = 4096 lanes per grid block
LANE_COLS = 128
LANES_PER_BLOCK = SUBLANES * LANE_COLS
CHUNK_BLOCK_WORDS = 16  # byte-steps per grid block = 32 * this
MAX_TOTAL_RANGES = 48  # compare budget per byte step


def as_tiles(arr_cl, lane_blocks: int) -> jnp.ndarray:
    """(chunk, lanes) -> (chunk, lane_blocks*32, 128) kernel tiles.

    Accepts a host ndarray (copied contiguous, uploaded by the caller's
    jnp.asarray) OR an already-device-resident jnp array — the engine's
    double-buffered feed uploads segment i+1 while segment i scans, and the
    reshape is then a free on-device bitcast (row-major contiguous)."""
    chunk = arr_cl.shape[0]
    if isinstance(arr_cl, jnp.ndarray):
        return arr_cl.reshape(chunk, lane_blocks * SUBLANES, LANE_COLS)
    return jnp.asarray(np.ascontiguousarray(
        arr_cl.reshape(chunk, lane_blocks * SUBLANES, LANE_COLS)
    ))


def validate_unroll(unroll: int) -> None:
    """Kernels unroll byte steps in sub-blocks of a 32-step word; a factor
    that does not divide 32 would silently skip the tail bytes of every
    word (silent false negatives), so reject it at trace time."""
    if not (1 <= unroll <= 32 and 32 % unroll == 0):
        raise ValueError(f"unroll must divide 32: {unroll}")


def available() -> bool:
    """True when a real TPU backend is present (tests use interpret=True).

    Checks JAX_PLATFORMS before touching jax so that a CPU-pinned test
    environment never triggers initialization of a TPU/axon backend (which
    can block indefinitely if the device tunnel is unavailable)."""
    import os

    platforms = os.environ.get("JAX_PLATFORMS", "")
    if platforms and "tpu" not in platforms and "axon" not in platforms:
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def eligible(model: ShiftAndModel) -> bool:
    return model.total_ranges <= MAX_TOTAL_RANGES


# unroll: byte steps per fori sub-block.  v5e sweep (2026-07-30): this
# kernel prefers FULL unroll (232/230 GB/s at 32 vs 218/207 at 8 on the
# 3-class filtered 'volcano') — its live state is one vreg pair, so the
# register pressure that pushes the FDR/NFA kernels to unroll 4-16 never
# materializes here.
def _kernel(data_ref, out_ref, state_ref, *, sym_ranges, match_bit, steps, coarse,
            unroll=32):
    """One grid step: scan `steps` bytes for 4096 lanes.

    Output per 32-byte word, two modes:

    * exact  — bit t set iff a match ends at byte t (the original packing);
      costs ~4 extra vector ops per byte for the per-position test+pack.
    * coarse — the word is nonzero iff ANY candidate match ends inside its
      32-byte span (the running state ORs into an accumulator; one mask
      per word).  For a full model spans are exact (no span-level false
      positives); for a rare-class filtered model (wildcard positions,
      models/shift_and.filtered_for_device) spans are a superset.  Either
      way the engine confirms the span's line(s) on host, overlapped with
      the next segment's scan — coarse words are candidates, never final
      output.
      Measured on v5e (2026-07-30): 139 -> ~290 GB/s for a 7-symbol
      literal; the exact per-byte pack was ~40% of the kernel's ALU work.
    """
    from jax.experimental import pallas as pl  # deferred: import cost

    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        state_ref[:] = jnp.zeros_like(state_ref)

    # Symbols sharing a byte-class share one compare: "needle" has 4
    # distinct classes across 6 positions, so its B-mask build costs 4
    # compares + 4 selects instead of 6 + 6 (repeated letters are the norm
    # in real patterns; the compare loop dominates the kernel's ALU work).
    # Positions with an EMPTY range list are wildcards (the rare-class
    # device filter, models/shift_and.filtered_for_device): their bits are
    # a compile-time constant OR — zero ALU cost per byte.
    groups: dict[tuple, int] = {}
    wildcard = 0
    for j, ranges in enumerate(sym_ranges):
        if not ranges:
            wildcard |= 1 << j
            continue
        groups[tuple(ranges)] = groups.get(tuple(ranges), 0) | (1 << j)
    range_groups = tuple(groups.items())

    n_inner = 32 // unroll

    def word_body(w, carry):
        def sub_body(sx, inner):
            word, s = inner
            for tt in range(unroll):
                b = data_ref[w * 32 + sx * unroll + tt].astype(jnp.int32)
                bmask = jnp.full((SUBLANES, LANE_COLS), jnp.uint32(wildcard))
                for ranges, mask in range_groups:
                    hit = None
                    for lo, hi in ranges:
                        r = (b >= lo) & (b <= hi) if lo != hi else (b == lo)
                        hit = r if hit is None else (hit | r)
                    bmask = bmask | jnp.where(hit, jnp.uint32(mask), jnp.uint32(0))
                s = ((s << jnp.uint32(1)) | jnp.uint32(1)) & bmask
                if coarse:
                    word = word | s
                else:
                    m = (s & jnp.uint32(match_bit)) != 0
                    bit = jnp.uint32(1 << tt) << (sx * jnp.uint32(unroll))
                    word = word | jnp.where(m, bit, jnp.uint32(0))
            return word, s

        word0 = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        if n_inner == 1:
            word, s = sub_body(0, (word0, carry))
        else:
            word, s = jax.lax.fori_loop(0, n_inner, sub_body, (word0, carry))
        out_ref[w] = (word & jnp.uint32(match_bit)) if coarse else word
        return s

    final = jax.lax.fori_loop(0, steps // 32, word_body, state_ref[:])
    state_ref[:] = final


@functools.partial(
    jax.jit,
    static_argnames=(
        "sym_ranges", "match_bit", "chunk", "lane_blocks", "interpret", "coarse",
        "unroll",
    ),
)
def _shift_and_pallas(data, *, sym_ranges, match_bit, chunk, lane_blocks,
                      interpret=False, coarse=False, unroll=32):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    validate_unroll(unroll)
    kernel = functools.partial(
        _kernel, sym_ranges=sym_ranges, match_bit=match_bit, steps=steps,
        coarse=coarse, unroll=unroll,
    )
    out = pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32)],
        interpret=interpret,
    )(data)
    return out


def shift_and_scan_words(
    arr_cl: np.ndarray,
    model: ShiftAndModel,
    interpret: bool | None = None,
    coarse: bool = False,
) -> jnp.ndarray:
    """Run the kernel; returns packed words as a DEVICE array
    (chunk//32, lane_blocks*32, 128) uint32.

    ``coarse=False``: bit t of a word = match ends at that byte — decode
    via ops/sparse.offsets_from_sparse_words.  ``coarse=True``: a word is
    nonzero iff some candidate match ends in its 32-byte span (~2x kernel
    throughput; exact at span granularity for full models, a superset for
    rare-class filtered ones) — decode via
    ops/sparse.span_starts_from_sparse_words and CONFIRM the span's lines
    (mandatory for filtered models).

    Requires lanes % 4096 == 0 and chunk % 512 == 0 (the engine's layout
    guarantees this on the pallas path).
    """
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0")
    if not eligible(model):
        raise ValueError("pattern exceeds the pallas compare budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = as_tiles(arr_cl, lane_blocks)
    if interpret is None:
        interpret = not available()
    return _shift_and_pallas(
        data,
        sym_ranges=tuple(tuple(r) for r in model.sym_ranges),
        match_bit=int(model.match_bit),
        chunk=chunk,
        lane_blocks=lane_blocks,
        interpret=interpret,
        coarse=coarse,
    )


def shift_and_scan(
    arr_cl: np.ndarray, model: ShiftAndModel, interpret: bool | None = None
) -> np.ndarray:
    """Dense-output wrapper (tests): packed bits in the scan_jnp convention."""
    chunk, lanes = arr_cl.shape
    words = shift_and_scan_words(arr_cl, model, interpret)
    return _unpack_words_to_lane_bits(np.asarray(words), chunk, lanes)


# ------------------------------------------------------ SWAR packed variant
#
# Four stripes per u32 lane element: the (chunk, lanes) u8 corpus bitcasts
# (free, on device) to (chunk, lanes//4) u32 where byte k of element j is
# stripe 4j+k's byte at this chunk position.  Each stripe's automaton
# lives in its own byte of the u32 state tile (SWAR_MAX_SYMBOLS: state +
# match bit fit 8 bits), so one (32, 128) vector op advances 16384
# stripes' automata — 4 corpus bytes per i32 lane element where the base
# kernel moves one (the ALU roofline the round-6 probe targets).
#
# Per-byte-class hit detection is the EXACT SWAR zero-byte test on
# y = x ^ (v * 0x01010101):
#
#   t  = y | ((y | 0x80808080) - 0x01010101)   # bit 7 of byte k clear
#                                              # iff y's byte k == 0; no
#                                              # cross-byte borrows (each
#                                              # minuend byte >= 0x80)
#   nz = ~t & 0x80808080                       # 0x80 flag per hit byte
#
# (NOT the classic Mycroft `(y - 1) & ~y & 0x80` form, whose borrows can
# false-flag a byte after a hit — still a candidate superset, but the
# probe's bit-exactness bar and the defeat guards want exact words.)
# Flags become per-byte B-mask contributions borrow-free:
#
#   (nz - (nz >> 7)) & (mask * 0x01010101)     # 0x7F at hits, then mask
#   nz & 0x80808080                            # bit-7 mask positions
#
# and the state step needs no cross-byte guard: the only leak of
# `s << 1` lands on bit 0 of the next byte, which `| 0x01010101`
# overwrites anyway.
#
# Output is COARSE only (the production literal path): one u32 word per
# 32 byte-steps per PACKED lane, byte k's match bit = "a candidate match
# ends in this 32-byte span of stripe 4j+k" — decode via
# ops/sparse.span_starts_from_packed_words.

SWAR_LANES_PER_BLOCK = 4 * LANES_PER_BLOCK  # corpus stripes per grid block


def swar_eligible(model: ShiftAndModel) -> bool:
    from distributed_grep_tpu.models.shift_and import swar_values

    return swar_values(model) is not None


def swar_enabled() -> bool:
    """DGREP_SWAR=1 routes eligible shift-and scans through the packed
    kernel.  Default OFF: the variant is interpret-validated bit-exact
    (tests/test_fuzz_swar.py) and op-count analysis predicts ~1.5x over
    the unpacked coarse kernel, but no real-chip slope receipt exists yet
    (the axon tunnel was absent the round it landed — BASELINE.md round
    6); flip the default only with a measured win."""
    import os

    return os.environ.get("DGREP_SWAR", "") == "1"


def _swar_kernel(data_ref, out_ref, state_ref, *, sym_values, match_bit,
                 steps, unroll=32):
    from jax.experimental import pallas as pl  # deferred: import cost

    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        state_ref[:] = jnp.zeros_like(state_ref)

    ONE = jnp.uint32(0x01010101)
    HI = jnp.uint32(0x80808080)

    # Symbols sharing a value set share one detection chain, exactly like
    # the unpacked kernel's range groups; wildcards are a compile-time OR.
    groups: dict[tuple, int] = {}
    wildcard = 0
    for j, vals in enumerate(sym_values):
        if not vals:
            wildcard |= 1 << j
            continue
        groups[vals] = groups.get(vals, 0) | (1 << j)
    group_list = tuple(groups.items())
    wild_rep = jnp.uint32(wildcard * 0x01010101)
    match_rep = jnp.uint32(match_bit * 0x01010101)

    n_inner = 32 // unroll

    def word_body(w, carry):
        def sub_body(sx, inner):
            word, s = inner
            for tt in range(unroll):
                x = data_ref[w * 32 + sx * unroll + tt]
                bmask = jnp.full((SUBLANES, LANE_COLS), wild_rep)
                for vals, mask in group_list:
                    t = None
                    for v in vals:
                        y = x ^ jnp.uint32(v * 0x01010101)
                        tv = y | ((y | HI) - ONE)
                        t = tv if t is None else (t & tv)  # OR of hits
                    nz = ~t & HI
                    m7f = mask & 0x7F
                    if m7f:
                        bmask = bmask | (
                            (nz - (nz >> jnp.uint32(7)))
                            & jnp.uint32(m7f * 0x01010101)
                        )
                    if mask & 0x80:
                        bmask = bmask | nz  # bit-7 position: flags ARE it
                s = ((s << jnp.uint32(1)) | ONE) & bmask
                word = word | s
            return word, s

        word0 = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        if n_inner == 1:
            word, s = sub_body(0, (word0, carry))
        else:
            word, s = jax.lax.fori_loop(0, n_inner, sub_body, (word0, carry))
        out_ref[w] = word & match_rep
        return s

    final = jax.lax.fori_loop(0, steps // 32, word_body, state_ref[:])
    state_ref[:] = final


@functools.partial(
    jax.jit,
    static_argnames=(
        "sym_values", "match_bit", "chunk", "lane_blocks", "interpret",
        "unroll",
    ),
)
def _swar_pallas(data, *, sym_values, match_bit, chunk, lane_blocks,
                 interpret=False, unroll=32):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    validate_unroll(unroll)
    kernel = functools.partial(
        _swar_kernel, sym_values=sym_values, match_bit=match_bit,
        steps=steps, unroll=unroll,
    )
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32)],
        interpret=interpret,
    )(data)


def swar_pack_tiles(arr_cl, lane_blocks: int) -> jnp.ndarray:
    """(chunk, lanes) u8 -> (chunk, lane_blocks*32, 128) u32 packed tiles:
    element [t, ...] byte k = stripe 4j+k's byte t.  On an already-device
    array this is a reshape + bitcast (free); host arrays pack via a
    little-endian u32 view."""
    chunk, lanes = arr_cl.shape
    if isinstance(arr_cl, jnp.ndarray):
        u32 = jax.lax.bitcast_convert_type(
            arr_cl.reshape(chunk, lanes // 4, 4), jnp.uint32
        )
        return u32.reshape(chunk, lane_blocks * SUBLANES, LANE_COLS)
    packed = np.ascontiguousarray(arr_cl).view("<u4")
    return jnp.asarray(np.ascontiguousarray(
        packed.reshape(chunk, lane_blocks * SUBLANES, LANE_COLS)
    ))


def swar_shift_and_scan_words(
    arr_cl,
    model: ShiftAndModel,
    interpret: bool | None = None,
    unroll: int = 32,
) -> jnp.ndarray:
    """Run the SWAR packed kernel; returns coarse words as a DEVICE array
    (chunk//32, lane_blocks*32, 128) uint32 over PACKED lanes — byte k's
    match bit of word [w, j] = candidate in stripe 4j+k's span w.  Decode
    via ops/sparse.span_starts_from_packed_words and confirm lines (the
    span_words contract).  Requires lanes % 16384 == 0, chunk % 512 == 0,
    and a swar_values-eligible model."""
    from distributed_grep_tpu.models.shift_and import swar_values

    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % SWAR_LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"swar layout needs lanes%{SWAR_LANES_PER_BLOCK}==0, "
            f"chunk%{steps}==0"
        )
    vals = swar_values(model)
    if vals is None:
        raise ValueError("pattern ineligible for the SWAR packed kernel")
    lane_blocks = lanes // SWAR_LANES_PER_BLOCK
    data = swar_pack_tiles(arr_cl, lane_blocks)
    if interpret is None:
        interpret = not available()
    return _swar_pallas(
        data,
        sym_values=tuple(vals),
        match_bit=int(model.match_bit),
        chunk=chunk,
        lane_blocks=lane_blocks,
        interpret=interpret,
        unroll=unroll,
    )


def _unpack_words_to_lane_bits(words: np.ndarray, chunk: int, lanes: int) -> np.ndarray:
    """Convert time-packed kernel words to the (chunk, lanes//8) lane-packed
    convention shared with scan_jnp (bit t of words[w, s, l] = match at
    chunk position w*32+t for lane (s // 32)*4096? — see reshape below)."""
    # words: (chunk//32, lane_blocks*32, 128) uint32; lane id of (S, l):
    # block = S // 32, sublane = S % 32 -> lane = block*4096 + sublane*128 + l
    n_words, S, L = words.shape
    lane_blocks = S // SUBLANES
    # bits along time: expand to (chunk, S, L) bool
    t = np.arange(32, dtype=np.uint32)
    bits = (words[:, None, :, :] >> t[None, :, None, None]) & 1  # (w, t, S, L)
    match = bits.reshape(chunk, S, L).astype(bool)
    # lane index mapping
    match = match.reshape(chunk, lane_blocks, SUBLANES, LANE_COLS)
    match = match.reshape(chunk, lanes)
    return np.packbits(match, axis=1, bitorder="little")

"""TPU compute path: byte-scan engines and line machinery.

The reference's compute hot loop is a per-line regexp.Match on the host
(application/grep.go:20-30).  Here the whole corpus is scanned on device:

* ``layout``      — bytes -> (lanes, chunk) stripe layout with '\\n' padding;
* ``scan_jnp``    — XLA engines: vectorized DFA table scan and bit-parallel
                    Shift-And scan, lane-parallel with per-lane sequential
                    chunks (lax.scan over byte columns);
* ``pallas_scan`` — Pallas TPU kernel for the Shift-And fast path;
* ``lines``       — host-side: packed match bits -> byte offsets -> line
                    numbers, plus exact stitching of lines that span lane
                    boundaries (the long-context correctness story,
                    SURVEY.md §5);
* ``engine``      — ties a compiled pattern model + engine + stitching into
                    one ``scan(data) -> matched lines`` object.
"""

from distributed_grep_tpu.ops.engine import GrepEngine, make_engine

__all__ = ["GrepEngine", "make_engine"]

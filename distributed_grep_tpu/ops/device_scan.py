"""Device-scan orchestration for GrepEngine (split out of ops/engine.py,
round 5 — VERDICT r4 item 6: the 2,100-line engine monolith).

This module owns the per-segment device dispatch pipeline — segment
prepare/feed double-buffering, the kernel-route selection, the collect
pool with its confirm/dense-guard/defeat closures, stall walls, and the
FDR self-calibration trio — operating on a ``GrepEngine`` instance
(``eng``).  The engine keeps thin delegate methods so the public surface
(and the tests poking ``eng._scan_device`` / ``eng._maybe_retune_fdr``)
is unchanged.  No behavior change: the code is the engine's, moved.
"""

from __future__ import annotations

import os as _os
import threading as _threading_mod
import time as _time_mod

import numpy as np

from distributed_grep_tpu.models.fdr import FdrError, compile_fdr
from distributed_grep_tpu.ops import lines as lines_mod
from distributed_grep_tpu.utils import spans as spans_mod
from distributed_grep_tpu.ops import engine as _engine_mod
from distributed_grep_tpu.ops.engine import (
    ScanResult,
    _accepts_grace_kwarg,
    _is_transport_error,
    log,
)

# Tunables (DEVICE_STALL_S, COMPILE_GRACE_S, SPAN_CONFIRM_LINE_LIMIT) are
# read THROUGH the engine module at each use, not from-imported: tests and
# operators monkeypatch them on ops.engine, and a binding copy here would
# silently detach this module from those overrides (found the hard way —
# the stall-wall test froze at the production 300 s after the split).



class _DeviceStall(TimeoutError):
    """Raised when a collect/feed wait exceeds DEVICE_STALL_S — a DISTINCT
    type so the recovery handler cannot confuse the wall with a transient
    transport timeout surfacing from inside a device call (socket.timeout
    is an alias of builtin TimeoutError since 3.10; those must keep the
    ordinary kernel-retry chain, not a permanent device demotion)."""


def _await_wall(fut):
    """fut.result() bounded by the stall wall; converts the futures
    timeout (its own type on 3.10, the builtin alias on 3.11+) into
    _DeviceStall so the except net can identify the wall precisely."""
    from concurrent.futures import TimeoutError as _FutTimeout

    try:
        return fut.result(timeout=_engine_mod.DEVICE_STALL_S)
    except (_FutTimeout, TimeoutError) as e:
        raise _DeviceStall(
            f"no collect/feed progress within {_engine_mod.DEVICE_STALL_S:.0f}s"
        ) from e


class _DaemonPool:
    """Minimal executor whose workers are DAEMON threads.

    The stdlib ThreadPoolExecutor's workers are non-daemon (Py>=3.9) and
    joined by threading._shutdown at interpreter exit, so ONE worker
    blocked forever inside a dead device transport would hang process
    shutdown — verified empirically; no registry surgery avoids that
    join.  Daemon workers simply die with the process.  API subset used
    by _scan_device: submit() -> concurrent.futures.Future, and
    shutdown(wait=, cancel_futures=)."""

    def __init__(self, max_workers: int, thread_name_prefix: str):
        import queue as _q

        self._q: _q.SimpleQueue = _q.SimpleQueue()
        self._futs: list = []  # for cancel_futures
        self._threads = [
            _threading_mod.Thread(
                target=self._worker, daemon=True,
                name=f"{thread_name_prefix}-{i}",
            )
            for i in range(max_workers)
        ]
        for t in self._threads:
            t.start()

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 — future carries it
                fut.set_exception(e)

    def submit(self, fn, *args):
        from concurrent.futures import Future

        fut = Future()
        # prune settled futures: a long-lived pool (the engine's persistent
        # reader slot submits once per chunk forever) must not grow this
        # cancel-bookkeeping list without bound
        self._futs = [f for f in self._futs if not f.done()]
        self._futs.append(fut)
        self._q.put((fut, fn, args))
        return fut

    def shutdown(self, wait: bool = True, cancel_futures: bool = False) -> None:
        if cancel_futures:
            for f in self._futs:
                f.cancel()
        for _ in self._threads:
            self._q.put(None)
        if wait:
            for t in self._threads:
                t.join()




def calibrate_fdr_confirm(eng) -> None:
    """Init-time probe: measure this host's single-thread ConfirmSet
    cost on synthetic candidates; if it is >4x off the priced constant
    (either way), recompile the filter plan under measured pricing.
    Random-offset probes under-read the FDR-candidate bias ~2x, hence
    the wide gate — the post-scan retune handles fine constants."""
    from dataclasses import replace as _replace

    from distributed_grep_tpu.models.fdr import probe_confirm_ps

    eng._fdr_pricing = eng._fdr_base_pricing()
    eng._fdr_retuned = False
    if _os.environ.get("DGREP_NO_CALIBRATE"):
        return
    measured = probe_confirm_ps(eng._fdr_confirm)
    eng.calibration = {"confirm_probe_ps": measured}
    ratio = measured / eng._fdr_pricing.confirm_ps_per_candidate
    if 0.25 <= ratio <= 4.0:
        return
    pricing = _replace(
        eng._fdr_pricing, confirm_ps_per_candidate=measured
    )
    swap_fdr_plan(eng, pricing, reason=(
        f"confirm probe {measured:.0f} ps/candidate vs priced "
        f"{eng._fdr_pricing.confirm_ps_per_candidate:.0f}"
    ))

def swap_fdr_plan(eng, pricing, reason: str) -> None:
    """Recompile the FDR model under `pricing`; adopt it if the check
    plan actually changed (device tables re-upload lazily)."""
    try:
        model = compile_fdr(
            eng._fdr_pats, ignore_case=eng.ignore_case, pricing=pricing
        )
    except FdrError as e:
        # real pricing says the set is not worth filtering at all:
        # same routing as the compile-time rejection
        eng._route_native(
            f"FDR retune ({reason}): set not filterable under "
            f"measured pricing ({e})"
        )
        eng._fdr_pricing = pricing
        # the engine no longer answers for its construction args (mode
        # changed under measured pricing): the cross-job cache must not
        # hand this corpus-specific verdict to the next job
        from distributed_grep_tpu.ops.engine import invalidate_cached_engine

        invalidate_cached_engine(eng)
        return
    old = [(b.m, b.checks) for b in eng.fdr.banks]
    new = [(b.m, b.checks) for b in model.banks]
    if old != new:
        log.info(
            "FDR plan retuned (%s): %s gathers -> %s",
            reason,
            sum(b.total_gathers for b in eng.fdr.banks),
            sum(b.total_gathers for b in model.banks),
        )
        eng.fdr = model
        eng._fdr_dev_tables = None
        eng._fdr_ep_dev_tables = None
        eng._model_gen += 1  # new plan = new kernel compile: re-grace
        # model_gen bump = the cached entry's compiled model is stale for
        # OTHER jobs (plan tuned under this corpus's measured candidate
        # rates): evict so the next lookup recompiles from base pricing
        from distributed_grep_tpu.ops.engine import invalidate_cached_engine

        invalidate_cached_engine(eng)
    eng._fdr_pricing = pricing

def maybe_retune_fdr(eng, n_bytes: int) -> None:
    """Self-calibration stage 2: after a scan with enough evidence,
    replace the assumed fp bias and confirm cost with the MEASURED
    values from engine.stats (real candidates, real confirm wall) and
    retune the plan if the constants were >2.5x off.  Runs at most once
    per engine; the measured constants subsume OVERLAP_RESIDUE's role
    for plan choice (both legs are observed, not modeled)."""
    from dataclasses import replace as _replace

    if (
        eng.mode != "fdr"
        or eng._fdr_retuned
        or _os.environ.get("DGREP_NO_CALIBRATE")
        # mixed sets OR the pairset kernel's EXACT 1-byte matches into
        # the candidate words, so stats["candidates"] no longer
        # measures the FDR filter's false-positive rate — a frequent
        # short member would read as a massively blown bias and swap
        # in a garbage plan.  The init probe and chip-aware pricing
        # still calibrate these engines; only the stats-based stage-2
        # retune is disabled.
        or eng._fdr_pairset is not None
    ):
        return
    cands = eng.stats.get("candidates", 0)
    conf_s = eng.stats.get("confirm_seconds", 0.0)
    if cands < 10_000 or n_bytes < (1 << 23) or conf_s <= 0.0:
        return  # not enough evidence for stable constants
    eng._fdr_retuned = True
    measured_bias = (cands / n_bytes) / max(eng.fdr.fp_per_byte, 1e-12)
    # confirm_seconds is wall through the ACTUAL thread fan of this
    # host (min(8, cpu)); convert to the single-thread constant, keep
    # pricing against the DECLARED deployment thread count.  The
    # memory-bound confirm scales sublinearly with threads, so ideal
    # x actual_threads would overestimate the single-thread cost and
    # bias the retune toward extra device gathers — measure the real
    # speedup with a second ConfirmSet probe at the actual fan and use
    # probe_1t/probe_Nt (== measured speedup <= N) as the factor.
    actual_threads = min(8, _os.cpu_count() or 1)
    speedup = float(actual_threads)
    probe_1t = getattr(eng, "calibration", {}).get("confirm_probe_ps")
    if actual_threads > 1 and probe_1t and eng._fdr_confirm is not None:
        from distributed_grep_tpu.models.fdr import probe_confirm_ps

        probe_nt = probe_confirm_ps(
            eng._fdr_confirm, n_threads=actual_threads
        )
        if probe_nt > 0:
            speedup = min(speedup, max(1.0, probe_1t / probe_nt))
    measured_ps = conf_s / cands * 1e12 * speedup
    pr = eng._fdr_pricing
    bias_off = measured_bias / pr.fp_bias
    ps_off = measured_ps / pr.confirm_ps_per_candidate
    eng.calibration = {
        **getattr(eng, "calibration", {}),
        "measured_fp_bias": measured_bias,
        "measured_confirm_ps": measured_ps,
    }
    if 0.4 <= bias_off <= 2.5 and 0.4 <= ps_off <= 2.5:
        return  # priced within tolerance: keep the plan
    pricing = _replace(
        pr,
        fp_bias=max(measured_bias, 0.5),
        confirm_ps_per_candidate=measured_ps,
    )
    swap_fdr_plan(eng, pricing, reason=(
        f"measured bias {measured_bias:.2f} (priced {pr.fp_bias:.2f}), "
        f"confirm {measured_ps:.0f} ps (priced "
        f"{pr.confirm_ps_per_candidate:.0f})"
    ))



def scan_device(eng, data: bytes, progress=None, corpus_key=None) -> ScanResult:
    import time as _time

    t_wall0 = _time.perf_counter()
    eng.stats = {"candidates": 0, "confirm_seconds": 0.0, "end_offsets": 0}
    # the ONE dict for this scan: collect()/prepare() run in pool
    # threads, where `eng.stats` would resolve to the POOL thread's
    # slot — references below go through this capture (except after a
    # fallback RESCAN, which replaces the thread's dict and makes this
    # capture stale)
    st = eng.stats
    # Grace capability probed ONCE from the callback's signature: a
    # live `except TypeError` around progress(grace_s=...) would also
    # swallow a TypeError raised INSIDE the callback body, silently
    # converting an internal callback bug into a plain stamp and
    # losing the compile-grace declaration (round-4 ADVICE).
    supports_grace = progress is not None and _accepts_grace_kwarg(progress)
    nl = lines_mod.newline_index(data)
    eng._nl_local.stash = (len(data), nl)  # reused by scan()'s EOL leg
    device_lines: set[int] = set()
    boundaries: list[int] = []
    seg = eng.segment_bytes
    # jax-importing modules stay out of the cpu/native path: a plain
    # `--backend cpu` grep never pays the ~0.8 s jax import
    from distributed_grep_tpu.ops import layout as layout_mod
    from distributed_grep_tpu.ops import scan_jnp
    from distributed_grep_tpu.ops import sparse as sparse_mod
    from distributed_grep_tpu.ops import (
        pallas_approx,
        pallas_fdr,
        pallas_nfa,
        pallas_scan,
    )

    # `_interpret` forces the Pallas kernels through interpret mode so
    # the CI mesh (8 virtual CPU devices) exercises the production
    # kernel path — the same gates a real TPU run takes.  The flag is
    # passed to every kernel call below (None = wrapper auto-detect).
    pallas_ok = eng._kernel_backend_ok()
    interp_flag = True if eng._interpret else None
    use_pallas_sa = (
        eng.mode == "shift_and"
        and pallas_ok
        and pallas_scan.eligible(eng.shift_and)
    )
    # SWAR packed variant (round 6, DGREP_SWAR=1): 4 stripes per u32 lane
    # element for byte-sized automata with equality-only classes.  BOTH
    # the full model and the rare-class filter must be eligible — the
    # mid-scan defeat guard swaps filtered -> full without re-planning
    # the (packed) segment layout.  Mesh mode keeps the unpacked kernel
    # (sharded_kernels has no packed wiring yet).
    use_swar = (
        use_pallas_sa
        and eng.mesh is None
        and pallas_scan.swar_enabled()
        and pallas_scan.swar_eligible(eng.shift_and)
        and (eng._sa_filtered is None
             or pallas_scan.swar_eligible(eng._sa_filtered))
    )
    if use_swar:
        st["swar"] = 1
    # NFA mode without a real TPU (or over budget) falls back to the XLA
    # DFA path — same tables, interpreter-free.
    use_pallas_nfa = (
        eng.mode == "nfa"
        and pallas_ok
        and pallas_nfa.eligible(eng.glushkov)
    )
    # FDR filter path: candidates on device, exact per-offset confirm on
    # host (ConfirmSet probe inside collect, overlapped with the next
    # segment's device scan); without a TPU (or after a kernel failure)
    # the same engine falls back to the exact DFA banks below.
    use_fdr = (
        eng.mode == "fdr" and not eng._fdr_broken and pallas_ok
    )
    use_pallas_approx = (
        eng.mode == "approx"
        and pallas_ok
        and pallas_approx.eligible(eng.approx)
    )
    # Exact short-set pair kernel: match words straight off the device
    # (kind "words", no confirm) — scan() already routed to the native
    # host path when no kernel backend exists.
    use_pairset = eng.mode == "pairset" and pallas_ok
    if use_pairset or eng._fdr_pairset is not None:
        from distributed_grep_tpu.ops import pallas_pairset
    use_pallas = (
        use_pallas_sa or use_pallas_nfa or use_fdr or use_pallas_approx
        or use_pairset
    )
    # Scan-local rare-class filter state: the dense-candidate guard in
    # collect() drops it for the REST OF THIS SCAN only (a dense corpus
    # says nothing about the next file this engine greps).
    sa_filtered = eng._sa_filtered

    # Segments round-robin across local chips (the worker drives every
    # chip on its host, SURVEY.md §7 step 5).  Dispatch is async — the
    # dense result plane stays on its device and the O(matches) sparse
    # fetch happens in a second phase, so device i+1 scans while device
    # i's results drain; MAX_INFLIGHT bounds resident result planes.
    import jax
    from contextlib import nullcontext

    if eng.devices == "all":
        try:
            devs: list = list(jax.local_devices())
        except Exception:  # noqa: BLE001 — no backend: default placement
            devs = [None]
    elif eng.devices:
        devs = list(eng.devices)  # type: ignore[arg-type]
    else:
        devs = [None]
    max_inflight = 2 * len(devs)

    # Mesh mode: each segment's lanes shard over the mesh and the SAME
    # Pallas kernels run per device under shard_map (the multi-chip
    # fast path — parallel/sharded_kernels).  The psum'd candidate
    # count is kept per segment as the collective cross-check.
    use_mesh = eng.mesh is not None and (
        use_pallas_sa or use_pallas_nfa or use_fdr or use_pallas_approx
        or use_pairset
    )
    if eng.mesh is not None and not use_mesh:
        log.warning(
            "mesh requested but mode %r has no sharded kernel "
            "(pallas_ok=%s) — scanning on the default device",
            eng.mode, pallas_ok,
        )
    if use_mesh:
        from distributed_grep_tpu.parallel import sharded_kernels as shk

        mesh_mult = shk.mesh_lane_multiple(eng.mesh, eng.mesh_axis)
        psum_totals: list = []
    ep_axis = eng.pattern_axis
    if use_mesh and use_fdr and ep_axis is not None:
        from distributed_grep_tpu.ops import pallas_fdr as _pfdr

        if len({(b.m, _pfdr.kernel_plan(b)) for b in eng.fdr.banks}) != 1:
            log.info(
                "mixed-plan FDR banks: pattern-parallel sharding "
                "unavailable — lanes shard over the full mesh instead"
            )
            ep_axis = None

    # Layout parameters, computed ONCE and shared by the prepare step and
    # the corpus-cache variant signature below — one source, so the cache
    # key can never drift from the layout the scan actually packs under.
    if use_pallas:
        if use_mesh:
            lane_mult = mesh_mult
        elif use_swar:
            # packed lanes tile in 4096-u32 blocks = 16384 stripes
            lane_mult = pallas_scan.SWAR_LANES_PER_BLOCK
        else:
            lane_mult = pallas_scan.LANES_PER_BLOCK
        lay_kwargs = dict(
            target_lanes=max(eng.target_lanes, lane_mult),
            min_chunk=512,
            lane_multiple=lane_mult,
            chunk_multiple=512,
            quantize_chunk=True,  # bound jit compiles over
            # arbitrarily-sized tails (full segments are unchanged)
        )
    else:
        lay_kwargs = dict(
            target_lanes=eng.target_lanes, quantize_chunk=True
        )

    # Device corpus cache (ops/layout.CorpusCache): when the caller
    # threaded a content key and a byte budget is in force, a resident
    # variant replaces the whole host-pad + upload pipeline for this
    # scan; a miss records the built segments and publishes them after
    # the scan SUCCEEDS (fallback/rescue paths never publish partial
    # state).  Mesh engines and explicit device lists bypass via
    # _corpus_budget() == 0 — same verdict as the model cache: resident
    # segments are committed to specific devices.  Inputs LARGER than
    # the budget are cache-ineligible outright: retaining their built
    # segments until scan end would defeat the double-buffer's bounded
    # footprint, and publishing them would LRU-wipe every smaller
    # entry before the oversized newcomer evicts itself.
    resident = None  # [(seg_start, Layout, device_array, dev)] when warm
    corpus_put = None  # (cache, sig, budget) when this scan populates
    if corpus_key is not None and eng.mesh is None and len(data) > 0:
        budget = eng._corpus_budget()
        # Eligibility is priced on the PADDED device bytes, computed
        # upfront from the hoisted lay_kwargs (choose_layout is pure
        # arithmetic): gating on raw len(data) alone would let the
        # raw<=budget<padded band set corpus_put, retain every built
        # segment until scan end, and then have put_segments decline
        # the publish — paying the retention on every repeat query.
        if budget > 0:
            n_full, tail = divmod(len(data), eng.segment_bytes)
            padded_total = n_full * layout_mod.choose_layout(
                eng.segment_bytes, **lay_kwargs
            ).padded if n_full else 0
            if tail:
                padded_total += layout_mod.choose_layout(
                    tail, **lay_kwargs
                ).padded
        if budget > 0 and padded_total <= budget:
            cache = layout_mod.corpus_cache()
            sig = (eng.segment_bytes, tuple(sorted(lay_kwargs.items())))
            resident = cache.resident_segments(corpus_key, sig)
            if resident is None:
                corpus_put = (cache, sig, budget)
            spans_mod.instant(
                f"corpus:{'hit' if resident is not None else 'miss'}",
                cat="engine", bytes=len(data),
            )

    # Scan-local NFA model state: the defeat guard below may swap the
    # relaxed filter for the exact automaton mid-scan (this scan only).
    nfa_model = eng.glushkov
    nfa_is_filter = eng._nfa_filter

    # Collects run on a small pool so confirms from different devices'
    # segments overlap each other AND the dispatch loop (VERDICT r3
    # item 1: with devices="all" the scan leg scales xN chips while a
    # dispatch-thread confirm stream doesn't).  Shared state below
    # (device_lines, stats, the mid-scan defeat guards) mutates under
    # one lock; the heavy legs — ConfirmSet probes, per-line matchers,
    # the native dense rescan — run outside it.
    from distributed_grep_tpu.utils import lockdep as _lockdep_mod

    state_lock = _lockdep_mod.make_lock("device-scan-state")
    confirm_active = [0]  # live confirm legs; peak recorded in stats

    def _confirm_enter() -> None:
        with state_lock:
            confirm_active[0] += 1
            if confirm_active[0] > st.get("confirm_concurrency_peak", 0):
                st["confirm_concurrency_peak"] = confirm_active[0]

    def _confirm_exit() -> None:
        with state_lock:
            confirm_active[0] -= 1

    def confirm_lines(cand) -> None:
        """Per-line host confirm for a sparse candidate-line set (the
        shared tail of the span/cand filter paths)."""
        good = []
        for ln in cand:
            start, end = lines_mod.line_span(nl, ln, len(data))
            if eng._host_line_matcher(data[start:end]):
                good.append(ln)
        with state_lock:
            device_lines.update(good)

    def dense_native_confirm(seg_start: int, seg_len: int) -> int:
        """Candidate-dense segment: one native DFA pass (C, ~GB/s)
        resolves every line vectorized instead of per-line Python
        confirm.  Returns the number of true matched lines found."""
        from distributed_grep_tpu.utils.native import dfa_scan_mt

        t = eng.table
        seg_bytes_ = data[seg_start : seg_start + seg_len]
        offs = dfa_scan_mt(
            seg_bytes_, t.full_table(), t.accept, t.start,
        ).astype(np.int64)
        if t.accept_eol.any():
            # '$' accepts (the round-5 device-filter patterns): second
            # pass with accept_eol as the accept set, kept only where
            # the next byte IN THE FULL DOCUMENT is '\n' or EOF (a
            # segment-final offset is not EOL unless it ends the data).
            eol = dfa_scan_mt(
                seg_bytes_, t.full_table(),
                t.accept_eol.astype(np.uint8), t.start,
            ).astype(np.int64)
            if eol.size:
                g = eol + seg_start
                arr = np.frombuffer(data, dtype=np.uint8)
                keep = (g == len(data)) | (
                    arr[np.minimum(g, len(data) - 1)] == 10
                )
                offs = np.concatenate([offs, eol[keep]])
        if not offs.size:
            return 0
        uniq = np.unique(
            lines_mod.line_of_offsets(offs + seg_start, nl)
        )
        with state_lock:
            device_lines.update(uniq.tolist())
        return int(uniq.size)

    def collect(job) -> None:
        with trace_mod.annotate(f"collect:{job[0]}@{job[3]}"):
            return _collect(job)

    def _collect(job) -> None:
        sparse_kind, payload, lay, seg_start, seg_len, dev = job
        # Fetch under the job's device context so the decode runs where
        # the plane lives instead of copying it to the default device.
        ctx = jax.default_device(dev) if dev is not None else nullcontext()
        with ctx:
            if sparse_kind in ("span_words", "span_words_packed"):
                # Coarse shift-and: nonzero words name 32-byte spans
                # that contain >= 1 candidate match end (exact at span
                # granularity for the full model; a superset when the
                # rare-class filter ran).  Map spans to their
                # overlapping lines, confirm each line once on host —
                # overlapped with the next segment's device scan.  The
                # SWAR variant packs 4 stripes per word; its decoder
                # demuxes byte-plane flags to the same span starts.
                idx, vals = scan_jnp.sparse_nonzero(payload)
                if sparse_kind == "span_words_packed":
                    starts = sparse_mod.span_starts_from_packed_words(
                        idx, vals, lay
                    )
                else:
                    starts = sparse_mod.span_starts_from_sparse_words(idx, lay)
                if starts.size:
                    g0 = starts + seg_start  # global span starts
                    g1 = np.minimum(g0 + 32, len(data))
                    l0 = lines_mod.line_of_offsets(g0 + 1, nl)
                    l1 = lines_mod.line_of_offsets(g1, nl)
                    cand = set()
                    for a, b in zip(l0.tolist(), l1.tolist()):
                        cand.update(range(a, b + 1))
                    with state_lock:
                        cand -= device_lines  # already confirmed earlier
                        st["candidates"] += len(cand)
                    if len(cand) > _engine_mod.SPAN_CONFIRM_LINE_LIMIT:
                        _confirm_enter()
                        try:
                            true_lines = dense_native_confirm(seg_start, seg_len)
                        finally:
                            _confirm_exit()
                        nonlocal sa_filtered
                        if sa_filtered is not None and true_lines * 4 < len(cand):
                            # mostly-false candidates: the corpus defeats
                            # the filter's byte prior — remaining segments
                            # of THIS scan run the full compare set.  (A
                            # dense corpus of TRUE matches keeps the
                            # filter: the DFA fallback was inevitable
                            # either way.)
                            log.info(
                                "rare-class filter mostly false on this "
                                "corpus (%d candidate lines, %d true) -> "
                                "full model for this scan",
                                len(cand), true_lines,
                            )
                            with state_lock:
                                sa_filtered = None
                    else:
                        _confirm_enter()
                        try:
                            confirm_lines(cand)
                        finally:
                            _confirm_exit()
                return
            if sparse_kind == "cand_words":
                # NFA filter path (models/nfa.compile_scan_model): the
                # device offsets are a candidate SUPERSET (bounded
                # repeats relaxed to save state words); confirm each
                # candidate line on host — overlapped with the next
                # segment's device scan.
                idx, vals = scan_jnp.sparse_nonzero(payload)
                offsets = sparse_mod.offsets_from_sparse_words(idx, vals, lay)
                with state_lock:
                    st["candidates"] += int(offsets.size)
                if offsets.size:
                    t0 = _time.perf_counter()
                    glines = lines_mod.line_of_offsets(offsets + seg_start, nl)
                    cand = set(np.unique(glines).tolist())
                    with state_lock:
                        cand -= device_lines
                    if len(cand) > _engine_mod.SPAN_CONFIRM_LINE_LIMIT and \
                            eng.table is not None:
                        _confirm_enter()
                        try:
                            true_lines = dense_native_confirm(seg_start, seg_len)
                        finally:
                            _confirm_exit()
                        nonlocal nfa_model, nfa_is_filter
                        if (
                            nfa_is_filter
                            and true_lines * 4 < len(cand)
                            and eng.glushkov_exact is not None
                            and pallas_nfa.eligible(eng.glushkov_exact)
                        ):
                            # mostly-false candidates: this corpus defeats
                            # the relaxed filter — remaining segments of
                            # THIS scan run the exact automaton.  (With
                            # no eligible exact model, filter + native
                            # rescan stays the best device plan: the XLA
                            # DFA fallback is ~10x slower than even a
                            # full native rescan per segment.)
                            log.info(
                                "relaxed NFA filter mostly false on this "
                                "corpus (%d candidate lines, %d true) -> "
                                "exact automaton for this scan",
                                len(cand), true_lines,
                            )
                            with state_lock:
                                nfa_model = eng.glushkov_exact
                                nfa_is_filter = False
                                st["nfa_filter_defeated"] = True
                    else:
                        _confirm_enter()
                        try:
                            confirm_lines(cand)
                        finally:
                            _confirm_exit()
                    with state_lock:
                        st["confirm_seconds"] += _time.perf_counter() - t0
                return
            if sparse_kind == "words":
                idx, vals = scan_jnp.sparse_nonzero(payload)
                offsets = sparse_mod.offsets_from_sparse_words(idx, vals, lay)
                if use_fdr:
                    # Exact per-candidate confirm (suffix probe + memcmp)
                    # against the WHOLE document, so a window reaching
                    # back across the segment start still confirms; runs
                    # here so it overlaps the next segment's device scan.
                    t0 = _time.perf_counter()
                    _confirm_enter()
                    try:
                        keep = eng._fdr_confirm.confirm(
                            data, offsets + seg_start
                        )
                    finally:
                        _confirm_exit()
                    with state_lock:
                        st["confirm_seconds"] += (
                            _time.perf_counter() - t0
                        )
                        st["candidates"] += int(offsets.size)
                    offsets = offsets[keep]
            elif sparse_kind == "lane_bytes":
                idx, vals = scan_jnp.sparse_nonzero(payload)
                offsets = sparse_mod.offsets_from_sparse_lane_bytes(idx, vals, lay)
            else:  # "bank_list": one packed plane per DFA bank
                per_bank = []
                for packed in payload:
                    idx, vals = scan_jnp.sparse_nonzero(packed)
                    per_bank.append(
                        sparse_mod.offsets_from_sparse_lane_bytes(idx, vals, lay)
                    )
                offsets = np.unique(np.concatenate(per_bank)) if per_bank else \
                    np.zeros(0, dtype=np.int64)
        with state_lock:
            st["end_offsets"] += int(offsets.size)
        if offsets.size:
            # transient slice: jobs hold (start, len), not segment copies
            seg_view = data[seg_start : seg_start + seg_len]
            seg_nl = lines_mod.newline_index(seg_view)
            # offsets are np.unique output (sorted): native linear merge
            seg_lines = lines_mod.unique_match_lines(offsets, seg_nl)
            base = int(np.searchsorted(nl, seg_start))  # lines before segment
            with state_lock:
                device_lines.update((seg_lines + base).tolist())

    # Double-buffered device feed (VERDICT r2 item 4): a one-slot
    # prepare thread builds segment i+1's stripe layout (host pad +
    # transpose copy) and enqueues its device upload while segment i's
    # kernels dispatch and its results confirm — the upload rides the
    # async transfer engine instead of serializing the dispatch loop.
    # stats["feed_wait_seconds"] is the residual stall: ~0 when compute
    # hides the feed, ~upload time when the scan is feed-bound.
    
    seg_starts = list(range(0, max(len(data), 1), seg))

    from distributed_grep_tpu.utils import trace as trace_mod

    def prepare(i: int, seg_start: int):
        # feed leg: visible as its own row in the profiler timeline so
        # the upload/compute overlap is inspectable (DGREP_TRACE_DIR)
        with trace_mod.annotate(f"feed:seg{i}"):
            return _prepare(i, seg_start)

    def _prepare(i: int, seg_start: int):
        seg_bytes = data[seg_start : seg_start + seg]
        # layout params are the hoisted lay_kwargs — the SAME values the
        # corpus-cache variant signature was derived from above
        lay = layout_mod.choose_layout(len(seg_bytes), **lay_kwargs)
        arr = layout_mod.to_device_array(seg_bytes, lay)
        dev = devs[i % len(devs)]
        if use_mesh:
            # the tile reshape/copy and the NamedSharding device_put
            # need no kernel state — running them HERE (prepare thread)
            # is what makes the double-buffer real in mesh mode: the
            # sharded upload of segment i+1 rides the transfer engine
            # while segment i's shard_map dispatch runs (round-3 advisor
            # finding: doing this inside the dispatch loop kept the mesh
            # path feed-serialized and under-reported feed_wait_seconds)
            arr = shk.prepare_tiles(arr, eng.mesh, eng.mesh_axis)
        else:
            # enqueue the host->device copy now (async on real backends)
            pctx = jax.default_device(dev) if dev is not None else nullcontext()
            with pctx:
                import jax.numpy as jnp

                arr = jnp.asarray(arr)
        return seg_bytes, lay, arr, dev

    pool = (
        _DaemonPool(1, thread_name_prefix="dgrep-feed")
        if len(seg_starts) > 1 and resident is None else None
    )
    # Collect pool (VERDICT r3 item 1): sparse decode + host confirm of
    # finished segments runs here, so confirms from different devices'
    # segments overlap each other and the dispatch loop instead of
    # serializing on it.  Mesh mode has one sharded stream — two workers
    # cover decode/confirm pipelining; round-robin mode sizes to the
    # device fan.  Single-segment scans collect inline (nothing to
    # overlap).
    from collections import deque as _deque

    n_collect = 2 if use_mesh else min(4, max(1, len(devs)))
    collect_pool = (
        _DaemonPool(n_collect, thread_name_prefix="dgrep-collect")
        if len(seg_starts) > 1 else None
    )
    collect_futs: _deque = _deque()
    st["feed_wait_seconds"] = 0.0
    built: list = []  # (seg_start, lay, arr, dev) — the corpus-put record
    nxt = (
        prepare(0, seg_starts[0])
        if seg_starts and resident is None else None
    )
    try:
        for i, seg_start in enumerate(seg_starts):
            if resident is not None:
                # warm: the segment is already packed, padded, and
                # device-resident — no read-ahead, no host transpose
                # copy, no upload; the feed pipeline has nothing to do
                _, lay, arr, dev = resident[i]
                seg_len = min(seg, len(data) - seg_start)
                nxt_future = None
            else:
                seg_bytes, lay, arr, dev = nxt
                seg_len = len(seg_bytes)
                nxt_future = (
                    pool.submit(prepare, i + 1, seg_starts[i + 1])
                    if i + 1 < len(seg_starts) else None
                )
            if seg_start > 0:
                boundaries.append(seg_start)
            # Every kernel below jit-specializes on the padded layout
            # shape (+ the plan constants, _model_gen): a key this
            # engine has not completed a dispatch for may block on a
            # fresh ~20-40 s compile with no observable progress, so
            # declare a grace window first.  Marked done only AFTER the
            # kernel call returns — a concurrent scan blocked on the
            # same compile still declares its own grace.  (The mid-scan
            # defeat guards swap models without bumping _model_gen;
            # their rare recompile risks one spurious sweep, accepted.)
            compile_key = (
                eng.mode, use_mesh, eng._model_gen,
                getattr(arr, "shape", None),
            )
            if progress is not None and compile_key not in eng._compiled_keys:
                if supports_grace:
                    progress(grace_s=_engine_mod.COMPILE_GRACE_S)
                else:  # legacy callbacks without the grace kwarg
                    progress()
            ctx = jax.default_device(dev) if dev is not None else nullcontext()
            # Dispatch the device scan; the sparse fetch (a 4-byte count
            # round-trip plus O(matches) coordinates — never the dense
            # packed plane) happens in collect().
            with ctx:
                if use_fdr:
                    if use_mesh and ep_axis is not None:
                        # EP: same-plan banks shard their tables over
                        # pattern_axis, lanes over mesh_axis
                        words, pt = shk.sharded_fdr_pattern_step(
                            arr, eng.fdr, eng.mesh,
                            data_axis=eng.mesh_axis,
                            pattern_axis=ep_axis,
                            interpret=interp_flag,
                            fold_case=eng.ignore_case,
                            tabs_dev=eng._fdr_ep_tables(ep_axis),
                        )
                        psum_totals.append(pt)
                    elif use_mesh:
                        words, pt = shk.sharded_fdr_words(
                            arr, eng.fdr, eng.mesh, eng.mesh_axis,
                            interpret=interp_flag,
                            dev_tables=eng._fdr_device_tables(None),
                            fold_case=eng.ignore_case,
                        )
                        psum_totals.append(pt)
                    else:
                        words = None
                        for bank, dev_tab in zip(
                            eng.fdr.banks, eng._fdr_device_tables(dev)
                        ):
                            # A-Z folds on device (pallas_fdr fold_case)
                            # instead of a host .lower() pass per segment
                            w = pallas_fdr.fdr_scan_words(
                                arr, bank, dev_tables=dev_tab,
                                interpret=interp_flag,
                                fold_case=eng.ignore_case,
                            )
                            words = w if words is None else words | w
                    if eng._fdr_pairset is not None:
                        # a mixed set's 1-byte members: exact pairset
                        # kernel on device, OR'd into the candidate
                        # words (the ConfirmSet includes the short
                        # members, so the union confirms exactly) —
                        # replaces a ~0.2 s/segment host AC scan that
                        # used to serialize this dispatch loop
                        if use_mesh:
                            pw, ppt = shk.sharded_pairset_words(
                                arr, eng._fdr_pairset, eng.mesh,
                                eng.mesh_axis, interpret=interp_flag,
                                dev_tables=eng._pairset_device_tables(None),
                            )
                            words = words | pw
                            psum_totals.append(ppt)
                        else:
                            words = words | pallas_pairset.pairset_scan_words(
                                arr, eng._fdr_pairset,
                                dev_tables=eng._pairset_device_tables(dev),
                                interpret=interp_flag,
                            )
                    job = ("words", words, lay, seg_start, seg_len, dev)
                elif use_pallas:
                    if use_pallas_sa:
                        # coarse packing: a nonzero word = "a match ends
                        # in this 32-byte span" (~2x kernel throughput);
                        # the span's lines are confirmed in collect()
                        if use_mesh:
                            words, pt = shk.sharded_shift_and_words(
                                arr, sa_filtered or eng.shift_and,
                                eng.mesh, eng.mesh_axis,
                                coarse=True, interpret=interp_flag,
                            )
                            psum_totals.append(pt)
                            kind = "span_words"
                        elif use_swar:
                            words = pallas_scan.swar_shift_and_scan_words(
                                arr, sa_filtered or eng.shift_and,
                                interpret=interp_flag,
                            )
                            kind = "span_words_packed"
                        else:
                            words = pallas_scan.shift_and_scan_words(
                                arr, sa_filtered or eng.shift_and,
                                coarse=True, interpret=interp_flag,
                            )
                            kind = "span_words"
                    elif use_pallas_approx:
                        if use_mesh:
                            words, pt = shk.sharded_approx_words(
                                arr, eng.approx, eng.mesh,
                                eng.mesh_axis, interpret=interp_flag,
                            )
                            psum_totals.append(pt)
                        else:
                            words = pallas_approx.approx_scan_words(
                                arr, eng.approx, interpret=interp_flag
                            )
                        kind = "words"
                    elif use_pairset:
                        if use_mesh:
                            words, pt = shk.sharded_pairset_words(
                                arr, eng.pairset, eng.mesh,
                                eng.mesh_axis, interpret=interp_flag,
                                dev_tables=eng._pairset_device_tables(None),
                            )
                            psum_totals.append(pt)
                        else:
                            words = pallas_pairset.pairset_scan_words(
                                arr, eng.pairset,
                                dev_tables=eng._pairset_device_tables(dev),
                                interpret=interp_flag,
                            )
                        kind = "words"
                    else:
                        # snapshot model+kind together: the defeat guard
                        # swaps them from a collect-pool thread, and a
                        # torn read (filter model + kind "words") would
                        # skip the confirm pass filter planes require
                        with state_lock:
                            nfa_now, nfa_filter_now = nfa_model, nfa_is_filter
                        if use_mesh:
                            words, pt = shk.sharded_nfa_words(
                                arr, nfa_now, eng.mesh,
                                eng.mesh_axis, interpret=interp_flag,
                            )
                            psum_totals.append(pt)
                        else:
                            words = pallas_nfa.nfa_scan_words(
                                arr, nfa_now, interpret=interp_flag
                            )
                        kind = "cand_words" if nfa_filter_now else "words"
                    job = (kind, words, lay, seg_start, seg_len, dev)
                elif eng.mode == "shift_and":
                    packed = scan_jnp.shift_and_scan(arr, eng.shift_and)
                    job = ("lane_bytes", packed, lay, seg_start, seg_len, dev)
                elif eng.mode == "approx":
                    packed = scan_jnp.approx_scan(arr, eng.approx)
                    job = ("lane_bytes", packed, lay, seg_start, seg_len, dev)
                else:
                    # One device pass per automaton bank; bytes AND bank
                    # tables are uploaded once (tables are cached on the
                    # engine — a near-full bank's table is ~67 MB,
                    # re-uploading it per segment would swamp the link
                    # the sparse fetch protects).
                    import jax.numpy as jnp

                    arr_dev = jnp.asarray(arr)
                    planes = []
                    for kind, bank in eng._device_tables(dev):
                        if kind == "stride":
                            planes.append(scan_jnp._dfa_stride_core(arr_dev, *bank))
                        else:
                            planes.append(scan_jnp._dfa_scan_core(arr_dev, *bank))
                    job = ("bank_list", planes, lay, seg_start, seg_len, dev)
            eng._compiled_keys.add(compile_key)
            if corpus_put is not None:
                # dispatched = the upload is enqueued and the array is
                # (or is becoming) device-resident; published only after
                # the WHOLE scan succeeds, below
                built.append((seg_start, lay, arr, dev))
            boundaries.extend((seg_start + lay.stripe_starts()).tolist())
            if collect_pool is not None:
                collect_futs.append(collect_pool.submit(collect, job))
                if len(collect_futs) >= max_inflight:
                    # bound resident result planes, like the old pending
                    # list: wait out the oldest in-flight collect.
                    # Time-boxed (DEVICE_STALL_S): a device that
                    # black-holes mid-scan must degrade, not hang.
                    _await_wall(collect_futs.popleft())
            else:
                collect(job)
            if progress is not None:
                progress()  # one milestone per dispatched segment
            if nxt_future is not None:
                t0 = _time.perf_counter()
                nxt = _await_wall(nxt_future)
                st["feed_wait_seconds"] += _time.perf_counter() - t0
        while collect_futs:
            _await_wall(collect_futs.popleft())
            if progress is not None:
                progress()
    except Exception as e:
        # Dispatch is async: a kernel can fail at execution time (first
        # consumed in collect) as well as at compile time.  Mosaic
        # limits are empirical — on an FDR device failure, flip to the
        # exact DFA banks and rescan; everything else propagates.
        # Host-side failures that cannot come from the Pallas/Mosaic
        # layer must not be misattributed to it (and silently retried
        # on the slower DFA path).  Only types jax internals never
        # surface kernel failures as: AttributeError/KeyError/etc. DO
        # occur inside jax on version skew, so they stay in the net.
        if isinstance(e, (MemoryError, UnicodeError)):
            raise
        stalled = isinstance(e, _DeviceStall)  # the DEVICE_STALL_S wall
        # (a transient socket.timeout from INSIDE a device call is a
        # plain TimeoutError and keeps the ordinary retry chain)
        if collect_pool is not None:
            # running collects mutate st/device_lines — let them
            # drain before any fallback rescan resets those under them
            # (their un-awaited exceptions, if any, mirror this one).
            # EXCEPT when the device stalled: the hung collect never
            # returns, so waiting on it would hang this recovery too.
            collect_pool.shutdown(wait=not stalled, cancel_futures=True)
        if stalled:
            host_scanner = eng._host_scanner()
            if host_scanner is not None:
                # Black-holed mid-scan (a healthy first touch, then the
                # transport died hanging instead of erroring): skip the
                # kernel-retry chain — the device is gone, not the
                # kernel — and degrade straight to the exact host
                # engines.  The hung pool threads are abandoned;
                # scrubbing them from the futures exit-join registry
                # keeps process shutdown from blocking on them.
                log.warning(
                    "device execution stalled > %.0fs mid-scan (%s) -> "
                    "exact host engines for this engine",
                    _engine_mod.DEVICE_STALL_S, e,
                )
                eng._mark_device_broken()
                result = eng._host_scan(host_scanner, data, progress)
                eng.stats["device_fallback"] = True
                return result
            # no host route: still mark the device dead so the next
            # scan fails fast instead of re-paying the full wall
            eng._mark_device_broken()
            raise
        if not use_fdr:
            if use_pallas and not eng._pallas_broken:
                # same policy as the FDR net: a Mosaic/runtime kernel
                # failure flips this engine to its non-Pallas engine
                # (XLA scan / DFA banks / re) and rescans — exactness
                # is preserved, speed degrades loudly.
                log.warning(
                    "pallas %s kernel failed (%s) -> non-Pallas fallback",
                    eng.mode, e,
                )
                eng._pallas_broken = True
                return eng.scan(data, progress=progress)
            host_scanner = eng._host_scanner()
            if host_scanner is not None:
                # Every DEVICE route is exhausted (e.g. the device link
                # died mid-job — observed live when the tunneled chip's
                # transport dropped): an exact host engine exists, so
                # degrade to it for the rest of this engine's life
                # instead of crashing the map task.
                log.warning(
                    "device scan failed with no device fallback left "
                    "(%s) -> exact host engines for this engine", e,
                )
                # Recognizable transport failures (the fast
                # `Connection Failed` phase of a tunnel outage surfaces
                # here as XlaRuntimeError, not via the stall wall) keep
                # the demotion eligible for the DEVICE_RETRY_S
                # un-demote — a long-lived worker reclaims the device
                # when the tunnel heals (round-4 ADVICE).  A generic
                # exception may be a per-pattern defect on a healthy
                # device: permanent demotion, and do NOT poison the
                # process-wide probe verdict.
                eng._mark_device_broken(
                    transport_evidence=_is_transport_error(e)
                )
                result = eng._host_scan(host_scanner, data, progress)
                eng.stats["device_fallback"] = True
                return result
            raise
        log.warning("pallas FDR kernel failed (%s) -> DFA banks", e)
        eng._fdr_broken = True
        from distributed_grep_tpu.utils.native import native_available

        if native_available():
            # same policy as the compile-time FDR rejection: the native
            # MT scanner beats the XLA DFA-bank device path ~100x
            eng.mode = "native"
            result = eng._scan_native(data)
        else:
            result = scan_device(eng, data, progress=progress)
        # rescan stats only — the rescan REPLACED this thread's stats
        # dict, so write through the property (scanning thread), not
        # the pre-fallback `st` capture (now orphaned)
        eng.stats["fdr_fallback"] = True
        return result
    finally:
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        if collect_pool is not None:
            collect_pool.shutdown(wait=False, cancel_futures=True)

    if corpus_put is not None:
        # the scan completed on the device route end to end: publish the
        # resident segments for the next query over this content (the
        # fallback/rescue paths above returned before reaching here, so
        # partial or degraded scans never populate the cache)
        cache, sig, budget = corpus_put
        cache.put_segments(corpus_key, sig, data, built, budget)
    # FDR candidates were already confirmed offset-exactly in collect();
    # boundary lines (stripe/segment heads, where the filter's all-ones
    # seed under-reports) are restored by the stitching pass below.
    stitched = lines_mod.stitch_lines(
        device_lines, data, nl, boundaries, eng._host_line_matcher
    )
    if use_mesh and psum_totals:
        # ICI-collective candidate tally across all segments — the
        # cross-check dryrun_multichip asserts against the host count.
        st["psum_candidates"] = sum(int(t) for t in psum_totals)
    st["scan_wall_seconds"] = _time.perf_counter() - t_wall0
    maybe_retune_fdr(eng, len(data))
    lines_arr = np.asarray(sorted(stitched), dtype=np.int64)
    return ScanResult(lines_arr, int(lines_arr.size), len(data))


"""Pallas TPU kernel: FDR bucketed literal-set filter (models/fdr.py).

Same shell as ops/pallas_scan.py / ops/pallas_nfa.py (lanes x chunk tiles,
time-packed uint32 candidate words, VMEM scratch carried across chunk
blocks), but the per-byte step is the bucketed pair-hash filter:

    h_f    = ((prev*a_f) ^ (b*b_f)) & (Dmax-1)   pair-domain hash, family f
    R_i    = tables[i][h_fam(i) & (D_i-1)]       one lookup per check
    M_k    = AND of R_i with slot(i) == k        per-slot reach masks
    V_0    = M_0 ;  V_k = V_{k-1}(prev byte) & M_k   pipeline over slots
    cand   = V_{m-1} != 0                        some bucket passed all checks

The reach lookup is the part the VPU had no primitive for until lane
gathers: ``jnp.take_along_axis(table_tile, idx, axis=1)`` gathers within a
128-lane vreg row, so a D-entry table is D/128 broadcast tiles selected by
the hash's high bits.  **Domains are per check** (models/fdr.py v3): hash
families nest (h_D == h_Dmax & (D-1)), so the kernel computes one hash per
family at that family's widest domain, shares the low-7-bit gather index
across every check of the family, and derives each narrower domain's
subtable-select masks by masking the same hash — the clustered check's
single-gather D=128 table costs exactly one take_along_axis.

Probed on TPU v5e (2026-07-30, unroll sweeps):

* the per-(8,128)-vreg 128-entry u32 gather issues at ~4.5-5 cycles and is
  the kernel's bottleneck resource — throughput ~= 1000 / (4.7 ps *
  total_gathers) GB/s at the best unroll;
* **the old "MAX_GATHERS = 24" Mosaic compile ceiling was an unroll
  artifact**: at unroll=32 a 32-gather/byte kernel crashes the compiler,
  at unroll<=16 it compiles and runs (measured 6.6 GB/s for 32 gathers);
* unroll sweep at a 21-gather plan (clustered@128 + 5xD512): unroll=2 ->
  9.5, 4 -> ~10.1, 8 -> 9.0-9.6, 16 -> 9.5 GB/s; at the old 28-gather
  plan unroll=8 beat 32 by ~20%.  unroll_for picks 4 for gather-heavy
  plans, 8 for small ones; the production 10k-set pick (clustered@128 +
  3x512 + 2x256 = 17 gathers, models/fdr.py v3) measures ~12.2 GB/s.

The V pipeline is seeded ALL-ONES at each stripe start: the first m
positions of a stripe then over-report candidates instead of missing
matches whose window spans the stripe head, and the engine's exact
confirmation keeps the final output exact either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.fdr import HASHES, MAX_GATHERS, FdrBank
from distributed_grep_tpu.ops import pallas_scan
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    SUBLANES,
    available,
    validate_unroll,
)

def unroll_for(plan) -> int:
    """Unroll factor for a (slot, family, n_sub) kernel plan.

    Probed on v5e (2026-07-30): gather-heavy kernels (the 10k-set 19-21
    gather plans) run ~10% faster at unroll=4 — register pressure — while
    small-gather kernels (the 1k-set 5-gather plan: 42 vs 35 GB/s) want
    unroll=8 to amortize the per-iteration pipeline carries.  The
    compile ceiling was re-probed at BOTH unroll factors each round:
    round 4 cleared 40 gathers; round 5 cleared 44/48/56/64 (fillers at
    D=1024, benchmarks/probe_gather_ceiling.py) — MAX_GATHERS=64 now."""
    return 4 if sum(ns for _, _, ns in plan) >= 12 else 8


def eligible(bank: FdrBank) -> bool:
    """models/fdr only emits kernel-sized banks; guard anyway."""
    from distributed_grep_tpu.models.fdr import DOMAINS

    return (
        bank.m <= 8
        # exact DOMAINS membership, not just d%128==0: the kernel's nested
        # hi/lo hash decomposition needs power-of-two domains (d=384 would
        # mask with 0b101111111 and never select subtable 1)
        and all(d in DOMAINS for _, _, d in bank.checks)
        and bank.total_gathers <= MAX_GATHERS
    )


def kernel_plan(bank: FdrBank) -> tuple[tuple[int, int, int], ...]:
    """Static (slot, family, n_subtables) plan the kernel compiles against."""
    return tuple((slot, fam, d // LANE_COLS) for slot, fam, d in bank.checks)


def bank_device_tables(bank: FdrBank) -> np.ndarray:
    """(sum of per-check subtables, SUBLANES, LANE_COLS) uint32 — each
    check's 128-entry subtables broadcast across sublanes and stacked in
    plan order, ready to pass to the kernel.  Upload once per engine;
    ~16 KB per subtable."""
    rows = []
    for t in bank.tables:
        rows.append(t.reshape(-1, LANE_COLS))
    sub = np.concatenate(rows, axis=0)
    tiles = np.broadcast_to(
        sub[:, None, :], (sub.shape[0], SUBLANES, LANE_COLS)
    )
    return np.ascontiguousarray(tiles)


def _kernel(data_ref, tabs_ref, out_ref, v_ref, prev_ref, *, m, plan, steps, unroll,
            fold_case=False):
    from jax.experimental import pallas as pl  # deferred: import cost

    validate_unroll(unroll)

    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        # all-ones: stripe heads over-report (exact confirm), never miss
        v_ref[...] = jnp.full_like(v_ref, jnp.uint32(0xFFFFFFFF))
        prev_ref[...] = jnp.zeros_like(prev_ref)

    zero = jnp.uint32(0)
    families = sorted({f for _, f, _ in plan})
    # widest domain per family: the hash is computed once at that width and
    # masked down per check (domains nest — models/fdr.pair_hash)
    fam_sub = {f: max(ns for _, ff, ns in plan if ff == f) for f in families}
    # static row offset of each check's subtables in tabs_ref
    offs, o = [], 0
    for _, _, ns in plan:
        offs.append(o)
        o += ns
    n_inner = 32 // unroll

    def word_body(w, carry):
        def sub_body(s, inner):
            prev_b, word, *V = inner
            for tt in range(unroll):
                b = data_ref[w * 32 + s * unroll + tt].astype(jnp.int32)
                if fold_case:
                    # ASCII A-Z -> a-z on device (patterns are normalized
                    # lowercase at compile, models/fdr._normalize): ~3 VPU
                    # ops per byte instead of a host .lower() pass + copy
                    # over every segment.  prev_b carries the folded byte.
                    b = jnp.where((b >= 65) & (b <= 90), b + 32, b)
                los, sels = {}, {}
                for f in families:
                    ha, hb = HASHES[f]
                    h = ((prev_b * ha) ^ (b * hb)) & (fam_sub[f] * LANE_COLS - 1)
                    los[f] = h & (LANE_COLS - 1)
                    for ns in sorted({n for _, ff, n in plan if ff == f and n > 1}):
                        hi = (h & (ns * LANE_COLS - 1)) >> 7
                        # all-ones/all-zero masks, shared by every check of
                        # this (family, domain) combination
                        sels[f, ns] = [
                            zero - (hi == j).astype(jnp.uint32) for j in range(ns)
                        ]
                prev_b = b
                masks = [None] * m
                for i, (slot, fam, ns) in enumerate(plan):
                    acc = None
                    for j in range(ns):
                        g = jnp.take_along_axis(
                            tabs_ref[offs[i] + j], los[fam], axis=1
                        )
                        if ns > 1:
                            g = g & sels[fam, ns][j]
                        acc = g if acc is None else (acc | g)
                    masks[slot] = acc if masks[slot] is None else (masks[slot] & acc)
                # slots with no check stay None -> all-ones (no AND needed)
                V_new = []
                for k in range(m):
                    prev_v = V[k - 1] if k else None
                    if masks[k] is None:
                        V_new.append(prev_v if k else jnp.full_like(V[0], ~zero))
                    else:
                        V_new.append(masks[k] if k == 0 else (prev_v & masks[k]))
                V = V_new
                bit = jnp.uint32(1 << tt) << (s * jnp.uint32(unroll))
                word = word | jnp.where(V[m - 1] != 0, bit, zero)
            return (prev_b, word, *V)

        prev_b, *V = carry
        word0 = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        if n_inner == 1:
            out = sub_body(0, (prev_b, word0, *V))
        else:
            out = jax.lax.fori_loop(0, n_inner, sub_body, (prev_b, word0, *V))
        prev_b, word, *V = out
        out_ref[w] = word
        return (prev_b, *V)

    carry0 = (prev_ref[...].astype(jnp.int32),) + tuple(v_ref[k] for k in range(m))
    final = jax.lax.fori_loop(0, steps // 32, word_body, carry0)
    prev_ref[...] = final[0].astype(jnp.uint32)
    for k in range(m):
        v_ref[k] = final[1 + k]


@functools.partial(
    jax.jit,
    static_argnames=("m", "plan", "chunk", "lane_blocks", "interpret", "unroll",
                     "fold_case"),
)
def _fdr_pallas(data, tabs, *, m, plan, chunk, lane_blocks, interpret=False,
                unroll=None, fold_case=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    n_rows = sum(ns for _, _, ns in plan)
    if unroll is None:
        unroll = unroll_for(plan)
    kernel = functools.partial(_kernel, m=m, plan=plan, steps=steps, unroll=unroll,
                               fold_case=fold_case)
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (n_rows, SUBLANES, LANE_COLS),
                lambda li, ci: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[
            pltpu.VMEM((m, SUBLANES, LANE_COLS), jnp.uint32),
            pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32),
        ],
        interpret=interpret,
    )(data, tabs)


def fdr_scan_words(
    arr_cl: np.ndarray,
    bank: FdrBank,
    dev_tables=None,
    interpret: bool | None = None,
    fold_case: bool = False,
) -> jnp.ndarray:
    """Run one bank's filter; returns time-packed candidate words as a
    DEVICE array in the shared Pallas convention ((chunk//32, S, 128)
    uint32 — decode via ops/sparse.offsets_from_sparse_words).  Candidates
    from several banks OR together on device before the sparse fetch.

    ``dev_tables`` lets the engine upload ``bank_device_tables`` once and
    reuse across segments."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0"
        )
    if not eligible(bank):
        raise ValueError("bank outside the kernel's check/domain budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = pallas_scan.as_tiles(arr_cl, lane_blocks)
    if dev_tables is None:
        dev_tables = jnp.asarray(bank_device_tables(bank))
    if interpret is None:
        interpret = not available()
    return _fdr_pallas(
        data,
        dev_tables,
        m=bank.m,
        plan=kernel_plan(bank),
        chunk=chunk,
        lane_blocks=lane_blocks,
        interpret=interpret,
        fold_case=fold_case,
    )


def fdr_scan(
    arr_cl: np.ndarray, bank: FdrBank, interpret: bool | None = None
) -> np.ndarray:
    """Dense-output wrapper (tests): packed bits in the scan_jnp convention."""
    from distributed_grep_tpu.ops.pallas_scan import _unpack_words_to_lane_bits

    chunk, lanes = arr_cl.shape
    words = fdr_scan_words(arr_cl, bank, interpret=interpret)
    return _unpack_words_to_lane_bits(np.asarray(words), chunk, lanes)

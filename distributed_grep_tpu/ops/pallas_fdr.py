"""Pallas TPU kernel: FDR bucketed literal-set filter (models/fdr.py).

Same shell as ops/pallas_scan.py / ops/pallas_nfa.py (lanes x chunk tiles,
time-packed uint32 candidate words, VMEM scratch carried across chunk
blocks), but the per-byte step is the bucketed pair-hash filter:

    h      = ((prev*37) ^ (b*101)) & (D-1)       pair-domain hash
    R_j    = tables[j][h]                        m reach lookups
    V_0    = R_0 ;  V_k = V_k-1(prev byte) & R_k pipeline over pair checks
    cand   = V_{m-1} != 0                        some bucket passed all m

The reach lookup is the part the VPU had no primitive for until lane
gathers: ``jnp.take_along_axis(table_tile, idx, axis=1)`` gathers within a
128-lane vreg row, so a D-entry table is D/128 broadcast tiles selected by
the hash's high bits (the ``hi == j`` selects are shared across all m
position tables — one compare set per byte, not per lookup).

Probed on TPU v5e (2026-07-30): m=4/D=256 ~22 GB/s, m=5/D=512 ~11.5 GB/s;
D=1024 crashes the Mosaic compiler, hence models/fdr.DOMAINS caps at 512.

The V pipeline is seeded ALL-ONES at each stripe start: the first m
positions of a stripe then over-report candidates instead of missing
matches whose window spans the stripe head, and the engine's host
confirmation (exact Aho-Corasick on the candidate's line) keeps the final
output exact either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.fdr import HASHES, MAX_GATHERS, FdrBank
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    SUBLANES,
    available,
)


def eligible(bank: FdrBank) -> bool:
    """models/fdr only emits kernel-sized banks; guard anyway."""
    return (
        bank.m <= 6
        and bank.domain <= 512
        and bank.domain % 128 == 0
        and bank.n_hashes * bank.m * (bank.domain // LANE_COLS) <= MAX_GATHERS
    )


def bank_device_tables(bank: FdrBank) -> np.ndarray:
    """(n_hashes * m * n_subtables, SUBLANES, LANE_COLS) uint32 — each
    128-entry subtable broadcast across sublanes, ready to pass to the
    kernel.  Upload once per engine; ~16 KB per subtable."""
    nh, m, d = bank.tables.shape
    g = d // LANE_COLS
    sub = bank.tables.reshape(nh, m, g, LANE_COLS)
    tiles = np.broadcast_to(
        sub[:, :, :, None, :], (nh, m, g, SUBLANES, LANE_COLS)
    ).reshape(nh * m * g, SUBLANES, LANE_COLS)
    return np.ascontiguousarray(tiles)


def _kernel(data_ref, tabs_ref, out_ref, v_ref, prev_ref, *, m, n_sub, n_hashes, steps):
    from jax.experimental import pallas as pl  # deferred: import cost

    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        # all-ones: stripe heads over-report (host confirm), never miss
        v_ref[...] = jnp.full_like(v_ref, jnp.uint32(0xFFFFFFFF))
        prev_ref[...] = jnp.zeros_like(prev_ref)

    zero = jnp.uint32(0)

    def word_body(w, carry):
        prev_b, *V = carry
        word = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        for t in range(32):
            b = data_ref[w * 32 + t].astype(jnp.int32)  # (32, 128)
            los, all_sels = [], []
            for hi_i in range(n_hashes):
                ha, hb = HASHES[hi_i]
                h = ((prev_b * ha) ^ (b * hb)) & (n_sub * LANE_COLS - 1)
                los.append(h & (LANE_COLS - 1))
                if n_sub > 1:
                    hi = h >> 7
                    # all-ones/all-zero select masks, shared by all m lookups
                    all_sels.append(
                        [zero - (hi == j).astype(jnp.uint32) for j in range(n_sub)]
                    )
            prev_b = b
            masks = []
            for p in range(m):
                anded = None  # AND over hashes of this position's reach
                for hi_i in range(n_hashes):
                    acc = None
                    base = (hi_i * m + p) * n_sub
                    for j in range(n_sub):
                        g = jnp.take_along_axis(tabs_ref[base + j], los[hi_i], axis=1)
                        if n_sub > 1:
                            g = g & all_sels[hi_i][j]
                        acc = g if acc is None else (acc | g)
                    anded = acc if anded is None else (anded & acc)
                masks.append(anded)
            V = [masks[0]] + [V[k - 1] & masks[k] for k in range(1, m)]
            word = word | jnp.where(V[m - 1] != 0, jnp.uint32(1 << t), zero)
        out_ref[w] = word
        return (prev_b, *V)

    carry0 = (prev_ref[...].astype(jnp.int32),) + tuple(v_ref[k] for k in range(m))
    final = jax.lax.fori_loop(0, steps // 32, word_body, carry0)
    prev_ref[...] = final[0].astype(jnp.uint32)
    for k in range(m):
        v_ref[k] = final[1 + k]


@functools.partial(
    jax.jit,
    static_argnames=("m", "n_sub", "n_hashes", "chunk", "lane_blocks", "interpret"),
)
def _fdr_pallas(data, tabs, *, m, n_sub, n_hashes=1, chunk, lane_blocks, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    kernel = functools.partial(
        _kernel, m=m, n_sub=n_sub, n_hashes=n_hashes, steps=steps
    )
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (n_hashes * m * n_sub, SUBLANES, LANE_COLS),
                lambda li, ci: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[
            pltpu.VMEM((m, SUBLANES, LANE_COLS), jnp.uint32),
            pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32),
        ],
        interpret=interpret,
    )(data, tabs)


def fdr_scan_words(
    arr_cl: np.ndarray,
    bank: FdrBank,
    dev_tables=None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Run one bank's filter; returns time-packed candidate words as a
    DEVICE array in the shared Pallas convention ((chunk//32, S, 128)
    uint32 — decode via ops/sparse.offsets_from_sparse_words).  Candidates
    from several banks OR together on device before the sparse fetch.

    ``dev_tables`` lets the engine upload ``bank_device_tables`` once and
    reuse across segments."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0"
        )
    if not eligible(bank):
        raise ValueError("bank outside the kernel's m/domain budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = np.ascontiguousarray(
        arr_cl.reshape(chunk, lane_blocks * SUBLANES, LANE_COLS)
    )
    if dev_tables is None:
        dev_tables = jnp.asarray(bank_device_tables(bank))
    if interpret is None:
        interpret = not available()
    return _fdr_pallas(
        jnp.asarray(data),
        dev_tables,
        m=bank.m,
        n_sub=bank.domain // LANE_COLS,
        n_hashes=bank.n_hashes,
        chunk=chunk,
        lane_blocks=lane_blocks,
        interpret=interpret,
    )


def fdr_scan(
    arr_cl: np.ndarray, bank: FdrBank, interpret: bool | None = None
) -> np.ndarray:
    """Dense-output wrapper (tests): packed bits in the scan_jnp convention."""
    from distributed_grep_tpu.ops.pallas_scan import _unpack_words_to_lane_bits

    chunk, lanes = arr_cl.shape
    words = fdr_scan_words(arr_cl, bank, interpret=interpret)
    return _unpack_words_to_lane_bits(np.asarray(words), chunk, lanes)

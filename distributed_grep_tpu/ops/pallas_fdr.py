"""Pallas TPU kernel: FDR bucketed literal-set filter (models/fdr.py).

Same shell as ops/pallas_scan.py / ops/pallas_nfa.py (lanes x chunk tiles,
time-packed uint32 candidate words, VMEM scratch carried across chunk
blocks), but the per-byte step is the bucketed pair-hash filter:

    h_f    = ((prev*a_f) ^ (b*b_f)) & (D-1)      pair-domain hash, family f
    R_i    = tables[i][h_fam(i)]                 one lookup per check
    M_k    = AND of R_i with slot(i) == k        per-slot reach masks
    V_0    = M_0 ;  V_k = V_{k-1}(prev byte) & M_k   pipeline over slots
    cand   = V_{m-1} != 0                        some bucket passed all checks

The reach lookup is the part the VPU had no primitive for until lane
gathers: ``jnp.take_along_axis(table_tile, idx, axis=1)`` gathers within a
128-lane vreg row, so a D-entry table is D/128 broadcast tiles selected by
the hash's high bits (the ``hi == j`` masks are shared across all checks
of one family — one compare set per byte, not per lookup).

Probed on TPU v5e (2026-07-30, unroll sweep):

* the per-(8,128)-vreg 128-entry u32 gather issues at ~4.5 cycles and is
  the kernel's bottleneck resource — throughput ~= 940 MHz * 4096 /
  (4.56 * lookups * (D/128) * 4) bytes/s, i.e. ~56/L GB/s at D=512;
* **the old "MAX_GATHERS = 24" Mosaic compile ceiling was an unroll
  artifact**: at unroll=32 a 32-gather/byte kernel crashes the compiler,
  at unroll<=16 it compiles and runs (measured 6.6 GB/s for 32 gathers);
* unroll=8 is also ~20% faster than unroll=32 at equal gather counts
  (11.4 vs 9.3 GB/s for 20 gathers), so the kernel now fixes unroll=8
  with a lax.fori_loop carrying the pipeline across sub-blocks.

The V pipeline is seeded ALL-ONES at each stripe start: the first m
positions of a stripe then over-report candidates instead of missing
matches whose window spans the stripe head, and the engine's exact
confirmation keeps the final output exact either way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distributed_grep_tpu.models.fdr import HASHES, MAX_GATHERS, FdrBank
from distributed_grep_tpu.ops.pallas_scan import (
    CHUNK_BLOCK_WORDS,
    LANE_COLS,
    LANES_PER_BLOCK,
    SUBLANES,
    available,
)

UNROLL = 8  # byte steps unrolled per fori iteration (see probe notes above)


def eligible(bank: FdrBank) -> bool:
    """models/fdr only emits kernel-sized banks; guard anyway."""
    return (
        bank.m <= 8
        and bank.domain <= 512
        and bank.domain % 128 == 0
        and bank.n_checks * bank.n_subtables <= MAX_GATHERS
    )


def bank_device_tables(bank: FdrBank) -> np.ndarray:
    """(n_checks * n_subtables, SUBLANES, LANE_COLS) uint32 — each
    128-entry subtable broadcast across sublanes, ready to pass to the
    kernel.  Upload once per engine; ~16 KB per subtable."""
    nc, d = bank.tables.shape
    g = d // LANE_COLS
    sub = bank.tables.reshape(nc, g, LANE_COLS)
    tiles = np.broadcast_to(
        sub[:, :, None, :], (nc, g, SUBLANES, LANE_COLS)
    ).reshape(nc * g, SUBLANES, LANE_COLS)
    return np.ascontiguousarray(tiles)


def _kernel(data_ref, tabs_ref, out_ref, v_ref, prev_ref, *, m, n_sub, plan, steps):
    from jax.experimental import pallas as pl  # deferred: import cost

    ci = pl.program_id(1)

    @pl.when(ci == 0)
    def _init():
        # all-ones: stripe heads over-report (exact confirm), never miss
        v_ref[...] = jnp.full_like(v_ref, jnp.uint32(0xFFFFFFFF))
        prev_ref[...] = jnp.zeros_like(prev_ref)

    zero = jnp.uint32(0)
    families = sorted({f for _, f in plan})
    n_inner = 32 // UNROLL

    def word_body(w, carry):
        def sub_body(s, inner):
            prev_b, word, *V = inner
            for tt in range(UNROLL):
                b = data_ref[w * 32 + s * UNROLL + tt].astype(jnp.int32)
                los, sels = {}, {}
                for f in families:
                    ha, hb = HASHES[f]
                    h = ((prev_b * ha) ^ (b * hb)) & (n_sub * LANE_COLS - 1)
                    los[f] = h & (LANE_COLS - 1)
                    if n_sub > 1:
                        hi = h >> 7
                        # all-ones/all-zero masks, shared by the family's checks
                        sels[f] = [
                            zero - (hi == j).astype(jnp.uint32) for j in range(n_sub)
                        ]
                prev_b = b
                masks = [None] * m
                for i, (slot, fam) in enumerate(plan):
                    acc = None
                    for j in range(n_sub):
                        g = jnp.take_along_axis(
                            tabs_ref[i * n_sub + j], los[fam], axis=1
                        )
                        if n_sub > 1:
                            g = g & sels[fam][j]
                        acc = g if acc is None else (acc | g)
                    masks[slot] = acc if masks[slot] is None else (masks[slot] & acc)
                # slots with no check stay None -> all-ones (no AND needed)
                V_new = []
                for k in range(m):
                    prev_v = V[k - 1] if k else None
                    if masks[k] is None:
                        V_new.append(prev_v if k else jnp.full_like(V[0], ~zero))
                    else:
                        V_new.append(masks[k] if k == 0 else (prev_v & masks[k]))
                V = V_new
                bit = jnp.uint32(1 << tt) << (s * jnp.uint32(UNROLL))
                word = word | jnp.where(V[m - 1] != 0, bit, zero)
            return (prev_b, word, *V)

        prev_b, *V = carry
        word0 = jnp.zeros((SUBLANES, LANE_COLS), dtype=jnp.uint32)
        if n_inner == 1:
            out = sub_body(0, (prev_b, word0, *V))
        else:
            out = jax.lax.fori_loop(0, n_inner, sub_body, (prev_b, word0, *V))
        prev_b, word, *V = out
        out_ref[w] = word
        return (prev_b, *V)

    carry0 = (prev_ref[...].astype(jnp.int32),) + tuple(v_ref[k] for k in range(m))
    final = jax.lax.fori_loop(0, steps // 32, word_body, carry0)
    prev_ref[...] = final[0].astype(jnp.uint32)
    for k in range(m):
        v_ref[k] = final[1 + k]


@functools.partial(
    jax.jit,
    static_argnames=("m", "n_sub", "plan", "chunk", "lane_blocks", "interpret"),
)
def _fdr_pallas(data, tabs, *, m, n_sub, plan, chunk, lane_blocks, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    steps = 32 * CHUNK_BLOCK_WORDS
    chunk_blocks = chunk // steps
    n_checks = len(plan)
    kernel = functools.partial(_kernel, m=m, n_sub=n_sub, plan=plan, steps=steps)
    return pl.pallas_call(
        kernel,
        grid=(lane_blocks, chunk_blocks),
        in_specs=[
            pl.BlockSpec(
                (steps, SUBLANES, LANE_COLS),
                lambda li, ci: (ci, li, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (n_checks * n_sub, SUBLANES, LANE_COLS),
                lambda li, ci: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (CHUNK_BLOCK_WORDS, SUBLANES, LANE_COLS),
            lambda li, ci: (ci, li, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(
            (chunk // 32, lane_blocks * SUBLANES, LANE_COLS), jnp.uint32
        ),
        scratch_shapes=[
            pltpu.VMEM((m, SUBLANES, LANE_COLS), jnp.uint32),
            pltpu.VMEM((SUBLANES, LANE_COLS), jnp.uint32),
        ],
        interpret=interpret,
    )(data, tabs)


def fdr_scan_words(
    arr_cl: np.ndarray,
    bank: FdrBank,
    dev_tables=None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Run one bank's filter; returns time-packed candidate words as a
    DEVICE array in the shared Pallas convention ((chunk//32, S, 128)
    uint32 — decode via ops/sparse.offsets_from_sparse_words).  Candidates
    from several banks OR together on device before the sparse fetch.

    ``dev_tables`` lets the engine upload ``bank_device_tables`` once and
    reuse across segments."""
    chunk, lanes = arr_cl.shape
    steps = 32 * CHUNK_BLOCK_WORDS
    if lanes % LANES_PER_BLOCK or chunk % steps:
        raise ValueError(
            f"pallas layout needs lanes%{LANES_PER_BLOCK}==0, chunk%{steps}==0"
        )
    if not eligible(bank):
        raise ValueError("bank outside the kernel's check/domain budget")
    lane_blocks = lanes // LANES_PER_BLOCK
    data = np.ascontiguousarray(
        arr_cl.reshape(chunk, lane_blocks * SUBLANES, LANE_COLS)
    )
    if dev_tables is None:
        dev_tables = jnp.asarray(bank_device_tables(bank))
    if interpret is None:
        interpret = not available()
    return _fdr_pallas(
        jnp.asarray(data),
        dev_tables,
        m=bank.m,
        n_sub=bank.domain // LANE_COLS,
        plan=tuple(bank.checks),
        chunk=chunk,
        lane_blocks=lane_blocks,
        interpret=interpret,
    )


def fdr_scan(
    arr_cl: np.ndarray, bank: FdrBank, interpret: bool | None = None
) -> np.ndarray:
    """Dense-output wrapper (tests): packed bits in the scan_jnp convention."""
    from distributed_grep_tpu.ops.pallas_scan import _unpack_words_to_lane_bits

    chunk, lanes = arr_cl.shape
    words = fdr_scan_words(arr_cl, bank, interpret=interpret)
    return _unpack_words_to_lane_bits(np.asarray(words), chunk, lanes)

"""Sparse match-result decoding: device packed bits -> host byte offsets.

Companion to scan_jnp.sparse_nonzero: the device keeps the dense packed
match plane; the host receives only (index, value) pairs for its nonzero
bytes/words and decodes absolute match end-offsets from the coordinates.
Transfer cost is O(matches), not O(corpus/8) — on slow host<->device links
(axon tunnel ~MB/s) this is the difference between microseconds and
minutes for a 256 MB shard.
"""

from __future__ import annotations

import numpy as np

from distributed_grep_tpu.ops.layout import Layout
from distributed_grep_tpu.ops.pallas_scan import LANE_COLS, LANES_PER_BLOCK, SUBLANES


def offsets_from_sparse_lane_bytes(
    idx: np.ndarray, vals: np.ndarray, layout: Layout
) -> np.ndarray:
    """Decode scan_jnp packing: packed (chunk, lanes//8) uint8, flat index
    = c*(lanes//8) + g, bit k = lane g*8+k.  Returns sorted end offsets."""
    if idx.size == 0:
        return np.zeros(0, dtype=np.int64)
    g8 = layout.lanes // 8
    c = idx // g8
    g = idx % g8
    out = []
    for k in range(8):
        sel = (vals >> k) & 1 != 0
        if sel.any():
            lane = g[sel] * 8 + k
            out.append(lane * layout.chunk + c[sel] + 1)
    offsets = np.concatenate(out) if out else np.zeros(0, dtype=np.int64)
    offsets = offsets[offsets <= layout.n_real]
    offsets.sort()
    return offsets


def span_starts_from_sparse_words(
    idx: np.ndarray, layout: Layout
) -> np.ndarray:
    """Decode the COARSE Pallas packing (pallas_scan coarse=True): a nonzero
    word means "some match ends in this 32-byte stripe span"; values don't
    matter.  Returns sorted document offsets of span starts — each span is
    [start, min(start+32, stripe/document end)); the engine confirms the
    lines overlapping it."""
    if idx.size == 0:
        return np.zeros(0, dtype=np.int64)
    S = layout.lanes // LANE_COLS
    l = idx % LANE_COLS
    rest = idx // LANE_COLS
    s = rest % S
    w = rest // S
    lane = (s // SUBLANES) * LANES_PER_BLOCK + (s % SUBLANES) * LANE_COLS + l
    starts = lane * layout.chunk + w * 32
    starts = starts[starts < layout.n_real]
    starts.sort()
    return starts.astype(np.int64)


def span_starts_from_packed_words(
    idx: np.ndarray, vals: np.ndarray, layout: Layout
) -> np.ndarray:
    """Decode the SWAR packed coarse output (pallas_scan
    swar_shift_and_scan_words): words live on PACKED lanes (4 stripes per
    u32), and byte k's match bit names a candidate 32-byte span of stripe
    4j+k.  Returns sorted document offsets of span starts, exactly the
    span_starts_from_sparse_words contract."""
    if idx.size == 0:
        return np.zeros(0, dtype=np.int64)
    S = (layout.lanes // 4) // LANE_COLS
    l = idx % LANE_COLS
    rest = idx // LANE_COLS
    s = rest % S
    w = rest // S
    j = (s // SUBLANES) * LANES_PER_BLOCK + (s % SUBLANES) * LANE_COLS + l
    out = []
    for k in range(4):
        sel = (vals >> np.uint32(8 * k)) & np.uint32(0xFF) != 0
        if sel.any():
            out.append((4 * j[sel] + k) * layout.chunk + w[sel] * 32)
    starts = np.concatenate(out) if out else np.zeros(0, dtype=np.int64)
    starts = starts[starts < layout.n_real]
    starts.sort()
    return starts.astype(np.int64)


def offsets_from_sparse_words(
    idx: np.ndarray, vals: np.ndarray, layout: Layout
) -> np.ndarray:
    """Decode the Pallas kernel packing: words (chunk//32, S, 128) uint32,
    flat index = (w*S + s)*128 + l, bit t = chunk position w*32+t, lane
    = (s//32)*4096 + (s%32)*128 + l.  Returns sorted end offsets."""
    if idx.size == 0:
        return np.zeros(0, dtype=np.int64)
    S = layout.lanes // LANE_COLS
    l = idx % LANE_COLS
    rest = idx // LANE_COLS
    s = rest % S
    w = rest // S
    lane = (s // SUBLANES) * LANES_PER_BLOCK + (s % SUBLANES) * LANE_COLS + l
    out = []
    for t in range(32):
        sel = (vals >> np.uint32(t)) & np.uint32(1) != 0
        if sel.any():
            c = w[sel] * 32 + t
            out.append(lane[sel] * layout.chunk + c + 1)
    offsets = np.concatenate(out) if out else np.zeros(0, dtype=np.int64)
    offsets = offsets[offsets <= layout.n_real]
    offsets.sort()
    return offsets

"""Checker driver: run the invariant rules over a project root, filter
pragmas and the baseline allowlist, render text/JSON, exit 0/1.

Exposed as ``python -m distributed_grep_tpu analyze`` and as
``run_analysis()`` for the tier-1 lint test (tests/test_analysis.py) and
the obs suite's logging check.

Suppression, narrowest first:

* an inline pragma on the flagged line — ``# analyze-ok: <rule>`` (or a
  bare ``# analyze-ok`` for any rule) — for single deliberate divergences;
* a baseline file (``--baseline``) of lines ``<rule>\\t<path>\\t<stripped
  source line>`` — content-keyed, so entries survive line drift.  The
  repo's own baseline is EMPTY by policy: pre-existing violations get
  fixed, not inventoried.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from distributed_grep_tpu.analysis.rules import (
    RULE_DOCS,
    RULES,
    Project,
    Violation,
)

PACKAGE_ROOT = Path(__file__).resolve().parents[1]

_PRAGMA = "# analyze-ok"


def _source_line(root: Path, v: Violation,
                 cache: dict[str, list[str]] | None = None) -> str:
    """Flagged source line text (pragma/baseline key).  ``cache`` holds
    splitlines per path for the run — one read per file, not per
    violation."""
    lines = cache.get(v.path) if cache is not None else None
    if lines is None:
        try:
            lines = (root / v.path).read_text(
                encoding="utf-8", errors="surrogateescape").splitlines()
        except OSError:
            lines = []
        if cache is not None:
            cache[v.path] = lines
    return lines[v.line - 1].strip() if 0 < v.line <= len(lines) else ""


def _pragma_suppressed(src_line: str, rule: str) -> bool:
    if _PRAGMA not in src_line:
        return False
    tail = src_line.split(_PRAGMA, 1)[1]
    if tail.startswith(":"):
        allowed = {r.strip() for r in tail[1:].split("#", 1)[0].split(",")}
        return rule in allowed
    return True  # bare pragma: any rule


def load_baseline(path: Path) -> set[tuple[str, str, str]]:
    entries: set[tuple[str, str, str]] = set()
    for raw in path.read_text(encoding="utf-8").splitlines():
        if not raw.strip() or raw.lstrip().startswith("#"):
            continue
        parts = raw.split("\t", 2)
        if len(parts) == 3:
            entries.add((parts[0], parts[1], parts[2].strip()))
    return entries


def run_analysis(
    root: Path | str | None = None,
    rules: list[str] | None = None,
    baseline: Path | str | None = None,
) -> list[Violation]:
    """All surviving violations, sorted (path, line, rule)."""
    root = Path(root) if root is not None else PACKAGE_ROOT
    selected = list(RULES) if rules is None else rules
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise ValueError(f"unknown rule(s) {unknown}; have {sorted(RULES)}")
    project = Project(root)
    base = load_baseline(Path(baseline)) if baseline is not None else set()
    lines_cache: dict[str, list[str]] = {}
    out: list[Violation] = []
    for name in selected:
        for v in RULES[name](project):
            src = _source_line(root, v, lines_cache)
            if _pragma_suppressed(src, v.rule):
                continue
            if (v.rule, v.path, src.strip()) in base:
                continue
            out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def to_sarif(violations: list[Violation]) -> dict:
    """Render violations as a SARIF 2.1.0 log (one run, one driver).
    Deterministic: rules sorted by id, results keep run_analysis's
    (path, line, rule) sort, and the serialization is sort_keys=True —
    the same repo state always yields byte-identical output (golden test
    in tests/test_analysis.py)."""
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "distributed-grep-analyze",
                "informationUri":
                    "https://example.invalid/distributed_grep_tpu",
                "rules": [
                    {"id": name,
                     "shortDescription": {"text": RULE_DOCS[name]}}
                    for name in sorted(RULES)
                ],
            }},
            "results": [
                {
                    "ruleId": v.rule,
                    "level": "error",
                    "message": {"text": v.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {"uri": v.path},
                            "region": {"startLine": v.line},
                        },
                    }],
                }
                for v in violations
            ],
        }],
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="distributed_grep_tpu analyze",
        description="project invariant checker (AST-walked; exit 1 on "
                    "violations)",
    )
    p.add_argument("--root", default=None,
                   help="source tree to analyze (default: the installed "
                        "distributed_grep_tpu package)")
    p.add_argument("--rule", action="append", default=None, metavar="NAME",
                   help="run only this rule (repeatable; default: all)")
    p.add_argument("--baseline", default=None,
                   help="allowlist file of known violations "
                        "(rule<TAB>path<TAB>stripped source line)")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write current violations as a baseline and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--sarif", action="store_true", dest="as_sarif",
                   help="SARIF 2.1.0 output (CI annotations / editors); "
                        "results keep the stable (path, line, rule) sort")
    p.add_argument("--list-rules", action="store_true",
                   help="list rules with the invariant each encodes")
    p.add_argument("--knobs", action="store_true",
                   help="print the DGREP_* env-knob registry as markdown "
                        "(the generated operator docs)")
    p.add_argument("--events", action="store_true",
                   help="print the telemetry event vocabulary (span/"
                        "instant/daemon-event names) as markdown")
    args = p.parse_args(argv)

    if args.list_rules:
        for name in RULES:
            print(f"{name}: {RULE_DOCS[name]}")
        return 0
    if args.knobs:
        from distributed_grep_tpu.analysis.knobs import knob_docs

        print(knob_docs(), end="")
        return 0
    if args.events:
        from distributed_grep_tpu.analysis.events import event_docs

        print(event_docs(), end="")
        return 0

    try:
        violations = run_analysis(root=args.root, rules=args.rule,
                                  baseline=args.baseline)
    except (ValueError, OSError) as e:  # unknown rule / unreadable baseline
        print(f"error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        root = Path(args.root) if args.root else PACKAGE_ROOT
        cache: dict[str, list[str]] = {}
        lines = [f"{v.rule}\t{v.path}\t{_source_line(root, v, cache)}"
                 for v in violations]
        try:
            Path(args.write_baseline).write_text(
                "\n".join(lines) + ("\n" if lines else ""), encoding="utf-8")
        except OSError as e:  # same clean exit-2 contract as the read side
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(f"{len(violations)} violation(s) -> {args.write_baseline}")
        return 0

    if args.as_sarif:
        print(json.dumps(to_sarif(violations), indent=2, sort_keys=True))
    elif args.as_json:
        print(json.dumps({
            "violations": [
                {"rule": v.rule, "path": v.path, "line": v.line,
                 "message": v.message}
                for v in violations
            ],
            "count": len(violations),
        }, indent=2, sort_keys=True))
    else:
        for v in violations:
            print(v.render())
        if violations:
            print(f"{len(violations)} violation(s)", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

"""Registry of every exported telemetry event name: span / instant /
daemon-event vocabulary, declared once.

This is the single source of truth rule R13 (``event-registry``) enforces:
span and instant names (``scan:<mode>``, ``cache:hit|miss|off``,
``index:prune``, ...) and DaemonLog event kinds are emitted as string
literals across ~10 modules and consumed by string-matching in
runtime/explain.py, utils/spans.py trace export, and ``dgrep top`` — a typo
or one-sided rename silently turns an explain route verdict or a fleet
trace row into a lie.  Every emit site must use a name declared here (or a
member of a declared family); every consumer-side string compare must match
a declared name; a declared name no emitter produces is stale.

Families cover computed sites: a key containing one ``*`` (``scan:*``,
``cache:*``, ``*:commit``) declares the enumerated ``members`` that may
replace the star — an f-string emit like ``f"scan:{mode}"`` matches the
family pattern, and ``mode`` is pinned dynamically by the
utils/event_audit.py recorder (DGREP_EVENT_AUDIT=1 or the conftest
fixture), the lockdep-style runtime half of this rule.

The registry doubles as generated operator docs: ``python -m
distributed_grep_tpu analyze --events`` renders it as a markdown table.
"""

from __future__ import annotations

from dataclasses import dataclass


# Event kinds: "span" (timed, has dur), "instant" (point event), "daemon"
# (DaemonLog lifecycle record — keyed "kind" in daemon.jsonl, no cat).
@dataclass(frozen=True)
class Event:
    kinds: tuple  # subset of ("span", "instant", "daemon")
    cat: str  # expected cat at emit sites; "" = computed / not checked
    owners: tuple  # emitting module(s), package-relative
    consumers: tuple  # known name-matching consumer module(s)
    doc: str  # one line: what the event records
    members: tuple = ()  # for family keys (one "*"): allowed substitutions


EVENTS: dict[str, Event] = {
    # ---------------------------------------------------------- engine spans
    "scan:*": Event(
        ("span",), "engine", ("utils/spans.py", "ops/engine.py"),
        ("runtime/explain.py",),
        "Per-scan engine span promoted from engine.stats; the member is the "
        "kernel family that ran (scan:batch = one packed cross-file flush).",
        members=("re", "native", "dfa", "nfa", "shift_and", "fdr",
                 "pairset", "approx", "batch"),
    ),
    # ---------------------------------------------------------- worker spans
    "map:task": Event(
        ("span",), "map", ("runtime/worker.py",), (),
        "Whole map-task attempt wall on the worker.",
    ),
    "map:read": Event(
        ("span",), "map", ("runtime/worker.py",), (),
        "Map input read (file / members / data-plane fetch).",
    ),
    "map:compute": Event(
        ("span",), "map", ("runtime/worker.py",), (),
        "Map app compute (the engine scan for grep apps).",
    ),
    "map:emit": Event(
        ("span",), "map", ("apps/grep_tpu.py",), (),
        "Grep-app record build (confirm/-v/batch construction) — separates "
        "scan time from record-build time in trace export.",
    ),
    "map:shuffle": Event(
        ("span",), "map", ("runtime/worker.py",), (),
        "Bucketize + mr-out partition writes for one map attempt.",
    ),
    "reduce:task": Event(
        ("span",), "reduce", ("runtime/worker.py",), (),
        "Whole reduce-task attempt wall on the worker.",
    ),
    "reduce:shuffle": Event(
        ("span",), "reduce", ("runtime/worker.py",), (),
        "Shuffle-file fetch wall for one reduce attempt.",
    ),
    "reduce:compute": Event(
        ("span",), "reduce", ("runtime/worker.py",), (),
        "Reduce app compute + output spool for one attempt.",
    ),
    "*:commit": Event(
        ("span",), "", ("runtime/worker.py",), (),
        "Task commit (store rename + commit record); cat equals the task "
        "kind, so the family star is the kind.",
        members=("map", "reduce"),
    ),
    # -------------------------------------------------------- cache instants
    "cache:*": Event(
        ("instant",), "engine", ("apps/grep_tpu.py",),
        ("runtime/explain.py",),
        "Cross-job compiled-model cache verdict at grep_tpu.configure "
        "(off = engine construction bypassed the cache).",
        members=("hit", "miss", "off"),
    ),
    "corpus:*": Event(
        ("instant",), "engine", ("ops/device_scan.py",),
        ("runtime/explain.py",),
        "Device-resident corpus cache verdict per scanned input.",
        members=("hit", "miss"),
    ),
    "index:prune": Event(
        ("instant",), "engine", ("ops/engine.py",), ("runtime/explain.py",),
        "Shard-index bloom answered cannot-match: scan skipped.",
    ),
    "index:maybe": Event(
        ("instant",), "engine", ("ops/engine.py",), ("runtime/explain.py",),
        "Shard-index bloom could not rule the input out: scan proceeds.",
    ),
    "result:hit": Event(
        ("instant",), "service", ("runtime/service.py",),
        ("runtime/explain.py",),
        "Query-result cache answered the whole job (no scheduler, no scan).",
    ),
    "result:partial": Event(
        ("instant",), "service", ("runtime/service.py",),
        ("runtime/explain.py",),
        "Query-result cache answered some map splits; the rest scan.",
    ),
    "result:miss": Event(
        ("instant",), "service", ("runtime/service.py",),
        ("runtime/explain.py",),
        "Query-result cache had no reusable split for the job.",
    ),
    "result:revalidate": Event(
        ("instant",), "service", ("runtime/service.py",),
        ("runtime/explain.py",),
        "Stored result declined at publish: split re-stat drifted during "
        "the scan (e.g. live append).",
    ),
    # -------------------------------------------------------- engine health
    "device_demoted": Event(
        ("instant",), "engine", ("ops/engine.py",), ("runtime/explain.py",),
        "Accelerator transport demoted to the exact host engines.",
    ),
    "device_recovered": Event(
        ("instant",), "engine", ("ops/engine.py",), ("runtime/explain.py",),
        "A degraded engine's re-probe found the device responsive again.",
    ),
    # ------------------------------------------------------ shuffle instants
    "shuffle:peer": Event(
        ("instant",), "reduce", ("runtime/worker.py",),
        ("runtime/explain.py",),
        "Reducer fetched a shuffle file from the producer's peer plane.",
    ),
    "shuffle:relay": Event(
        ("instant",), "reduce", ("runtime/worker.py",),
        ("runtime/explain.py",),
        "Reducer fetched a shuffle file through the coordinator relay "
        "(emitted per fetch in peer deployments: pre-peer or fallback leg).",
    ),
    # ------------------------------------------------------- fusion instants
    "fuse:plan": Event(
        ("instant",), "fuse", ("runtime/service.py",),
        ("runtime/explain.py",),
        "Service planned a fused map assignment; written into each "
        "participant's events.jsonl.",
    ),
    "fuse:split": Event(
        ("instant",), "fuse", ("runtime/worker.py",),
        ("runtime/explain.py",),
        "Worker ran one fused split scan for K participant queries.",
    ),
    "fuse:wake": Event(
        ("instant",), "follow", ("runtime/follow.py",),
        ("runtime/explain.py",),
        "Fused follow-group wake served this member's standing query.",
    ),
    "follow:wake": Event(
        ("instant",), "follow", ("runtime/follow.py",),
        ("runtime/explain.py",),
        "Solo follow wake (including a joiner's catch-up poll).",
    ),
    # ---------------------------------------------------- scheduler instants
    "assign_map": Event(
        ("instant",), "sched", ("runtime/scheduler.py",),
        ("runtime/explain.py",),
        "Map task assigned to a worker (attempt number in args).",
    ),
    "assign_reduce": Event(
        ("instant",), "sched", ("runtime/scheduler.py",),
        ("runtime/explain.py",),
        "Reduce task assigned to a worker.",
    ),
    "map_committed": Event(
        ("instant",), "sched", ("runtime/scheduler.py",),
        ("runtime/explain.py",),
        "Map task commit accepted (attempt resolution done).",
    ),
    "reduce_committed": Event(
        ("instant",), "sched", ("runtime/scheduler.py",),
        ("runtime/explain.py",),
        "Reduce task commit accepted.",
    ),
    "grace_declared": Event(
        ("instant",), "sched", ("runtime/scheduler.py",), (),
        "Compile-grace window declared for a fresh device-compile shape.",
    ),
    "task_timeout": Event(
        ("instant",), "sched", ("runtime/scheduler.py",),
        ("runtime/explain.py",),
        "Task attempt timed out and was re-enqueued.",
    ),
    "map_lost_output": Event(
        ("instant", "daemon"), "sched",
        ("runtime/scheduler.py",),
        ("runtime/explain.py",),
        "Peer-held map output reported lost: producing task re-enqueued "
        "(also a job-tagged daemon lifecycle record).",
    ),
    "quarantine": Event(
        ("instant", "daemon"), "sched",
        ("runtime/scheduler.py",),
        ("runtime/explain.py",),
        "Worker parked after consecutive attributed failures (also a "
        "daemon lifecycle record via WorkerHealth.on_event).",
    ),
    # ------------------------------------------------------ service instants
    "resume": Event(
        ("instant", "daemon"), "service", ("runtime/service.py",),
        ("runtime/explain.py",),
        "Job resumed across a daemon restart (journal replayed); also the "
        "daemon-scope restart record.",
    ),
    "spans_dropped": Event(
        ("instant",), "pipeline", ("utils/spans.py",), (),
        "SpanBuffer shed oldest records under its cap (count in args).",
    ),
    # ------------------------------------------------- daemon lifecycle kinds
    "start": Event(
        ("daemon",), "", ("runtime/service.py",), ("runtime/explain.py",),
        "Daemon incarnation started serving.",
    ),
    "stop": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "Graceful daemon stop.",
    ),
    "job_terminal": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "A job reached a terminal state (state in payload).",
    ),
    "lease_lost": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "Write fence tripped: this daemon's lease token no longer matches.",
    ),
    "admission_reject": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "Submit rejected (queue full, deposed, or validation).",
    ),
    "worker_attach": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "New worker id allocated and registered.",
    ),
    "worker_expire": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "Worker row expired after an hour of silence.",
    ),
    "stream_shed": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "A follow job's StreamRing shed records under its cap.",
    ),
    "scale_advice": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "Elastic-pool advice changed (grow/shrink/hold, change-gated).",
    ),
    "scale_action": Event(
        ("daemon",), "", ("runtime/service.py",), (),
        "Scaler thread acted on advice (workers count in payload).",
    ),
    "quarantine_expire": Event(
        ("daemon",), "", ("runtime/scheduler.py",), (),
        "A quarantine window expired: worker re-probationed.",
    ),
    "quarantine_clear": Event(
        ("daemon",), "", ("runtime/scheduler.py",), (),
        "A committed task cleared a worker's failure streak.",
    ),
    "standby_park": Event(
        ("daemon",), "", ("__main__.py",), (),
        "Standby parked behind a live lease for parked_s seconds.",
    ),
    "lease_acquire": Event(
        ("daemon",), "", ("__main__.py",), ("utils/spans.py",),
        "Work-root lease acquired fresh (epoch 1 or uncontended).",
    ),
    "lease_steal": Event(
        ("daemon",), "", ("__main__.py",), ("utils/spans.py",),
        "Stale lease stolen (prev_epoch in payload — the durable failover "
        "record).",
    ),
    "promoted": Event(
        ("daemon",), "", ("__main__.py",),
        ("utils/spans.py", "runtime/explain.py"),
        "Standby finished resume and is serving (failover_s in payload).",
    ),
}


def is_family(key: str) -> bool:
    return "*" in key


def family_concrete(key: str, ev: Event) -> tuple:
    """All concrete names a family key's declared members expand to."""
    return tuple(key.replace("*", m) for m in ev.members)


def concrete_names() -> frozenset:
    """Every declared exact name plus every enumerated family member."""
    out = set()
    for key, ev in EVENTS.items():
        if is_family(key):
            out.update(family_concrete(key, ev))
        else:
            out.add(key)
    return frozenset(out)


def lookup(name: str):
    """Declaration for a concrete emitted/consumed name (exact wins),
    or for a family pattern like ``scan:*`` synthesized from an f-string.
    Returns (registry key, Event) or None."""
    ev = EVENTS.get(name)
    if ev is not None:
        return name, ev
    for key, fam in EVENTS.items():
        if is_family(key) and name in family_concrete(key, fam):
            return key, fam
    return None


def event_docs() -> str:
    """Markdown table of the whole vocabulary (``analyze --events``)."""
    lines = [
        "# Telemetry event vocabulary",
        "",
        "| name | kind | cat | owner | consumers | doc |",
        "|------|------|-----|-------|-----------|-----|",
    ]
    for key in sorted(EVENTS):
        ev = EVENTS[key]
        name = key
        if is_family(key):
            name = f"{key} ({'|'.join(ev.members)})"
        lines.append(
            "| `{}` | {} | {} | {} | {} | {} |".format(
                name, "/".join(ev.kinds), ev.cat or "-",
                ", ".join(ev.owners), ", ".join(ev.consumers) or "-",
                ev.doc,
            )
        )
    lines.append("")
    return "\n".join(lines)

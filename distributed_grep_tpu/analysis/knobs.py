"""Registry of every ``DGREP_*`` environment knob: name -> (owner module,
default, one-line doc).

This is the single source of truth rule R4 (``env-knobs``) enforces: each
knob may be READ (``os.environ.get`` / ``os.getenv`` / ``os.environ[...]``)
in exactly one module — its owner — so two call sites can never parse the
same override differently (the failure mode DGREP_BATCH_BYTES already
guards against via ``ops/layout.env_batch_bytes``: a planner that accepts
a malformed value its worker engines then crash on).  Other modules that
need a knob's value import the owner's accessor.

The registry doubles as generated operator docs: ``python -m
distributed_grep_tpu analyze --knobs`` renders it as a markdown table.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Knob:
    owner: str  # package-relative module path, e.g. "ops/engine.py"
    default: str  # human-readable default
    doc: str  # one line: what the knob controls


KNOBS: dict[str, Knob] = {
    "DGREP_COMPILE_GRACE_S": Knob(
        "ops/engine.py", "90",
        "Heartbeat grace window declared per fresh device-compile shape "
        "(cold XLA/Mosaic compiles run 20-40 s with no progress).",
    ),
    "DGREP_DEVICE_PROBE_S": Knob(
        "ops/engine.py", "30",
        "First-touch device responsiveness wall: jax backend init is "
        "time-boxed on a side thread (a wedged tunnel hangs it in C).",
    ),
    "DGREP_DEVICE_STALL_S": Knob(
        "ops/engine.py", "300",
        "Mid-scan per-segment stall wall before the scan degrades to the "
        "exact host engines.",
    ),
    "DGREP_DEVICE_RETRY_S": Knob(
        "ops/engine.py", "600",
        "How often a degraded engine re-probes the device (0 disables); "
        "the verdict is process-global.",
    ),
    "DGREP_DEVICE_MIN_BYTES": Knob(
        "ops/layout.py", "1048576",
        "Inputs below this host-scan when the default backend is a real "
        "accelerator; also the map-split planner's 'small file' bound "
        "(one parse, ops/layout.env_device_min_bytes).",
    ),
    "DGREP_BATCH_BYTES": Knob(
        "ops/layout.py", "33554432",
        "Cross-file packing window for sub-threshold inputs (0 disables); "
        "one parse (ops/layout.env_batch_bytes) shared by the planner and "
        "the engine packing cap.",
    ),
    "DGREP_NO_CALIBRATE": Knob(
        "ops/device_scan.py", "unset",
        "1 disables the FDR tuner's init confirm probe + post-scan retune "
        "(deterministic CI).",
    ),
    "DGREP_CONFIRM_THREADS": Knob(
        "models/fdr.py", "min(8, cpu_count)",
        "Declared confirm-thread fan of the deployment; prices the FDR "
        "filter/confirm trade.",
    ),
    "DGREP_SWAR": Knob(
        "ops/pallas_scan.py", "unset",
        "1 routes eligible short equality-class patterns through the SWAR "
        "packed shift-and kernel (default off: no real-chip receipt yet).",
    ),
    "DGREP_SPOOL_DIR": Knob(
        "runtime/http_transport.py", "system temp dir",
        "Directory HTTP workers spool oversized task payloads to.",
    ),
    "DGREP_SPANS": Knob(
        "utils/spans.py", "unset",
        "Force the span/event observability pipeline on (operator "
        "override of JobConfig.spans).",
    ),
    "DGREP_TRACE_DIR": Knob(
        "utils/trace.py", "unset",
        "Directory for the jax.profiler device trace; also enables "
        "annotate() regions.",
    ),
    "DGREP_LOG": Knob(
        "utils/logging.py", "INFO",
        "Log level for the structured control-plane logger.",
    ),
    "DGREP_SERVICE_MAX_JOBS": Knob(
        "runtime/service.py", "4",
        "Concurrent running-job cap of the grep-as-a-service daemon "
        "(accessor: runtime/service.env_service_max_jobs).",
    ),
    "DGREP_SERVICE_QUEUE": Knob(
        "runtime/service.py", "64",
        "Queued-submission cap (admission control) of the service daemon; "
        "submits beyond it answer 429 (accessor: env_service_queue).",
    ),
    "DGREP_SERVICE_RESUME": Knob(
        "runtime/service.py", "1",
        "Crash-recovery resume of the service daemon: a restart replays "
        "the work root's jobs.jsonl registry (re-admit queued, resume "
        "running jobs from their journals); 0/false disables "
        "(accessor: env_service_resume).",
    ),
    "DGREP_WORKER_QUARANTINE_S": Knob(
        "runtime/scheduler.py", "30",
        "Base quarantine window for flaky workers: after 3 consecutive "
        "attributed task timeouts a worker receives no assignments for "
        "base * 2^(episode-1) seconds (capped at 8x; accessor: "
        "runtime/scheduler.env_worker_quarantine_s).",
    ),
    "DGREP_RPC_RETRIES": Knob(
        "runtime/http_transport.py", "6",
        "Transient-error retries per client HTTP call (worker RPCs, data "
        "plane, CLI client_call); 0 disables (accessor: env_rpc_retries).",
    ),
    "DGREP_RPC_BACKOFF_S": Knob(
        "runtime/http_transport.py", "0.5",
        "Base backoff between transient-error retries: exponential, "
        "capped at 5 s per sleep, +/-50% jitter so a daemon restart's "
        "synchronized failures do not retry in lockstep (accessor: "
        "env_rpc_backoff_s).",
    ),
    "DGREP_CORPUS_BYTES": Knob(
        "ops/layout.py", "backend-sized (0 on CPU, 1 GiB on accelerators)",
        "Device-resident corpus cache byte budget (ops/layout.CorpusCache; "
        "0 disables): packed/padded HBM segments stay resident per content "
        "key so a repeat query over unchanged inputs skips the read/pack/"
        "upload path (accessor: ops/layout.env_corpus_bytes).",
    ),
    "DGREP_MODEL_CACHE": Knob(
        "ops/engine.py", "32",
        "Entry cap of the cross-job compiled-model cache (0 disables; "
        "accessor: ops/engine.env_model_cache_entries) — a cache hit "
        "returns the same engine, skipping model compile and the "
        "per-shape compile-grace path.",
    ),
    "DGREP_EVENT_AUDIT": Knob(
        "utils/event_audit.py", "unset",
        "1 switches the runtime event-vocabulary recorder on: every "
        "span/instant/daemon-event name emitted through SpanBuffer, "
        "EventLog, or DaemonLog is validated against the "
        "analysis/events.py registry and undeclared names log warnings "
        "(accessor: utils/event_audit.env_event_audit).  The "
        "service/obs/follow/fuse/result/chaos test fixture activates it "
        "per test — the dynamic half of analyze rule event-registry.",
    ),
    "DGREP_LOCKDEP": Knob(
        "utils/lockdep.py", "unset",
        "1 switches the runtime lock-discipline harness on: locks built "
        "via lockdep.make_lock are instrumented (per-thread acquisition "
        "stacks, lock-order inversion + blocking-syscall-while-held "
        "detection; accessor: utils/lockdep.env_lockdep).  The "
        "service/chaos/soak_mini test fixture activates it per test.",
    ),
    "DGREP_NATIVE_RECORDS": Knob(
        "utils/native.py", "on",
        "0/false disables the native map-record pipeline (round 8: "
        "dgrep_unique_lines / dgrep_line_spans / dgrep_build_records — "
        "kernel output to partitioned mr-out slabs in one C pass); the "
        "numpy fallbacks then serve every call, byte-identical "
        "(accessor: utils/native.env_native_records).  Debug kill-switch.",
    ),
    "DGREP_SERVICE_FUSE": Knob(
        "runtime/fusion.py", "1",
        "Cross-tenant scan fusion of the service daemon (round 13): "
        "co-running print-mode grep jobs over content-identical splits "
        "share ONE worker scan per split; 0/false disables planning "
        "entirely — wire payloads, journals, and outputs then match the "
        "pre-fusion daemon byte for byte (accessor: "
        "runtime/fusion.env_service_fuse).",
    ),
    "DGREP_FUSE_MAX_QUERIES": Knob(
        "runtime/fusion.py", "8",
        "Queries per fused attempt cap: bounds the union automaton's "
        "size and the blast radius of one lost worker (each re-enqueued "
        "participant re-runs solo; accessor: "
        "runtime/fusion.env_fuse_max_queries).",
    ),
    "DGREP_NATIVE_LIB": Knob(
        "utils/native.py", "unset",
        "Absolute path of the libdgrep build to load instead of "
        "native/libdgrep.so (sanitizer builds: libdgrep-asan.so / "
        "libdgrep-tsan.so); a set-but-unloadable path raises instead of "
        "silently degrading to the Python fallbacks.",
    ),
    "DGREP_INDEX": Knob(
        "index/summary.py", "on",
        "Shard-index tier (trigram summaries route queries past shards "
        "that cannot match): 0/false disables every lookup, build, and "
        "prune — planning, wire payloads, and outputs revert to the "
        "pre-index behavior exactly (accessor: "
        "index/summary.env_index_enabled).",
    ),
    "DGREP_METRICS_WINDOW_S": Knob(
        "utils/metrics.py", "300",
        "Rolling-window width for the /metrics cache-hit rate gauges "
        "(dgrep_window_* / *_hit_ratio): piggybacked counter deltas "
        "older than this many seconds age out of the windowed totals "
        "(accessor: utils/metrics.env_metrics_window_s).",
    ),
    "DGREP_PEER_SHUFFLE": Knob(
        "runtime/peer.py", "1",
        "Peer-to-peer shuffle (round 16): service-attached workers keep "
        "map output on their local spool and reducers fetch it directly "
        "from the producer — the daemon moves shuffle METADATA only; "
        "0/false reverts to the relay data plane exactly (no server, no "
        "spool, byte-identical wire payloads; accessor: "
        "runtime/peer.env_peer_shuffle).",
    ),
    "DGREP_PEER_PORT": Knob(
        "runtime/peer.py", "0",
        "Worker shuffle data-server listen port (0 = ephemeral, the "
        "default — N worker processes per host each bind their own; "
        "accessor: runtime/peer.env_peer_port).",
    ),
    "DGREP_PEER_HOST": Knob(
        "runtime/peer.py", "bind host",
        "Advertised shuffle-endpoint host override for workers behind "
        "NAT/wildcard binds — peers must dial a routable name "
        "(accessor: runtime/peer.env_peer_host).",
    ),
    "DGREP_PEER_BIND": Knob(
        "runtime/peer.py", "127.0.0.1; 0.0.0.0 when DGREP_PEER_HOST set",
        "Shuffle data-server BIND address.  Loopback by default; a set "
        "DGREP_PEER_HOST implies the wildcard (an advertised routable "
        "name a loopback-bound server can never honor); set both for a "
        "specific-interface bind (accessor: runtime/peer.env_peer_bind).",
    ),
    "DGREP_FOLLOW_POLL_S": Knob(
        "runtime/follow.py", "0.5",
        "Standing-query wake cadence (round 17): how often a follow "
        "job's runner stats its inputs and suffix-scans growth; wins "
        "over JobConfig.follow_poll_s as the operator override "
        "(accessor: runtime/follow.env_follow_poll_s).",
    ),
    "DGREP_STREAM_BUFFER": Knob(
        "runtime/follow.py", "4194304",
        "Per-subscriber stream buffer byte cap for GET "
        "/jobs/<id>/stream: past it the oldest records shed (counted in "
        "stream_dropped_records, surfaced as an explicit `dropped` "
        "count to the lagging consumer) — the scan loop never blocks "
        "(accessor: runtime/follow.env_stream_buffer).",
    ),
    "DGREP_FOLLOW_FUSE": Knob(
        "runtime/follow.py", "1",
        "Fused follow tier (round 21): follow jobs sharing a "
        "fusion-eligible (watched-input identity x non-query options) "
        "key ride ONE group wake loop — one stat + one union suffix "
        "scan per (file, wake) serves every member; 0/false disables "
        "the group registry entirely — solo runners, /status, and wire "
        "payloads then match the pre-fusion daemon byte for byte "
        "(accessor: runtime/follow.env_follow_fuse).",
    ),
    "DGREP_LEASE_TTL_S": Knob(
        "runtime/lease.py", "10",
        "Work-root lease staleness wall (round 18 active/standby "
        "failover): a standby steals the lease — and promotes via the "
        "resume path — once the active's renewal stamp is older than "
        "this many seconds.  Setting it is also the env-side HA switch "
        "(like `dgrep serve --standby`); unset single-daemon "
        "deployments never create a lease file (accessor: "
        "runtime/lease.env_lease_ttl_s).",
    ),
    "DGREP_LEASE_RENEW_S": Knob(
        "runtime/lease.py", "ttl/3",
        "Active daemon's lease renewal cadence (and the standby's "
        "lease-poll interval).  Default ttl/3 — three missed renewals "
        "before the lease goes stale (accessor: "
        "runtime/lease.env_lease_renew_s).",
    ),
    "DGREP_DAEMON_LOG": Knob(
        "runtime/daemon_log.py", "1",
        "Daemon lifecycle event log (round 19): serving daemons append "
        "lease/quarantine/scale/admission/terminal events to "
        "<work_root>/daemon.jsonl for trace-export --fleet and dgrep "
        "explain disruptions; 0 is a true no-op — no file, no staged "
        "list, /status byte-identical (accessor: "
        "runtime/daemon_log.env_daemon_log).",
    ),
    "DGREP_TOP_INTERVAL_S": Knob(
        "__main__.py", "2",
        "Refresh cadence of the `dgrep top` live console between "
        "/status + /metrics polls (accessor: "
        "__main__.env_top_interval_s).",
    ),
    "DGREP_INDEX_SUMMARY_BYTES": Knob(
        "index/summary.py", "16384",
        "Per-shard trigram bloom size, rounded down to a power of two in "
        "[1 KB, 1 MB]; larger summaries lower the bloom false-positive "
        "rate on trigram-dense shards (accessor: "
        "index/summary.env_summary_bytes).",
    ),
    "DGREP_RESULT_CACHE": Knob(
        "runtime/result_cache.py", "on",
        "Query-result cache (round 20): the daemon persists each "
        "eligible job's results per map split under <work_root>/results/ "
        "and answers repeated queries over unchanged inputs from the "
        "store (full hit: no scheduler, no scan; partial hit: only "
        "drifted splits rescan).  0/false is a true no-op — no results/ "
        "dir, no /status key, byte-identical behavior.  One-shot CLI "
        "jobs never consult the tier (accessor: "
        "runtime/result_cache.env_result_cache).",
    ),
    "DGREP_RESULT_BYTES": Knob(
        "runtime/result_cache.py", "268435456",
        "On-disk byte budget for the result store (whole-entry LRU by "
        "mtime; loads touch).  0 disables the tier like "
        "DGREP_RESULT_CACHE=0; an entry larger than the whole budget is "
        "declined outright (accessor: "
        "runtime/result_cache.env_result_bytes).",
    ),
}


def knob_docs() -> str:
    """The registry as a markdown table — the generated operator docs."""
    rows = ["| knob | owner | default | controls |",
            "| --- | --- | --- | --- |"]
    for name in sorted(KNOBS):
        k = KNOBS[name]
        rows.append(f"| `{name}` | `{k.owner}` | {k.default} | {k.doc} |")
    return "\n".join(rows) + "\n"

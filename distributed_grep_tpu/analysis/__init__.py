"""Project static analysis: machine-checked invariants (rules.py), the
DGREP_* env-knob registry (knobs.py), and the ``analyze`` CLI driver
(checker.py).  RE2/Hyperscan-style: constructs the execution engine can't
honor are rejected at check time, not discovered in a prod job."""

from distributed_grep_tpu.analysis.checker import run_analysis
from distributed_grep_tpu.analysis.rules import RULES, Project, Violation

__all__ = ["run_analysis", "RULES", "Project", "Violation"]

"""The invariant rules: each encodes one correctness contract this repo
previously documented only as CLAUDE.md prose (or enforced as a grep test).

Every rule walks real ASTs (no regex-over-source false positives from
strings or comments) and reports ``Violation(rule, path, line, message)``
records.  Rules are registered in ``RULES``; the checker (checker.py) runs
them over a project root — the installed package by default, a fixture
mini-tree in tests/test_analysis.py, which pins each rule against both
false negatives (fires on a known-bad snippet) and false positives (stays
silent on this repo).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable, Iterator


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # project-root-relative, posix separators
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Project:
    """Parsed view of a source tree; trees are parsed once and shared."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self._cache: dict[str, ast.Module | None] = {}
        self._files: list[str] | None = None
        # scratch space rules share within one run (e.g. the R9/R10 lock
        # declarations, derived once per file instead of once per rule)
        self.cache: dict = {}

    def files(self) -> list[str]:
        if self._files is None:
            self._files = sorted(
                p.relative_to(self.root).as_posix()
                for p in self.root.rglob("*.py")
                if "__pycache__" not in p.parts
            )
        return self._files

    def tree(self, rel: str) -> ast.Module | None:
        if rel not in self._cache:
            try:
                src = (self.root / rel).read_text(encoding="utf-8",
                                                  errors="surrogateescape")
                self._cache[rel] = ast.parse(src)
            except (OSError, SyntaxError, ValueError):
                # ValueError: ast.parse raises UnicodeEncodeError on
                # surrogateescape-decoded non-UTF-8 source — skip the
                # file like a SyntaxError, don't abort the whole run
                self._cache[rel] = None
        return self._cache[rel]


# --------------------------------------------------------------- AST helpers

def _last_name(node: ast.AST) -> str:
    """Trailing identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted form of a Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _str_consts(node: ast.AST) -> Iterator[str]:
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value
        elif isinstance(n, ast.Constant) and isinstance(n.value, bytes):
            yield n.value.decode("latin-1")


def _scope_assignments(scope: ast.AST) -> dict[str, list[ast.expr]]:
    """name -> assigned value expressions, within one function/module scope
    (nested function bodies are NOT descended — they are their own scope)."""
    out: dict[str, list[ast.expr]] = {}

    def visit(body: Iterable[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign) and stmt.value is not None:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.setdefault(tgt.id, []).append(stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    out.setdefault(stmt.target.id, []).append(stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                if isinstance(stmt.target, ast.Name):
                    out.setdefault(stmt.target.id, []).append(stmt.value)
            # descend statement bodies that stay in this scope
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    visit(sub)
            for h in getattr(stmt, "handlers", []) or []:
                visit(h.body)

    visit(scope.body)  # type: ignore[attr-defined]
    return out


def _enclosing_scopes(tree: ast.Module) -> list[tuple[ast.AST, list[ast.AST]]]:
    """[(scope_node, [calls and other nodes directly in that scope])] for
    the module and every (possibly nested) function."""
    scopes: list[tuple[ast.AST, list[ast.AST]]] = []

    def collect(scope: ast.AST) -> None:
        nodes: list[ast.AST] = []
        stack = list(getattr(scope, "body", []))
        funcs: list[ast.AST] = []
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.append(n)
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        scopes.append((scope, nodes))
        for f in funcs:
            collect(f)

    collect(tree)
    return scopes


# ------------------------------------------------------------------- rule R1

_RE_PATTERN_FUNCS = {"compile", "search", "match", "fullmatch", "finditer",
                     "findall", "sub", "subn", "split"}
_SANITIZERS = {"expand_posix_classes", "escape"}


def _re_aliases(tree: ast.Module) -> set[str]:
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "re":
                    names.add(a.asname or "re")
    return names


def _expr_sanitized(expr: ast.expr, env: dict[str, list[ast.expr]],
                    visited: set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _last_name(node.func) in _SANITIZERS:
            return True
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id not in visited:
            visited.add(node.id)
            for v in env.get(node.id, ()):
                if _expr_sanitized(v, env, visited):
                    return True
    return False


def _resolves_to_literal(expr: ast.expr, env: dict[str, list[ast.expr]],
                         visited: set[str] | None = None) -> bool:
    """True when the pattern is built from constants alone — an
    app-internal literal the author wrote, not a user pattern.  Names
    resolve through the scope's assignments, so a hoisted module constant
    (``_WORD = rb"[A-Za-z]+"`` ... ``re.findall(_WORD, ...)``) stays
    exempt; any Call/Attribute, or a name with no all-literal assignment,
    makes it computed."""
    if visited is None:
        visited = set()
    if any(isinstance(n, (ast.Call, ast.Attribute)) for n in ast.walk(expr)):
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            if node.id in visited:
                continue
            visited.add(node.id)
            vals = env.get(node.id)
            if not vals or not all(
                    _resolves_to_literal(v, env, visited) for v in vals):
                return False
    return True


def rule_posix_expand(project: Project) -> Iterator[Violation]:
    """R1: every ``re`` handoff of a non-literal pattern must route through
    ``models/dfa.expand_posix_classes`` (or ``re.escape`` for literals).
    Python's re misparses POSIX bracket classes ('[[:digit:]]' matches
    ':digit' members), so an unexpanded handoff silently changes the
    language the confirm/fallback matcher accepts."""
    for rel in project.files():
        tree = project.tree(rel)
        if tree is None:
            continue
        aliases = _re_aliases(tree)
        if not aliases:
            continue
        module_env = _scope_assignments(tree)
        for scope, nodes in _enclosing_scopes(tree):
            env = dict(module_env)
            if scope is not tree:
                env.update(_scope_assignments(scope))
            for node in nodes:
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _RE_PATTERN_FUNCS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in aliases
                        and node.args):
                    continue
                pat = node.args[0]
                if _resolves_to_literal(pat, env):
                    continue
                if _expr_sanitized(pat, env, set()):
                    continue
                yield Violation(
                    "posix-expand", rel, node.lineno,
                    f"re.{node.func.attr} on a computed pattern with no "
                    f"expand_posix_classes/re.escape on any path to it — "
                    f"POSIX bracket classes would be misparsed by re",
                )


# ------------------------------------------------------------------- rule R2

_RAW_READERS = {"glob", "iglob", "rglob", "listdir", "scandir", "iterdir"}


def rule_store_resolve(project: Project) -> Iterator[Violation]:
    """R2: no raw ``glob``/``listdir``/``open`` over work-dir ``mr-*``
    artifacts outside runtime/store.py.  On non-atomic stores, commit
    RECORDS are the unit of truth — a raw directory scan sees torn
    ``.part.*`` files and duplicate attempts; readers must resolve through
    ``WorkDir.list_outputs`` / ``store.get``."""
    for rel in project.files():
        if rel == "runtime/store.py":
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_reader = (
                (isinstance(fn, ast.Name) and fn.id == "open")
                or (isinstance(fn, ast.Attribute) and fn.attr in _RAW_READERS)
            )
            if not is_reader:
                continue
            hit = next(
                (s for a in list(node.args)
                 + [k.value for k in node.keywords]
                 for s in _str_consts(a) if "mr-" in s),
                None,
            )
            if hit is not None:
                name = _last_name(fn) or "open"
                yield Violation(
                    "store-resolve", rel, node.lineno,
                    f"raw {name}() over {hit!r}: mr-* artifacts must "
                    f"resolve through the work dir's Store "
                    f"(WorkDir.list_outputs / store.get) — commit records, "
                    f"not file existence, are the unit of truth",
                )


# ------------------------------------------------------------------- rule R3

_R3_SCOPE = ("runtime/", "apps/")
_R3_FILES = ("__main__.py",)
_UTF8 = {None, "utf-8", "utf8", "UTF-8", "UTF8"}


def rule_surrogateescape(project: Project) -> Iterator[Violation]:
    """R3: str<->bytes conversions on the data plane (runtime/, apps/, the
    CLI) must state an ``errors=`` policy.  Pattern and path bytes
    round-trip via surrogateescape everywhere (display decodes use
    'replace' deliberately); a bare .encode()/.decode() is a latent
    UnicodeError on the first non-UTF-8 filename or pattern byte.
    json.dumps(...).encode(...) is exempt (ASCII by construction), as are
    non-UTF-8 codecs (declared fixed-alphabet data, e.g. ascii hex)."""
    for rel in project.files():
        if not (rel.startswith(_R3_SCOPE) or rel in _R3_FILES):
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("encode", "decode")):
                continue
            encoding = None
            if node.args and isinstance(node.args[0], ast.Constant):
                encoding = node.args[0].value
            for k in node.keywords:
                if k.arg == "encoding" and isinstance(k.value, ast.Constant):
                    encoding = k.value.value
            if node.args and not isinstance(node.args[0], ast.Constant):
                encoding = "<dynamic>"  # can't prove it's utf-8: still flag
            if isinstance(encoding, str) and encoding not in _UTF8 \
                    and encoding != "<dynamic>":
                continue  # ascii/latin-1 etc: fixed-alphabet by declaration
            has_errors = len(node.args) >= 2 or any(
                k.arg == "errors" for k in node.keywords)
            if has_errors:
                continue
            recv = node.func.value
            if isinstance(recv, ast.Call) and _last_name(recv.func) == "dumps":
                continue  # json.dumps output is ASCII by construction
            yield Violation(
                "surrogateescape", rel, node.lineno,
                f".{node.func.attr}() without an errors= policy on a "
                f"data-plane path — pattern/path bytes round-trip via "
                f"surrogateescape (display output uses 'replace')",
            )


# ------------------------------------------------------------------- rule R4

def _env_reads(tree: ast.Module) -> Iterator[tuple[str, int]]:
    """(var, line) for each environment READ with a resolvable key.
    Key constants resolve through EVERY scope's assignments (module
    ``_ENV_VAR = ...`` indirection and function-local names alike); a
    name assigned several string constants yields each — over-reporting
    beats a knob read hidden behind a local variable."""
    consts: dict[str, set[str]] = {}
    for scope, _ in _enclosing_scopes(tree):
        for name, exprs in _scope_assignments(scope).items():
            for e in exprs:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    consts.setdefault(name, set()).add(e.value)

    def resolve(arg: ast.expr) -> set[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return {arg.value}
        if isinstance(arg, ast.Name):
            return consts.get(arg.id, set())
        return set()

    for node in ast.walk(tree):
        keys: set[str] = set()
        if isinstance(node, ast.Call) and node.args:
            dn = _dotted(node.func)
            if dn.endswith("environ.get") or _last_name(node.func) == "getenv":
                keys = resolve(node.args[0])
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.ctx, ast.Load)
              and _last_name(node.value) == "environ"):
            keys = resolve(node.slice)
        for var in sorted(keys):
            yield var, node.lineno


def rule_env_knobs(project: Project) -> Iterator[Violation]:
    """R4: each DGREP_* env knob is read by exactly one owner module — the
    one registered in analysis/knobs.py (which doubles as the generated
    operator docs).  Two parsers of one knob can disagree on a malformed
    override (the DGREP_BATCH_BYTES failure mode env_batch_bytes guards);
    an unregistered knob is undocumented and unowned."""
    from distributed_grep_tpu.analysis.knobs import KNOBS

    seen: dict[str, list[tuple[str, int]]] = {}
    for rel in project.files():
        tree = project.tree(rel)
        if tree is None:
            continue
        for var, line in _env_reads(tree):
            if var.startswith("DGREP_"):
                seen.setdefault(var, []).append((rel, line))
    for var in sorted(seen):
        knob = KNOBS.get(var)
        for rel, line in seen[var]:
            if knob is None:
                yield Violation(
                    "env-knobs", rel, line,
                    f"unregistered env knob {var}: add it (owner, default, "
                    f"doc) to analysis/knobs.py KNOBS",
                )
            elif rel != knob.owner:
                yield Violation(
                    "env-knobs", rel, line,
                    f"{var} read outside its owner module {knob.owner} — "
                    f"import the owner's accessor instead of re-parsing "
                    f"the env var",
                )
    # stale registry entries: the owner module exists but never reads the
    # knob (fixture mini-trees without the owner file stay silent)
    for var, knob in KNOBS.items():
        if var in seen:
            continue
        if (project.root / knob.owner).exists():
            yield Violation(
                "env-knobs", knob.owner, 1,
                f"registered env knob {var} is never read by its owner "
                f"{knob.owner}: stale registry entry in analysis/knobs.py",
            )


# ------------------------------------------------------------------- rule R5

def _is_dataclass(node: ast.ClassDef) -> bool:
    return any(_last_name(d if not isinstance(d, ast.Call) else d.func)
               == "dataclass" for d in node.decorator_list)


def _field_default(expr: ast.expr | None):
    """(known, value): the field's declared default, when statically
    derivable.  field(default_factory=list/dict) -> []/{}."""
    if expr is None:
        return False, None
    if isinstance(expr, ast.Constant):
        return True, expr.value
    if isinstance(expr, ast.Call) and _last_name(expr.func) == "field":
        for k in expr.keywords:
            if k.arg == "default_factory":
                factory = _last_name(k.value)
                if factory == "list":
                    return True, []
                if factory == "dict":
                    return True, {}
            if k.arg == "default" and isinstance(k.value, ast.Constant):
                return True, k.value.value
    return False, None


def _is_optional_ann(ann: ast.expr | None) -> bool:
    if ann is None:
        return False
    for n in ast.walk(ann):
        if isinstance(n, ast.Constant) and n.value is None:
            return True
        if isinstance(n, ast.Name) and n.id == "Optional":
            return True
        # annotations arrive as strings under `from __future__ import
        # annotations`-style quoting: "dict | None"
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and "None" in n.value:
            return True
    return False


def _str_seq_assign(tree: ast.AST, name: str):
    """(values, lineno) of a module-level ``NAME = ("a", "b", ...)`` tuple/
    list of string constants; (None, 1) when absent."""
    for node in ast.walk(tree):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if (targets
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return ([e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)], node.lineno)
    return None, 1


def rule_rpc_elide(project: Project, rel: str = "runtime/rpc.py"
                   ) -> Iterator[Violation]:
    """R5: wire-compat reflection over the RPC schema, both halves.  Every
    Optional-default field on the rpc dataclasses must appear in
    ``_ELIDE_DEFAULTS`` (else a span-disabled run's payloads grow keys old
    peers choke on), every elide key must exist as a field, and the
    registered elide default must EQUAL the field's declared default on
    every dataclass carrying it (drift silently un-elides the field).
    Reply side: every field on a ``*Reply`` dataclass must be declared on
    exactly one side of the wire contract — ``_REPLY_BASE`` (historical
    asdict shape, always present) or ``_REPLY_ELIDE`` (dropped at its
    falsy default by ``reply_to_dict`` — old peers interop) — and an
    elide-registered field's default must be falsy, because reply_to_dict
    elides by ``not value``: a truthy default never elides and the
    registration is a lie."""
    tree = project.tree(rel)
    if tree is None:
        return
    elide: dict[str, object] | None = None
    elide_line = 1
    for node in ast.walk(tree):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if (targets
                and any(isinstance(t, ast.Name) and t.id == "_ELIDE_DEFAULTS"
                        for t in targets)
                and isinstance(node.value, ast.Dict)):
            elide, elide_line = {}, node.lineno
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    try:
                        elide[k.value] = ast.literal_eval(v)
                    except ValueError:
                        elide[k.value] = _field_default(v)[1]
    if elide is None:
        yield Violation("rpc-elide", rel, 1,
                        "no _ELIDE_DEFAULTS dict literal found")
        return
    field_names: set[str] = set()
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and _is_dataclass(cls)):
            continue
        for stmt in cls.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            name = stmt.target.id
            field_names.add(name)
            known, default = _field_default(stmt.value)
            if (_is_optional_ann(stmt.annotation) and stmt.value is not None
                    and name not in elide):
                yield Violation(
                    "rpc-elide", rel, stmt.lineno,
                    f"Optional-default field {cls.name}.{name} missing from "
                    f"_ELIDE_DEFAULTS: span-disabled payloads would grow a "
                    f"key old peers reject",
                )
            if name in elide and known and elide[name] != default:
                yield Violation(
                    "rpc-elide", rel, stmt.lineno,
                    f"_ELIDE_DEFAULTS[{name!r}] == {elide[name]!r} but "
                    f"{cls.name}.{name} defaults to {default!r}: elision "
                    f"would silently stop matching the wire default",
                )
    for key in sorted(set(elide) - field_names):
        yield Violation(
            "rpc-elide", rel, elide_line,
            f"_ELIDE_DEFAULTS key {key!r} is not a field on any rpc "
            f"dataclass: dead elision entry",
        )

    replies = [cls for cls in ast.walk(tree)
               if isinstance(cls, ast.ClassDef) and _is_dataclass(cls)
               and cls.name.endswith("Reply")]
    if not replies:
        return
    base, base_line = _str_seq_assign(tree, "_REPLY_BASE")
    reply_elide, relide_line = _str_seq_assign(tree, "_REPLY_ELIDE")
    if base is None or reply_elide is None:
        yield Violation(
            "rpc-elide", rel, replies[0].lineno,
            "reply dataclasses present but _REPLY_BASE/_REPLY_ELIDE tuple "
            "literals missing: every reply field must declare its wire side",
        )
        return
    base_set, elide_set = set(base), set(reply_elide)
    for key in sorted(base_set & elide_set):
        yield Violation(
            "rpc-elide", rel, relide_line,
            f"reply field {key!r} registered in BOTH _REPLY_BASE and "
            f"_REPLY_ELIDE: the wire contract must pick one side",
        )
    reply_field_names: set[str] = set()
    for cls in replies:
        for stmt in cls.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            name = stmt.target.id
            reply_field_names.add(name)
            if name not in base_set and name not in elide_set:
                yield Violation(
                    "rpc-elide", rel, stmt.lineno,
                    f"reply field {cls.name}.{name} is in neither "
                    f"_REPLY_BASE nor _REPLY_ELIDE: a new reply field must "
                    f"declare its wire side (elide it unless old peers "
                    f"already expect the key)",
                )
            if name in elide_set:
                known, default = _field_default(stmt.value)
                if known and default:
                    yield Violation(
                        "rpc-elide", rel, stmt.lineno,
                        f"_REPLY_ELIDE field {cls.name}.{name} defaults to "
                        f"{default!r} (truthy): reply_to_dict elides falsy "
                        f"values only, so this registration never fires",
                    )
    for key in sorted((base_set | elide_set) - reply_field_names):
        yield Violation(
            "rpc-elide", rel,
            base_line if key in base_set else relide_line,
            f"reply registry key {key!r} is not a field on any *Reply "
            f"dataclass: dead wire-contract entry",
        )


# ------------------------------------------------------------------- rule R6

_NARROW = {"int8", "uint8", "int16", "uint16"}
_PROBED_GATHER_CEILING = 64  # benchmarks/probe_gather_ceiling.py, 2026-08-01
_PROBED_DOMAINS = {128, 256, 512, 1024}
_PROBED_UNROLLS = {1, 2, 4, 8, 16, 32}  # divisors of 32 (pallas_scan gate)


def _narrow_cast_in(expr: ast.expr) -> str | None:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            if (isinstance(n.func, ast.Attribute) and n.func.attr == "astype"
                    and n.args and _last_name(n.args[0]) in _NARROW):
                return _last_name(n.args[0])
            if _last_name(n.func) in _NARROW:
                return _last_name(n.func)
    return None


def _return_value_consts(fn: ast.FunctionDef) -> Iterator[tuple[int, int]]:
    """(value, line) for int constants a return statement can evaluate to
    (IfExp arms flattened; condition subtrees are NOT scanned)."""
    def arms(e: ast.expr) -> Iterator[ast.expr]:
        if isinstance(e, ast.IfExp):
            yield from arms(e.body)
            yield from arms(e.orelse)
        else:
            yield e

    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            for arm in arms(node.value):
                if isinstance(arm, ast.Constant) and isinstance(arm.value,
                                                                int):
                    yield arm.value, arm.lineno


def rule_mosaic_ceilings(project: Project) -> Iterator[Violation]:
    """R6: the Mosaic compile ceilings measured on real v5e hardware
    (BASELINE.md rounds 4-5), checked statically instead of discovered as
    kernel compile crashes: no u8/i8/i16 vector compares in Pallas kernel
    bodies ('Target does not support this comparison'), gather plans
    bounded by the probed MAX_GATHERS=64 ceiling, unroll factors within
    the probed divisor-of-32 set, FDR domains within the probed set."""
    pallas = [f for f in project.files()
              if f.startswith("ops/pallas_") and f.endswith(".py")]
    for rel in pallas:
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare):
                for operand in [node.left, *node.comparators]:
                    dt = _narrow_cast_in(operand)
                    if dt:
                        yield Violation(
                            "mosaic-ceilings", rel, node.lineno,
                            f"{dt} vector compare in a Pallas kernel file: "
                            f"Mosaic rejects sub-i32 vector cmpi on this "
                            f"target (probed round 4, probe_narrow.py) — "
                            f"widen to i32 first",
                        )
                        break
            if isinstance(node, ast.Call):
                for k in node.keywords:
                    if (k.arg == "unroll"
                            and isinstance(k.value, ast.Constant)
                            and isinstance(k.value.value, int)
                            and k.value.value not in _PROBED_UNROLLS):
                        yield Violation(
                            "mosaic-ceilings", rel, node.lineno,
                            f"unroll={k.value.value} outside the probed set "
                            f"{sorted(_PROBED_UNROLLS)}",
                        )
            if isinstance(node, ast.FunctionDef) and node.name == "unroll_for":
                for val, line in _return_value_consts(node):
                    if val not in _PROBED_UNROLLS:
                        yield Violation(
                            "mosaic-ceilings", rel, line,
                            f"unroll_for returns {val}, outside the probed "
                            f"set {sorted(_PROBED_UNROLLS)}",
                        )
    fdr = project.tree("models/fdr.py")
    if fdr is not None:
        for name, exprs in _scope_assignments(fdr).items():
            for e in exprs:
                if name == "MAX_GATHERS" and isinstance(e, ast.Constant):
                    if e.value > _PROBED_GATHER_CEILING:
                        yield Violation(
                            "mosaic-ceilings", "models/fdr.py", e.lineno,
                            f"MAX_GATHERS={e.value} exceeds the probed "
                            f"compile ceiling {_PROBED_GATHER_CEILING} "
                            f"(probe_gather_ceiling.py) — re-probe on a "
                            f"real chip before raising",
                        )
                if name == "DOMAINS" and isinstance(e, (ast.Tuple, ast.List)):
                    for el in e.elts:
                        if (isinstance(el, ast.Constant)
                                and isinstance(el.value, int)
                                and el.value not in _PROBED_DOMAINS):
                            yield Violation(
                                "mosaic-ceilings", "models/fdr.py", el.lineno,
                                f"DOMAINS entry {el.value} outside the "
                                f"probed power-of-two set "
                                f"{sorted(_PROBED_DOMAINS)}",
                            )


# ------------------------------------------------------------------- rule R7

_LOG_ROOTS = ("runtime/", "utils/", "parallel/")
_LOG_EXEMPT = "utils/logging.py"


def rule_logging(project: Project) -> Iterator[Violation]:
    """R7: control-plane modules (runtime/, utils/, parallel/) log via
    utils.logging.get_logger only — no bare print() (stdout is a DATA
    contract: bench.py's one-JSON-line, the CLI's user output), no root
    logging.getLogger.  Migrated from the grep-based obs test; AST-walked,
    so prints in nested expressions are caught too."""
    for rel in project.files():
        if not rel.startswith(_LOG_ROOTS):
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) and node.func.id == "print":
                    yield Violation(
                        "logging", rel, node.lineno,
                        "bare print() on a control-plane path (use "
                        "utils.logging.get_logger)",
                    )
                elif (rel != _LOG_EXEMPT
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr == "getLogger"
                      and _last_name(node.func.value) == "logging"):
                    yield Violation(
                        "logging", rel, node.lineno,
                        "root-logger use (want utils.logging.get_logger)",
                    )
            elif isinstance(node, ast.Assign):
                if (any(isinstance(t, ast.Name) and t.id == "log"
                        for t in node.targets)
                        and not (isinstance(node.value, ast.Call)
                                 and _last_name(node.value.func)
                                 == "get_logger")):
                    yield Violation(
                        "logging", rel, node.lineno,
                        "log defined without get_logger",
                    )


# ------------------------------------------------------------------- rule R8

_NET_SCOPE = ("runtime/",)
_NET_FILES = ("__main__.py",)
_NET_EXEMPT = "runtime/http_transport.py"
# Raw client-side connection constructors: urlopen plus the http.client /
# socket primitives it wraps.  Server-side classes (ThreadingHTTPServer)
# are not listed — serving has no retry story to bypass.
_RAW_NET_CALLS = {"urlopen", "create_connection", "HTTPConnection",
                  "HTTPSConnection"}


def rule_net_retry(project: Project) -> Iterator[Violation]:
    """R8: no raw ``urlopen``/client-socket calls on control-plane paths
    (runtime/, the CLI) outside runtime/http_transport.py — every client
    HTTP call routes through the transport's bounded-jittered-retry
    helpers (``HttpTransport._request`` / ``client_call``).  A raw call
    dies on the first transient connection reset, exactly the failure the
    retry layer exists to absorb (a daemon restart resets EVERY attached
    client at once), and silently forks the retry policy the
    DGREP_RPC_RETRIES/DGREP_RPC_BACKOFF_S knobs are supposed to govern.

    Round 18 extension (active/standby failover): comma-splitting an
    address outside http_transport is also flagged — address-list
    rotation lives INSIDE the shared retry loop (``split_addrs`` + the
    transport's rotating ``base``), and a hand-rolled split grows a
    second rotation policy that the failover machinery can't see (it
    would pin one member, or rotate on HTTPError, or skip the jittered
    backoff)."""
    for rel in project.files():
        if not (rel.startswith(_NET_SCOPE) or rel in _NET_FILES):
            continue
        if rel == _NET_EXEMPT:
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _last_name(node.func)
            if name in _RAW_NET_CALLS:
                yield Violation(
                    "net-retry", rel, node.lineno,
                    f"raw {name}() on a control-plane path: client HTTP "
                    f"calls must route through the retry-wrapped transport "
                    f"helpers (http_transport._request / client_call) — a "
                    f"bare call dies on the first transient reset and "
                    f"bypasses the DGREP_RPC_RETRIES policy",
                )
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "split"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and node.args[0].value == ","
                    and "addr" in ast.unparse(node.func.value).lower()):
                yield Violation(
                    "net-retry", rel, node.lineno,
                    "address list split outside http_transport: use "
                    "split_addrs / the transport's rotating base — a "
                    "hand-rolled comma split forks the failover rotation "
                    "policy out of the shared retry loop",
                )


# --------------------------------------------- shared lock model (R9/R10)
# The concurrency rules resolve lock expressions through their
# construction sites, so the static layer reads the SAME declarations the
# dynamic harness (utils/lockdep.py) instruments:
#
#   X = lockdep.make_lock("name", io_ok=True)     -> node "name", io_ok
#   self._lock = threading.Lock()                 -> node "<rel>:<attr>"
#   self._cond = threading.Condition(self._lock)  -> alias of self._lock
#
# ``io_ok=True`` is the blessed escape for locks whose PURPOSE is
# serializing blocking work (registry/journal/start flush, the model-cache
# compile lock, the device-probe wait) — R9 skips their critical sections;
# R10 still graphs them.

_CONC_SCOPE = ("runtime/", "ops/")
_LOCKISH_SUFFIXES = ("lock", "cond", "mutex")


def _lock_ctor_info(value: ast.expr) -> tuple[str | None, bool] | None:
    """(make_lock name or None, io_ok) when ``value`` constructs a lock;
    None when it does not."""
    if not isinstance(value, ast.Call):
        return None
    fname = _last_name(value.func)
    if fname in ("make_lock", "make_rlock"):
        name = None
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            name = value.args[0].value
        io_ok = any(
            k.arg == "io_ok" and isinstance(k.value, ast.Constant)
            and bool(k.value.value)
            for k in value.keywords
        )
        return name, io_ok
    if fname in ("Lock", "RLock"):
        return None, False
    return None


class _LockDecls:
    """Lock bindings of one module: (class-or-None, binding name) ->
    (node id, io_ok).  Condition(self._lock) aliases the wrapped lock.
    One recursive pass over the tree (the enclosing class travels down
    with the recursion — no per-class re-walk)."""

    def __init__(self, tree: ast.Module, rel: str):
        self.rel = rel
        self.map: dict[tuple[str | None, str], tuple[str, bool]] = {}
        self._collect(tree, None)

    def _bind(self, cls: str | None, name: str, value: ast.expr) -> None:
        info = _lock_ctor_info(value)
        if info is not None:
            node_id, io_ok = info
            self.map[(cls, name)] = (node_id or f"{self.rel}:{name}", io_ok)
            return
        # Condition over a declared lock: alias the lock's node
        if isinstance(value, ast.Call) \
                and _last_name(value.func) == "Condition" and value.args:
            tgt = value.args[0]
            key = None
            if isinstance(tgt, ast.Attribute) \
                    and _last_name(tgt.value) == "self":
                key = (cls, tgt.attr)
            elif isinstance(tgt, ast.Name):
                key = (cls, tgt.id) if (cls, tgt.id) in self.map \
                    else (None, tgt.id)
            if key in self.map:
                self.map[(cls, name)] = self.map[key]

    def _collect(self, scope: ast.AST, cls: str | None) -> None:
        for node in ast.iter_child_nodes(scope):
            if isinstance(node, ast.ClassDef):
                self._collect(node, node.name)
                continue
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if cls is not None and isinstance(tgt, ast.Attribute) \
                            and _last_name(tgt.value) == "self":
                        self._bind(cls, tgt.attr, node.value)
                    elif isinstance(tgt, ast.Name):
                        self._bind(cls, tgt.id, node.value)
            self._collect(node, cls)

    def resolve(self, expr: ast.expr,
                cls: str | None) -> tuple[str, bool] | None:
        """(node id, io_ok) for a with-item lock expression, or None when
        the expression is not lock-like.  Undeclared names that END in
        lock/cond still count (io_ok False) — an unregistered lock must
        not silently escape the rules."""
        if isinstance(expr, ast.Attribute) and _last_name(expr.value) == "self":
            hit = self.map.get((cls, expr.attr))
            if hit is not None:
                return hit
            name = expr.attr
        elif isinstance(expr, ast.Name):
            hit = self.map.get((cls, expr.id)) or self.map.get((None, expr.id))
            if hit is not None:
                return hit
            name = expr.id
        else:
            return None
        stripped = name.lstrip("_").lower()
        if stripped.endswith(_LOCKISH_SUFFIXES):
            return f"{self.rel}:{name}", False
        return None


def _decls_for(project: Project, rel: str, tree: ast.Module) -> _LockDecls:
    """Per-run memo of a file's lock declarations (R9 and R10 both need
    them; deriving once per file keeps the repo-wide analyze fast)."""
    key = ("lock-decls", rel)
    decls = project.cache.get(key)
    if decls is None:
        decls = project.cache[key] = _LockDecls(tree, rel)
    return decls


def _functions_with_class(tree: ast.Module
                          ) -> Iterator[tuple[str | None, ast.AST]]:
    """(enclosing class name or None, function node) for every function,
    carrying the nearest enclosing class through nested defs (closures in
    a method still see that method's ``self``)."""

    def rec(node: ast.AST, cls: str | None) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from rec(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child
                yield from rec(child, cls)
            else:
                yield from rec(child, cls)

    yield from rec(tree, None)


# ------------------------------------------------------------------- rule R9

# Blocking primitives by bare callable name (Name or trailing Attribute).
_BLOCKING_CALLS = {
    "open", "urlopen", "create_connection",
    # engine/journal/log construction: model compile, file open+fsync
    "GrepEngine", "TaskJournal", "EventLog", "WorkDir", "Popen",
}
# Attribute calls gated on the receiver (os.replace yes, str.replace no).
_BLOCKING_RECV_ATTRS = {
    "os": {"fsync", "replace", "rename", "unlink", "remove"},
    "time": {"sleep"},
    "subprocess": {"run", "call", "check_call", "check_output"},
    "jax": {"device_put", "block_until_ready", "devices", "local_devices"},
    "shutil": {"rmtree", "copyfile", "copy", "move"},
}
# Any method call on these receivers is filesystem/flush work: the
# journal/registry fsync per record, event logs flush per batch, stores
# and work dirs touch the work-dir filesystem.
_IO_RECEIVERS = {"journal", "event_log", "registry", "store", "workdir"}


def _blocking_call(node: ast.Call) -> str | None:
    """A short label when ``node`` is a blocking call, else None."""
    fn = node.func
    name = _last_name(fn)
    if name in _BLOCKING_CALLS:
        return f"{name}()"
    if isinstance(fn, ast.Attribute):
        recv = _last_name(fn.value).lstrip("_")
        # normalized receiver module aliases (time as _time / _time_mod)
        recv_mod = recv[:-len("_mod")] if recv.endswith("_mod") else recv
        for mod, attrs in _BLOCKING_RECV_ATTRS.items():
            if fn.attr in attrs and (recv == mod or recv_mod == mod):
                return f"{mod}.{fn.attr}()"
        if recv in _IO_RECEIVERS:
            return f"{recv}.{fn.attr}() [I/O object]"
    return None


def rule_locked_blocking(project: Project) -> Iterator[Violation]:
    """R9: no blocking work inside a lock's critical section on the
    control plane (runtime/, ops/) — no file opens/fsyncs, no sockets, no
    sleeps, no engine construction, no jax device calls, and no calls on
    the journal/event-log/registry/store/work-dir I/O objects, either
    lexically under ``with <lock>:`` or anywhere in a ``*_locked``-
    convention method (called with the lock already held).  The blessed
    escapes are the staged-flush pattern (stage under the lock, write
    after release) and locks DECLARED ``io_ok=True`` via lockdep.make_lock
    — locks whose purpose is serializing that I/O (registry/journal/start
    flush, the model-cache compile lock, the device-probe wait)."""
    for rel in project.files():
        if not rel.startswith(_CONC_SCOPE):
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        decls = _decls_for(project, rel, tree)
        for cls, fn in _functions_with_class(tree):
            base_held: list[tuple[str, bool]] = []
            if fn.name.endswith("_locked") or "_locked_" in fn.name:
                base_held.append((f"<{fn.name}: _locked convention>", False))

            def check(node: ast.Call, held) -> Iterator[Violation]:
                label = _blocking_call(node)
                if label is None:
                    return
                hot = [n for n, io_ok in held if not io_ok]
                if hot:
                    yield Violation(
                        "locked-blocking", rel, node.lineno,
                        f"blocking {label} inside the critical section of "
                        f"{hot[-1]} — stage the work under the lock and "
                        f"flush after release (or declare the lock "
                        f"io_ok=True if serializing this I/O is its "
                        f"purpose)",
                    )

            def scan(node: ast.AST, held) -> Iterator[Violation]:
                # nested defs/classes are their own scope: defining one
                # under a lock runs nothing (the outer loop visits it)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    return
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    cur = list(held)
                    for item in node.items:  # left-to-right acquisition
                        for c in ast.walk(item.context_expr):
                            if isinstance(c, ast.Call):
                                yield from check(c, cur)
                        r = decls.resolve(item.context_expr, cls)
                        if r is not None:
                            cur.append(r)
                    for child in node.body:
                        yield from scan(child, cur)
                    return
                if isinstance(node, ast.Call):
                    yield from check(node, held)
                for child in ast.iter_child_nodes(node):
                    yield from scan(child, held)

            for stmt in fn.body:
                yield from scan(stmt, base_held)


# ------------------------------------------------------------------ rule R10

def _module_of_import(node: ast.ImportFrom | ast.Import) -> dict[str, str]:
    """alias -> dotted module/name path for import statements."""
    out = {}
    if isinstance(node, ast.Import):
        for a in node.names:
            out[(a.asname or a.name.split(".")[0])] = a.name
    else:
        mod = node.module or ""
        for a in node.names:
            out[a.asname or a.name] = f"{mod}.{a.name}"
    return out


class _CallGraph:
    """Project-wide lock-acquisition summaries: which locks each function
    acquires (directly or transitively) and which locks are HELD at each
    call site — the inputs to R10's cycle search.

    Receiver typing is deliberately shallow but declaration-driven:
    ``self`` resolves to the enclosing class; attribute/variable receivers
    resolve through dataclass field annotations and ``self.x = Class()``
    assignments anywhere in the project; bare names resolve to same-module
    functions, ``from x import y`` targets, and project classes (their
    __init__).  Unresolvable calls contribute no edges — under-
    approximation here is covered by the dynamic lockdep harness."""

    def __init__(self, project: Project):
        self.project = project
        self.fns: dict[tuple, ast.AST] = {}  # (rel, cls, name) -> node
        self.decls: dict[str, _LockDecls] = {}
        self.classes: dict[str, list[str]] = {}  # class name -> [rel]
        self.attr_types: dict[str, set[str]] = {}  # attr -> class names
        self.imports: dict[str, dict[str, str]] = {}  # rel -> alias map
        self.mod_to_rel: dict[str, str] = {}
        for rel in project.files():
            tree = project.tree(rel)
            if tree is None:
                continue
            self.decls[rel] = _decls_for(project, rel, tree)
            mod = rel[:-3].replace("/", ".")
            self.mod_to_rel[mod] = rel
            self.mod_to_rel[f"distributed_grep_tpu.{mod}"] = rel
            imp: dict[str, str] = {}
            # one walk per file: imports, classes, attr types together
            for node in ast.walk(tree):
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    imp.update(_module_of_import(node))
                elif isinstance(node, ast.ClassDef):
                    self.classes.setdefault(node.name, []).append(rel)
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    for n in ast.walk(node.annotation):
                        if isinstance(n, ast.Name) and n.id[:1].isupper():
                            self.attr_types.setdefault(
                                node.target.id, set()).add(n.id)
                elif isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Attribute) \
                                and _last_name(tgt.value) == "self":
                            for c in ast.walk(node.value):
                                if isinstance(c, ast.Call):
                                    nm = _last_name(c.func)
                                    if nm[:1].isupper():
                                        self.attr_types.setdefault(
                                            tgt.attr, set()).add(nm)
            self.imports[rel] = imp
            for cls, fn in _functions_with_class(tree):
                self.fns[(rel, cls, fn.name)] = fn

    # ---------------------------------------------------------- resolution
    def _method_keys(self, class_name: str, meth: str) -> list[tuple]:
        out = []
        for rel in self.classes.get(class_name, ()):
            key = (rel, class_name, meth)
            if key in self.fns:
                out.append(key)
        return out

    def resolve_call(self, call: ast.Call, rel: str,
                     cls: str | None) -> list[tuple]:
        fn = call.func
        if isinstance(fn, ast.Name):
            name = fn.id
            if (rel, cls, name) in self.fns:
                return [(rel, cls, name)]
            if (rel, None, name) in self.fns:
                return [(rel, None, name)]
            if name in self.attr_types:  # local var named like a field
                return [k for c in self.attr_types[name]
                        for k in self._method_keys(c, "__init__")] or []
            if name in self.classes:
                return self._method_keys(name, "__init__")
            target = self.imports.get(rel, {}).get(name)
            if target:
                mod, _, leaf = target.rpartition(".")
                trel = self.mod_to_rel.get(mod)
                if trel and (trel, None, leaf) in self.fns:
                    return [(trel, None, leaf)]
                if trel and leaf in self.classes:
                    return self._method_keys(leaf, "__init__")
            return []
        if isinstance(fn, ast.Attribute):
            recv = _last_name(fn.value)
            meth = fn.attr
            if recv == "self" and cls is not None:
                keys = self._method_keys(cls, meth)
                return [k for k in keys if k[0] == rel] or keys
            out: list[tuple] = []
            for c in self.attr_types.get(recv, ()):
                out.extend(self._method_keys(c, meth))
            if not out and recv in self.classes:  # ClassName.static()
                out.extend(self._method_keys(recv, meth))
            if not out:
                target = self.imports.get(rel, {}).get(recv)
                if target:
                    trel = self.mod_to_rel.get(target)
                    if trel:
                        if (trel, None, meth) in self.fns:
                            out.append((trel, None, meth))
                        elif meth in self.classes:
                            out.extend(self._method_keys(meth, "__init__"))
            return out
        return []


def rule_lock_order(project: Project) -> Iterator[Violation]:
    """R10: the static lock-acquisition graph must be acyclic.  Nodes are
    declared locks (lockdep.make_lock names; raw Locks key by module:var);
    edges run held -> acquired, from nested ``with`` scopes and from calls
    made inside a critical section to functions that (transitively)
    acquire other locks — cross-module edges included, resolved through
    dataclass annotations and ``self.x = Class()`` sites (the service ->
    scheduler ``stop()`` edge, the flush locks' outer-to-inner contract).
    Any cycle is a potential deadlock and is reported once with the
    participating locks.  Same-lock call-path self-edges are skipped (the
    ``locked=True`` conditional-acquire helpers would false-positive);
    a LEXICAL ``with A: with A:`` still reports — that one is a certain
    deadlock on a non-reentrant Lock."""
    graph = _CallGraph(project)
    # direct acquires per function
    direct: dict[tuple, set[str]] = {}
    calls: dict[tuple, list] = {}
    edges: dict[tuple[str, str], tuple[str, int]] = {}  # (a,b) -> site

    for (rel, cls, name), fn in graph.fns.items():
        decls = graph.decls[rel]
        acq: set[str] = set()
        fncalls: list = []

        def walk(node: ast.AST, held: tuple) -> None:
            # nested defs/classes are their own scope: defining one under
            # a lock runs nothing (they get their own summary)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cur = held
                for item in node.items:  # left-to-right acquisition
                    for c in ast.walk(item.context_expr):
                        if isinstance(c, ast.Call):
                            fncalls.append((cur, c, c.lineno))
                    r = decls.resolve(item.context_expr, cls)
                    if r is not None:
                        g = r[0]
                        acq.add(g)
                        for h in cur:
                            edges.setdefault((h, g), (rel, node.lineno))
                        cur = cur + (g,)
                for child in node.body:
                    walk(child, cur)
                return
            if isinstance(node, ast.Call):
                fncalls.append((held, node, node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, ())
        direct[(rel, cls, name)] = acq
        calls[(rel, cls, name)] = fncalls

    # transitive acquires: fixpoint over the (shallow) call graph
    trans: dict[tuple, set[str]] = {k: set(v) for k, v in direct.items()}
    resolved: dict[tuple, list[list[tuple]]] = {}
    for key, fncalls in calls.items():
        rel, cls, _ = key
        resolved[key] = [graph.resolve_call(c, rel, cls)
                         for _, c, _ in fncalls]
    changed = True
    while changed:
        changed = False
        for key, callee_lists in resolved.items():
            cur = trans[key]
            before = len(cur)
            for callees in callee_lists:
                for ck in callees:
                    cur |= trans.get(ck, set())
            if len(cur) != before:
                changed = True

    # call edges: held locks -> everything the callee may acquire
    for key, fncalls in calls.items():
        for (held, call, line), callees in zip(fncalls, resolved[key]):
            if not held:
                continue
            for ck in callees:
                for lk in trans.get(ck, ()):
                    for h in held:
                        if h != lk:  # call-path self-edges: see docstring
                            edges.setdefault((h, lk), (key[0], line))

    # cycle detection over the edge graph (iterative DFS per node)
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    reported: set[frozenset] = set()
    for (a, b), (rel, line) in sorted(edges.items(),
                                      key=lambda kv: (kv[1][0], kv[1][1])):
        if a == b:
            yield Violation(
                "lock-order", rel, line,
                f"lock {a!r} re-acquired while already held — certain "
                f"deadlock on a non-reentrant Lock",
            )
            continue
        # path b ->* a closes a cycle through edge (a, b); keep the path
        # so one N-lock cycle dedups to ONE report (keying on just the
        # closing edge would report a 3-cycle three times, once per edge)
        stack, seen = [(b, (b,))], {b}
        found: tuple | None = None
        while stack and found is None:
            n, path = stack.pop()
            for m in adj.get(n, ()):
                if m == a:
                    found = path
                    break
                if m not in seen:
                    seen.add(m)
                    stack.append((m, path + (m,)))
        if found is not None:
            cyc = frozenset(found) | {a}
            if cyc in reported:
                continue
            reported.add(cyc)
            chain = " -> ".join(found + (a,))
            yield Violation(
                "lock-order", rel, line,
                f"lock-order cycle: {a!r} -> {b!r} here, but a path "
                f"{chain} exists elsewhere — two threads taking the two "
                f"routes deadlock",
            )


# ------------------------------------------------------------------ rule R11

def _touches_pallas(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias, target in _module_of_import(node).items():
                if "pallas" in target or "pallas" in alias:
                    return True
        elif isinstance(node, ast.Call) \
                and _last_name(node.func) == "pallas_call":
            return True
    return False


def rule_shard_map_rep(project: Project) -> Iterator[Violation]:
    """R11: every ``shard_map`` in a pallas-touching module must pass
    ``check_rep=False`` — pallas_call's out_shape carries no varying-mesh-
    axes annotation, so shard_map's replication checker cannot see through
    it and rejects the (correct) kernel at trace time; correctness is
    pinned by the bit-identical vs-single-device tests instead
    (test_parallel.py).  Module granularity is the deliberate
    over-approximation: the kernel body usually arrives through a
    parameter the AST cannot trace, and check_rep=False on a non-pallas
    body in such a module costs only the checker's (unusable) coverage."""
    for rel in project.files():
        tree = project.tree(rel)
        if tree is None:
            continue
        pallas = None  # lazy: most files have no shard_map at all
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _last_name(node.func) == "shard_map"):
                continue
            if pallas is None:
                pallas = _touches_pallas(tree)
            if not pallas:
                continue
            explicit_false = any(
                k.arg == "check_rep" and isinstance(k.value, ast.Constant)
                and k.value.value is False
                for k in node.keywords
            )
            if not explicit_false:
                yield Violation(
                    "shard-map-rep", rel, node.lineno,
                    "shard_map in a pallas-touching module without "
                    "check_rep=False: the replication checker cannot see "
                    "through pallas_call out_shapes and rejects the "
                    "kernel at trace time (CLAUDE.md round-4 invariant, "
                    "pinned by test_parallel.py)",
                )


# ------------------------------------------------------------------ rule R12

# Instrument factory callables (utils/metrics.py): module-level
# counter()/gauge()/histogram() and the MetricsRegistry methods share
# these names; a first argument that is a "dgrep_"-prefixed string
# constant marks the call as a series creation.
_METRIC_FACTORIES = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}
_SERIES_PREFIX = "dgrep_"


def rule_metrics_registry(project: Project) -> Iterator[Violation]:
    """R12: every exported metrics series name is declared once in
    ``utils/metrics.SERIES`` (the env-knobs registry pattern — the table
    doubles as the /metrics HELP text).  A ``counter()``/``gauge()``/
    ``histogram()`` creation whose name is undeclared is unowned and
    undocumented; a creation whose kind disagrees with the declaration
    would render the series under the wrong Prometheus TYPE; a declared
    name no call site creates is a stale registry entry (checked only
    when the project carries utils/metrics.py — fixture mini-trees stay
    silent, like the env-knobs stale check)."""
    from distributed_grep_tpu.utils.metrics import SERIES

    seen: dict[str, list[tuple[str, int, str]]] = {}
    for rel in project.files():
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            kind = _METRIC_FACTORIES.get(_last_name(node.func))
            if kind is None:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith(_SERIES_PREFIX)):
                continue
            seen.setdefault(arg.value, []).append((rel, node.lineno, kind))
    for name in sorted(seen):
        decl = SERIES.get(name)
        for rel, line, kind in seen[name]:
            if decl is None:
                yield Violation(
                    "metrics-registry", rel, line,
                    f"undeclared metrics series {name}: add it (kind, "
                    f"help) to utils/metrics.py SERIES — the registry is "
                    f"the /metrics HELP text and the one place a series "
                    f"name is owned",
                )
            elif decl[0] != kind:
                yield Violation(
                    "metrics-registry", rel, line,
                    f"{name} created as a {kind} but declared "
                    f"{decl[0]} in utils/metrics.py SERIES — the series "
                    f"would render under the wrong Prometheus TYPE",
                )
    if (project.root / "utils/metrics.py").exists():
        for name in SERIES:
            if name not in seen:
                yield Violation(
                    "metrics-registry", "utils/metrics.py", 1,
                    f"declared metrics series {name} is never created by "
                    f"any counter()/gauge()/histogram() call site: stale "
                    f"registry entry in utils/metrics.py SERIES",
                )


# ------------------------------------------------------------------ rule R13

# Consumer modules that string-match event names (explain views, fleet
# trace export, daemon-log readers, `dgrep top`): every literal compare on
# a variable named `name`/`kind` there must hit a declared event.
_EVENT_CONSUMER_FILES = ("runtime/explain.py", "utils/spans.py",
                         "runtime/daemon_log.py", "__main__.py")
# Emitter callables: span-pipeline entry points plus the daemon-event
# helpers (service._daemon_event, DaemonLog.append_now, the scheduler's
# daemon_events hook, WorkerHealth._emit).
_SPAN_EMITTERS = {"instant": "instant", "span": "span", "complete": "span"}
_DAEMON_EMITTERS = {"_daemon_event", "append_now", "daemon_events", "_emit"}


def _event_name_shapes(expr: ast.expr):
    """Resolve an emit-site name expression to concrete names and family
    patterns (``*`` marks a computed f-string segment).  None = not
    statically resolvable (a bare-Name pass-through helper parameter) —
    silently skipped, the metrics-registry convention; the
    utils/event_audit.py dynamic recorder covers those at runtime."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.IfExp):
        body = _event_name_shapes(expr.body)
        orelse = _event_name_shapes(expr.orelse)
        if body is not None and orelse is not None:
            return body + orelse
        return None
    if isinstance(expr, ast.JoinedStr):
        parts = []
        for v in expr.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        pat = "".join(parts)
        if pat.count("*") == 1:
            return [pat]
    return None


def rule_event_registry(project: Project) -> Iterator[Violation]:
    """R13: every exported telemetry event name — span/instant names and
    DaemonLog kinds — is declared once in ``analysis/events.py EVENTS``
    (the knobs/metrics registry pattern; the table is also the operator
    docs via ``analyze --events``).  Emit sites (``instant()``/``span()``/
    ``complete()`` calls, daemon-event helpers, and raw ``{"t": "instant"|
    "span", "name": ...}`` dict literals) must use string constants or
    declared-family f-strings; a consumer-side compare in explain / trace
    export / daemon-log readers matching an undeclared name is a one-sided
    rename that turns the view into a lie; a declared name with no
    surviving emit site is stale (checked only when the project carries
    utils/spans.py — fixture mini-trees stay silent)."""
    from distributed_grep_tpu.analysis.events import (
        EVENTS, is_family, lookup)

    seen_keys: set[str] = set()

    def check_site(rel, line, kind, name_expr, cat):
        shapes = _event_name_shapes(name_expr)
        if shapes is None:
            return
        for shape in shapes:
            if "*" in shape:
                ev = EVENTS.get(shape)
                if ev is None or not is_family(shape):
                    yield Violation(
                        "event-registry", rel, line,
                        f"undeclared event family {shape!r}: a computed "
                        f"emit name must match an enumerated family "
                        f"declared in analysis/events.py EVENTS",
                    )
                    continue
                key = shape
            else:
                hit = lookup(shape)
                if hit is None:
                    yield Violation(
                        "event-registry", rel, line,
                        f"undeclared event name {shape!r}: add it (kind, "
                        f"cat, owner) to analysis/events.py EVENTS — the "
                        f"registry is the telemetry vocabulary and the one "
                        f"place an event name is owned",
                    )
                    continue
                key, ev = hit
            seen_keys.add(key)
            if kind not in ev.kinds:
                yield Violation(
                    "event-registry", rel, line,
                    f"{shape!r} emitted as a {kind} but declared "
                    f"{'/'.join(ev.kinds)} in analysis/events.py EVENTS",
                )
            if cat is not None and ev.cat and cat != ev.cat:
                yield Violation(
                    "event-registry", rel, line,
                    f"{shape!r} emitted with cat {cat!r} but declared cat "
                    f"{ev.cat!r} in analysis/events.py EVENTS — consumers "
                    f"and trace rows bucket by cat",
                )

    for rel in project.files():
        if rel.startswith("analysis/"):
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Dict):
                keys = {k.value: v for k, v in zip(node.keys, node.values)
                        if isinstance(k, ast.Constant)}
                t = keys.get("t")
                if not (isinstance(t, ast.Constant)
                        and t.value in ("span", "instant")):
                    continue
                name_expr = keys.get("name")
                if name_expr is None:
                    continue
                cat_expr = keys.get("cat")
                cat = (cat_expr.value
                       if isinstance(cat_expr, ast.Constant)
                       and isinstance(cat_expr.value, str) else None)
                yield from check_site(rel, node.lineno, t.value,
                                      name_expr, cat)
            elif isinstance(node, ast.Call) and node.args:
                fname = _last_name(node.func)
                kind = cat = None
                if fname in _SPAN_EMITTERS:
                    kind = _SPAN_EMITTERS[fname]
                    for k in node.keywords:
                        if (k.arg == "cat"
                                and isinstance(k.value, ast.Constant)
                                and isinstance(k.value.value, str)):
                            cat = k.value.value
                elif fname == "_event":
                    kind = "instant"
                elif fname in _DAEMON_EMITTERS:
                    kind = "daemon"
                elif fname == "stage" and isinstance(node.func,
                                                    ast.Attribute):
                    recv = _last_name(node.func.value) or ""
                    if "daemon" in recv or recv == "dl":
                        kind = "daemon"
                if kind is None:
                    continue
                yield from check_site(rel, node.lineno, kind,
                                      node.args[0], cat)

    for rel in _EVENT_CONSUMER_FILES:
        tree = project.tree(rel)
        if tree is None:
            continue
        module_dicts: dict[str, tuple[list[str], int]] = {}
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Dict)
                    and node.value.keys
                    and all(isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            for k in node.value.keys)):
                module_dicts[node.targets[0].id] = (
                    [k.value for k in node.value.keys], node.lineno)
        getted: set[str] = set()
        matched: list[tuple[str, int]] = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Compare)
                    and isinstance(node.left, ast.Name)
                    and node.left.id in ("name", "kind")
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.Eq, ast.In))):
                comp = node.comparators[0]
                if (isinstance(comp, ast.Constant)
                        and isinstance(comp.value, str)):
                    matched.append((comp.value, node.lineno))
                elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    matched.extend(
                        (e.value, node.lineno) for e in comp.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.args
                    and isinstance(node.func.value, ast.Name)):
                if (node.func.attr == "startswith"
                        and node.func.value.id in ("name", "kind")
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and node.args[0].value.endswith(":")):
                    pat = node.args[0].value + "*"
                    if not (pat in EVENTS and is_family(pat)):
                        yield Violation(
                            "event-registry", rel, node.lineno,
                            f"consumer matches undeclared event family "
                            f"{pat!r}: no declared family produces these "
                            f"names (analysis/events.py EVENTS)",
                        )
                elif (node.func.attr == "get"
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in ("name", "kind")):
                    getted.add(node.func.value.id)
        for dname in sorted(getted & set(module_dicts)):
            keys, line = module_dicts[dname]
            matched.extend((k, line) for k in keys)
        for value, line in matched:
            if value and lookup(value) is None:
                yield Violation(
                    "event-registry", rel, line,
                    f"consumer matches undeclared event name {value!r}: "
                    f"no emitter produces it (analysis/events.py EVENTS) — "
                    f"a one-sided rename turns this view into a lie",
                )

    if (project.root / "utils/spans.py").exists():
        for key in EVENTS:
            if key not in seen_keys:
                yield Violation(
                    "event-registry", "analysis/events.py", 1,
                    f"declared event {key!r} has no surviving emit site: "
                    f"stale registry entry in analysis/events.py EVENTS",
                )


# ------------------------------------------------------------------ registry

RULES: dict[str, Callable[[Project], Iterator[Violation]]] = {
    "posix-expand": rule_posix_expand,
    "store-resolve": rule_store_resolve,
    "surrogateescape": rule_surrogateescape,
    "env-knobs": rule_env_knobs,
    "rpc-elide": rule_rpc_elide,
    "mosaic-ceilings": rule_mosaic_ceilings,
    "logging": rule_logging,
    "net-retry": rule_net_retry,
    "locked-blocking": rule_locked_blocking,
    "lock-order": rule_lock_order,
    "shard-map-rep": rule_shard_map_rep,
    "metrics-registry": rule_metrics_registry,
    "event-registry": rule_event_registry,
}

RULE_DOCS: dict[str, str] = {
    name: (fn.__doc__ or "").strip().splitlines()[0].rstrip(".")
    for name, fn in RULES.items()
}

"""The invariant rules: each encodes one correctness contract this repo
previously documented only as CLAUDE.md prose (or enforced as a grep test).

Every rule walks real ASTs (no regex-over-source false positives from
strings or comments) and reports ``Violation(rule, path, line, message)``
records.  Rules are registered in ``RULES``; the checker (checker.py) runs
them over a project root — the installed package by default, a fixture
mini-tree in tests/test_analysis.py, which pins each rule against both
false negatives (fires on a known-bad snippet) and false positives (stays
silent on this repo).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable, Iterator


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str  # project-root-relative, posix separators
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Project:
    """Parsed view of a source tree; trees are parsed once and shared."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self._cache: dict[str, ast.Module | None] = {}
        self._files: list[str] | None = None

    def files(self) -> list[str]:
        if self._files is None:
            self._files = sorted(
                p.relative_to(self.root).as_posix()
                for p in self.root.rglob("*.py")
                if "__pycache__" not in p.parts
            )
        return self._files

    def tree(self, rel: str) -> ast.Module | None:
        if rel not in self._cache:
            try:
                src = (self.root / rel).read_text(encoding="utf-8",
                                                  errors="surrogateescape")
                self._cache[rel] = ast.parse(src)
            except (OSError, SyntaxError, ValueError):
                # ValueError: ast.parse raises UnicodeEncodeError on
                # surrogateescape-decoded non-UTF-8 source — skip the
                # file like a SyntaxError, don't abort the whole run
                self._cache[rel] = None
        return self._cache[rel]


# --------------------------------------------------------------- AST helpers

def _last_name(node: ast.AST) -> str:
    """Trailing identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted form of a Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _str_consts(node: ast.AST) -> Iterator[str]:
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            yield n.value
        elif isinstance(n, ast.Constant) and isinstance(n.value, bytes):
            yield n.value.decode("latin-1")


def _scope_assignments(scope: ast.AST) -> dict[str, list[ast.expr]]:
    """name -> assigned value expressions, within one function/module scope
    (nested function bodies are NOT descended — they are their own scope)."""
    out: dict[str, list[ast.expr]] = {}

    def visit(body: Iterable[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Assign) and stmt.value is not None:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.setdefault(tgt.id, []).append(stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    out.setdefault(stmt.target.id, []).append(stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                if isinstance(stmt.target, ast.Name):
                    out.setdefault(stmt.target.id, []).append(stmt.value)
            # descend statement bodies that stay in this scope
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    visit(sub)
            for h in getattr(stmt, "handlers", []) or []:
                visit(h.body)

    visit(scope.body)  # type: ignore[attr-defined]
    return out


def _enclosing_scopes(tree: ast.Module) -> list[tuple[ast.AST, list[ast.AST]]]:
    """[(scope_node, [calls and other nodes directly in that scope])] for
    the module and every (possibly nested) function."""
    scopes: list[tuple[ast.AST, list[ast.AST]]] = []

    def collect(scope: ast.AST) -> None:
        nodes: list[ast.AST] = []
        stack = list(getattr(scope, "body", []))
        funcs: list[ast.AST] = []
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.append(n)
                continue
            nodes.append(n)
            stack.extend(ast.iter_child_nodes(n))
        scopes.append((scope, nodes))
        for f in funcs:
            collect(f)

    collect(tree)
    return scopes


# ------------------------------------------------------------------- rule R1

_RE_PATTERN_FUNCS = {"compile", "search", "match", "fullmatch", "finditer",
                     "findall", "sub", "subn", "split"}
_SANITIZERS = {"expand_posix_classes", "escape"}


def _re_aliases(tree: ast.Module) -> set[str]:
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "re":
                    names.add(a.asname or "re")
    return names


def _expr_sanitized(expr: ast.expr, env: dict[str, list[ast.expr]],
                    visited: set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and _last_name(node.func) in _SANITIZERS:
            return True
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id not in visited:
            visited.add(node.id)
            for v in env.get(node.id, ()):
                if _expr_sanitized(v, env, visited):
                    return True
    return False


def _resolves_to_literal(expr: ast.expr, env: dict[str, list[ast.expr]],
                         visited: set[str] | None = None) -> bool:
    """True when the pattern is built from constants alone — an
    app-internal literal the author wrote, not a user pattern.  Names
    resolve through the scope's assignments, so a hoisted module constant
    (``_WORD = rb"[A-Za-z]+"`` ... ``re.findall(_WORD, ...)``) stays
    exempt; any Call/Attribute, or a name with no all-literal assignment,
    makes it computed."""
    if visited is None:
        visited = set()
    if any(isinstance(n, (ast.Call, ast.Attribute)) for n in ast.walk(expr)):
        return False
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            if node.id in visited:
                continue
            visited.add(node.id)
            vals = env.get(node.id)
            if not vals or not all(
                    _resolves_to_literal(v, env, visited) for v in vals):
                return False
    return True


def rule_posix_expand(project: Project) -> Iterator[Violation]:
    """R1: every ``re`` handoff of a non-literal pattern must route through
    ``models/dfa.expand_posix_classes`` (or ``re.escape`` for literals).
    Python's re misparses POSIX bracket classes ('[[:digit:]]' matches
    ':digit' members), so an unexpanded handoff silently changes the
    language the confirm/fallback matcher accepts."""
    for rel in project.files():
        tree = project.tree(rel)
        if tree is None:
            continue
        aliases = _re_aliases(tree)
        if not aliases:
            continue
        module_env = _scope_assignments(tree)
        for scope, nodes in _enclosing_scopes(tree):
            env = dict(module_env)
            if scope is not tree:
                env.update(_scope_assignments(scope))
            for node in nodes:
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _RE_PATTERN_FUNCS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in aliases
                        and node.args):
                    continue
                pat = node.args[0]
                if _resolves_to_literal(pat, env):
                    continue
                if _expr_sanitized(pat, env, set()):
                    continue
                yield Violation(
                    "posix-expand", rel, node.lineno,
                    f"re.{node.func.attr} on a computed pattern with no "
                    f"expand_posix_classes/re.escape on any path to it — "
                    f"POSIX bracket classes would be misparsed by re",
                )


# ------------------------------------------------------------------- rule R2

_RAW_READERS = {"glob", "iglob", "rglob", "listdir", "scandir", "iterdir"}


def rule_store_resolve(project: Project) -> Iterator[Violation]:
    """R2: no raw ``glob``/``listdir``/``open`` over work-dir ``mr-*``
    artifacts outside runtime/store.py.  On non-atomic stores, commit
    RECORDS are the unit of truth — a raw directory scan sees torn
    ``.part.*`` files and duplicate attempts; readers must resolve through
    ``WorkDir.list_outputs`` / ``store.get``."""
    for rel in project.files():
        if rel == "runtime/store.py":
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_reader = (
                (isinstance(fn, ast.Name) and fn.id == "open")
                or (isinstance(fn, ast.Attribute) and fn.attr in _RAW_READERS)
            )
            if not is_reader:
                continue
            hit = next(
                (s for a in list(node.args)
                 + [k.value for k in node.keywords]
                 for s in _str_consts(a) if "mr-" in s),
                None,
            )
            if hit is not None:
                name = _last_name(fn) or "open"
                yield Violation(
                    "store-resolve", rel, node.lineno,
                    f"raw {name}() over {hit!r}: mr-* artifacts must "
                    f"resolve through the work dir's Store "
                    f"(WorkDir.list_outputs / store.get) — commit records, "
                    f"not file existence, are the unit of truth",
                )


# ------------------------------------------------------------------- rule R3

_R3_SCOPE = ("runtime/", "apps/")
_R3_FILES = ("__main__.py",)
_UTF8 = {None, "utf-8", "utf8", "UTF-8", "UTF8"}


def rule_surrogateescape(project: Project) -> Iterator[Violation]:
    """R3: str<->bytes conversions on the data plane (runtime/, apps/, the
    CLI) must state an ``errors=`` policy.  Pattern and path bytes
    round-trip via surrogateescape everywhere (display decodes use
    'replace' deliberately); a bare .encode()/.decode() is a latent
    UnicodeError on the first non-UTF-8 filename or pattern byte.
    json.dumps(...).encode(...) is exempt (ASCII by construction), as are
    non-UTF-8 codecs (declared fixed-alphabet data, e.g. ascii hex)."""
    for rel in project.files():
        if not (rel.startswith(_R3_SCOPE) or rel in _R3_FILES):
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("encode", "decode")):
                continue
            encoding = None
            if node.args and isinstance(node.args[0], ast.Constant):
                encoding = node.args[0].value
            for k in node.keywords:
                if k.arg == "encoding" and isinstance(k.value, ast.Constant):
                    encoding = k.value.value
            if node.args and not isinstance(node.args[0], ast.Constant):
                encoding = "<dynamic>"  # can't prove it's utf-8: still flag
            if isinstance(encoding, str) and encoding not in _UTF8 \
                    and encoding != "<dynamic>":
                continue  # ascii/latin-1 etc: fixed-alphabet by declaration
            has_errors = len(node.args) >= 2 or any(
                k.arg == "errors" for k in node.keywords)
            if has_errors:
                continue
            recv = node.func.value
            if isinstance(recv, ast.Call) and _last_name(recv.func) == "dumps":
                continue  # json.dumps output is ASCII by construction
            yield Violation(
                "surrogateescape", rel, node.lineno,
                f".{node.func.attr}() without an errors= policy on a "
                f"data-plane path — pattern/path bytes round-trip via "
                f"surrogateescape (display output uses 'replace')",
            )


# ------------------------------------------------------------------- rule R4

def _env_reads(tree: ast.Module) -> Iterator[tuple[str, int]]:
    """(var, line) for each environment READ with a resolvable key.
    Key constants resolve through EVERY scope's assignments (module
    ``_ENV_VAR = ...`` indirection and function-local names alike); a
    name assigned several string constants yields each — over-reporting
    beats a knob read hidden behind a local variable."""
    consts: dict[str, set[str]] = {}
    for scope, _ in _enclosing_scopes(tree):
        for name, exprs in _scope_assignments(scope).items():
            for e in exprs:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    consts.setdefault(name, set()).add(e.value)

    def resolve(arg: ast.expr) -> set[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return {arg.value}
        if isinstance(arg, ast.Name):
            return consts.get(arg.id, set())
        return set()

    for node in ast.walk(tree):
        keys: set[str] = set()
        if isinstance(node, ast.Call) and node.args:
            dn = _dotted(node.func)
            if dn.endswith("environ.get") or _last_name(node.func) == "getenv":
                keys = resolve(node.args[0])
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.ctx, ast.Load)
              and _last_name(node.value) == "environ"):
            keys = resolve(node.slice)
        for var in sorted(keys):
            yield var, node.lineno


def rule_env_knobs(project: Project) -> Iterator[Violation]:
    """R4: each DGREP_* env knob is read by exactly one owner module — the
    one registered in analysis/knobs.py (which doubles as the generated
    operator docs).  Two parsers of one knob can disagree on a malformed
    override (the DGREP_BATCH_BYTES failure mode env_batch_bytes guards);
    an unregistered knob is undocumented and unowned."""
    from distributed_grep_tpu.analysis.knobs import KNOBS

    seen: dict[str, list[tuple[str, int]]] = {}
    for rel in project.files():
        tree = project.tree(rel)
        if tree is None:
            continue
        for var, line in _env_reads(tree):
            if var.startswith("DGREP_"):
                seen.setdefault(var, []).append((rel, line))
    for var in sorted(seen):
        knob = KNOBS.get(var)
        for rel, line in seen[var]:
            if knob is None:
                yield Violation(
                    "env-knobs", rel, line,
                    f"unregistered env knob {var}: add it (owner, default, "
                    f"doc) to analysis/knobs.py KNOBS",
                )
            elif rel != knob.owner:
                yield Violation(
                    "env-knobs", rel, line,
                    f"{var} read outside its owner module {knob.owner} — "
                    f"import the owner's accessor instead of re-parsing "
                    f"the env var",
                )
    # stale registry entries: the owner module exists but never reads the
    # knob (fixture mini-trees without the owner file stay silent)
    for var, knob in KNOBS.items():
        if var in seen:
            continue
        if (project.root / knob.owner).exists():
            yield Violation(
                "env-knobs", knob.owner, 1,
                f"registered env knob {var} is never read by its owner "
                f"{knob.owner}: stale registry entry in analysis/knobs.py",
            )


# ------------------------------------------------------------------- rule R5

def _is_dataclass(node: ast.ClassDef) -> bool:
    return any(_last_name(d if not isinstance(d, ast.Call) else d.func)
               == "dataclass" for d in node.decorator_list)


def _field_default(expr: ast.expr | None):
    """(known, value): the field's declared default, when statically
    derivable.  field(default_factory=list/dict) -> []/{}."""
    if expr is None:
        return False, None
    if isinstance(expr, ast.Constant):
        return True, expr.value
    if isinstance(expr, ast.Call) and _last_name(expr.func) == "field":
        for k in expr.keywords:
            if k.arg == "default_factory":
                factory = _last_name(k.value)
                if factory == "list":
                    return True, []
                if factory == "dict":
                    return True, {}
            if k.arg == "default" and isinstance(k.value, ast.Constant):
                return True, k.value.value
    return False, None


def _is_optional_ann(ann: ast.expr | None) -> bool:
    if ann is None:
        return False
    for n in ast.walk(ann):
        if isinstance(n, ast.Constant) and n.value is None:
            return True
        if isinstance(n, ast.Name) and n.id == "Optional":
            return True
        # annotations arrive as strings under `from __future__ import
        # annotations`-style quoting: "dict | None"
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and "None" in n.value:
            return True
    return False


def rule_rpc_elide(project: Project, rel: str = "runtime/rpc.py"
                   ) -> Iterator[Violation]:
    """R5: wire-compat reflection over the RPC schema.  Every
    Optional-default field on the rpc dataclasses must appear in
    ``_ELIDE_DEFAULTS`` (else a span-disabled run's payloads grow keys old
    peers choke on), every elide key must exist as a field, and the
    registered elide default must EQUAL the field's declared default on
    every dataclass carrying it (drift silently un-elides the field)."""
    tree = project.tree(rel)
    if tree is None:
        return
    elide: dict[str, object] | None = None
    elide_line = 1
    for node in ast.walk(tree):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if (targets
                and any(isinstance(t, ast.Name) and t.id == "_ELIDE_DEFAULTS"
                        for t in targets)
                and isinstance(node.value, ast.Dict)):
            elide, elide_line = {}, node.lineno
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    try:
                        elide[k.value] = ast.literal_eval(v)
                    except ValueError:
                        elide[k.value] = _field_default(v)[1]
    if elide is None:
        yield Violation("rpc-elide", rel, 1,
                        "no _ELIDE_DEFAULTS dict literal found")
        return
    field_names: set[str] = set()
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and _is_dataclass(cls)):
            continue
        for stmt in cls.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            name = stmt.target.id
            field_names.add(name)
            known, default = _field_default(stmt.value)
            if (_is_optional_ann(stmt.annotation) and stmt.value is not None
                    and name not in elide):
                yield Violation(
                    "rpc-elide", rel, stmt.lineno,
                    f"Optional-default field {cls.name}.{name} missing from "
                    f"_ELIDE_DEFAULTS: span-disabled payloads would grow a "
                    f"key old peers reject",
                )
            if name in elide and known and elide[name] != default:
                yield Violation(
                    "rpc-elide", rel, stmt.lineno,
                    f"_ELIDE_DEFAULTS[{name!r}] == {elide[name]!r} but "
                    f"{cls.name}.{name} defaults to {default!r}: elision "
                    f"would silently stop matching the wire default",
                )
    for key in sorted(set(elide) - field_names):
        yield Violation(
            "rpc-elide", rel, elide_line,
            f"_ELIDE_DEFAULTS key {key!r} is not a field on any rpc "
            f"dataclass: dead elision entry",
        )


# ------------------------------------------------------------------- rule R6

_NARROW = {"int8", "uint8", "int16", "uint16"}
_PROBED_GATHER_CEILING = 64  # benchmarks/probe_gather_ceiling.py, 2026-08-01
_PROBED_DOMAINS = {128, 256, 512, 1024}
_PROBED_UNROLLS = {1, 2, 4, 8, 16, 32}  # divisors of 32 (pallas_scan gate)


def _narrow_cast_in(expr: ast.expr) -> str | None:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            if (isinstance(n.func, ast.Attribute) and n.func.attr == "astype"
                    and n.args and _last_name(n.args[0]) in _NARROW):
                return _last_name(n.args[0])
            if _last_name(n.func) in _NARROW:
                return _last_name(n.func)
    return None


def _return_value_consts(fn: ast.FunctionDef) -> Iterator[tuple[int, int]]:
    """(value, line) for int constants a return statement can evaluate to
    (IfExp arms flattened; condition subtrees are NOT scanned)."""
    def arms(e: ast.expr) -> Iterator[ast.expr]:
        if isinstance(e, ast.IfExp):
            yield from arms(e.body)
            yield from arms(e.orelse)
        else:
            yield e

    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            for arm in arms(node.value):
                if isinstance(arm, ast.Constant) and isinstance(arm.value,
                                                                int):
                    yield arm.value, arm.lineno


def rule_mosaic_ceilings(project: Project) -> Iterator[Violation]:
    """R6: the Mosaic compile ceilings measured on real v5e hardware
    (BASELINE.md rounds 4-5), checked statically instead of discovered as
    kernel compile crashes: no u8/i8/i16 vector compares in Pallas kernel
    bodies ('Target does not support this comparison'), gather plans
    bounded by the probed MAX_GATHERS=64 ceiling, unroll factors within
    the probed divisor-of-32 set, FDR domains within the probed set."""
    pallas = [f for f in project.files()
              if f.startswith("ops/pallas_") and f.endswith(".py")]
    for rel in pallas:
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Compare):
                for operand in [node.left, *node.comparators]:
                    dt = _narrow_cast_in(operand)
                    if dt:
                        yield Violation(
                            "mosaic-ceilings", rel, node.lineno,
                            f"{dt} vector compare in a Pallas kernel file: "
                            f"Mosaic rejects sub-i32 vector cmpi on this "
                            f"target (probed round 4, probe_narrow.py) — "
                            f"widen to i32 first",
                        )
                        break
            if isinstance(node, ast.Call):
                for k in node.keywords:
                    if (k.arg == "unroll"
                            and isinstance(k.value, ast.Constant)
                            and isinstance(k.value.value, int)
                            and k.value.value not in _PROBED_UNROLLS):
                        yield Violation(
                            "mosaic-ceilings", rel, node.lineno,
                            f"unroll={k.value.value} outside the probed set "
                            f"{sorted(_PROBED_UNROLLS)}",
                        )
            if isinstance(node, ast.FunctionDef) and node.name == "unroll_for":
                for val, line in _return_value_consts(node):
                    if val not in _PROBED_UNROLLS:
                        yield Violation(
                            "mosaic-ceilings", rel, line,
                            f"unroll_for returns {val}, outside the probed "
                            f"set {sorted(_PROBED_UNROLLS)}",
                        )
    fdr = project.tree("models/fdr.py")
    if fdr is not None:
        for name, exprs in _scope_assignments(fdr).items():
            for e in exprs:
                if name == "MAX_GATHERS" and isinstance(e, ast.Constant):
                    if e.value > _PROBED_GATHER_CEILING:
                        yield Violation(
                            "mosaic-ceilings", "models/fdr.py", e.lineno,
                            f"MAX_GATHERS={e.value} exceeds the probed "
                            f"compile ceiling {_PROBED_GATHER_CEILING} "
                            f"(probe_gather_ceiling.py) — re-probe on a "
                            f"real chip before raising",
                        )
                if name == "DOMAINS" and isinstance(e, (ast.Tuple, ast.List)):
                    for el in e.elts:
                        if (isinstance(el, ast.Constant)
                                and isinstance(el.value, int)
                                and el.value not in _PROBED_DOMAINS):
                            yield Violation(
                                "mosaic-ceilings", "models/fdr.py", el.lineno,
                                f"DOMAINS entry {el.value} outside the "
                                f"probed power-of-two set "
                                f"{sorted(_PROBED_DOMAINS)}",
                            )


# ------------------------------------------------------------------- rule R7

_LOG_ROOTS = ("runtime/", "utils/", "parallel/")
_LOG_EXEMPT = "utils/logging.py"


def rule_logging(project: Project) -> Iterator[Violation]:
    """R7: control-plane modules (runtime/, utils/, parallel/) log via
    utils.logging.get_logger only — no bare print() (stdout is a DATA
    contract: bench.py's one-JSON-line, the CLI's user output), no root
    logging.getLogger.  Migrated from the grep-based obs test; AST-walked,
    so prints in nested expressions are caught too."""
    for rel in project.files():
        if not rel.startswith(_LOG_ROOTS):
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) and node.func.id == "print":
                    yield Violation(
                        "logging", rel, node.lineno,
                        "bare print() on a control-plane path (use "
                        "utils.logging.get_logger)",
                    )
                elif (rel != _LOG_EXEMPT
                      and isinstance(node.func, ast.Attribute)
                      and node.func.attr == "getLogger"
                      and _last_name(node.func.value) == "logging"):
                    yield Violation(
                        "logging", rel, node.lineno,
                        "root-logger use (want utils.logging.get_logger)",
                    )
            elif isinstance(node, ast.Assign):
                if (any(isinstance(t, ast.Name) and t.id == "log"
                        for t in node.targets)
                        and not (isinstance(node.value, ast.Call)
                                 and _last_name(node.value.func)
                                 == "get_logger")):
                    yield Violation(
                        "logging", rel, node.lineno,
                        "log defined without get_logger",
                    )


# ------------------------------------------------------------------- rule R8

_NET_SCOPE = ("runtime/",)
_NET_FILES = ("__main__.py",)
_NET_EXEMPT = "runtime/http_transport.py"
# Raw client-side connection constructors: urlopen plus the http.client /
# socket primitives it wraps.  Server-side classes (ThreadingHTTPServer)
# are not listed — serving has no retry story to bypass.
_RAW_NET_CALLS = {"urlopen", "create_connection", "HTTPConnection",
                  "HTTPSConnection"}


def rule_net_retry(project: Project) -> Iterator[Violation]:
    """R8: no raw ``urlopen``/client-socket calls on control-plane paths
    (runtime/, the CLI) outside runtime/http_transport.py — every client
    HTTP call routes through the transport's bounded-jittered-retry
    helpers (``HttpTransport._request`` / ``client_call``).  A raw call
    dies on the first transient connection reset, exactly the failure the
    retry layer exists to absorb (a daemon restart resets EVERY attached
    client at once), and silently forks the retry policy the
    DGREP_RPC_RETRIES/DGREP_RPC_BACKOFF_S knobs are supposed to govern."""
    for rel in project.files():
        if not (rel.startswith(_NET_SCOPE) or rel in _NET_FILES):
            continue
        if rel == _NET_EXEMPT:
            continue
        tree = project.tree(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _last_name(node.func)
            if name in _RAW_NET_CALLS:
                yield Violation(
                    "net-retry", rel, node.lineno,
                    f"raw {name}() on a control-plane path: client HTTP "
                    f"calls must route through the retry-wrapped transport "
                    f"helpers (http_transport._request / client_call) — a "
                    f"bare call dies on the first transient reset and "
                    f"bypasses the DGREP_RPC_RETRIES policy",
                )


# ------------------------------------------------------------------ registry

RULES: dict[str, Callable[[Project], Iterator[Violation]]] = {
    "posix-expand": rule_posix_expand,
    "store-resolve": rule_store_resolve,
    "surrogateescape": rule_surrogateescape,
    "env-knobs": rule_env_knobs,
    "rpc-elide": rule_rpc_elide,
    "mosaic-ceilings": rule_mosaic_ceilings,
    "logging": rule_logging,
    "net-retry": rule_net_retry,
}

RULE_DOCS: dict[str, str] = {
    name: (fn.__doc__ or "").strip().splitlines()[0].rstrip(".")
    for name, fn in RULES.items()
}

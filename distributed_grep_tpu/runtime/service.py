"""Grep-as-a-service: a persistent multi-tenant coordinator daemon.

The reference runs one job per coordinator process (coordinator_launch.go
builds one task table and exits when Done()); every request therefore pays
process launch, engine construction, and — on a real chip — the ~20-40 s
first XLA/Mosaic compile per fresh (mode, mesh, model_gen, shape) key.
This module turns the HTTP coordinator into a long-lived daemon serving a
STREAM of jobs over the same persistent workers and engines:

* ``GrepService`` — the multiplexing core: a bounded job queue with
  admission control (``DGREP_SERVICE_MAX_JOBS`` concurrent jobs,
  ``DGREP_SERVICE_QUEUE`` queued submissions), one Scheduler + WorkDir +
  journal + EventLog per job (exactly the single-job machinery, unchanged),
  and a service-level AssignTask that round-robins ready tasks across the
  running jobs' schedulers.  Workers attach ONCE and serve many jobs: each
  assignment carries the job id and the application module spec
  (rpc.AssignTaskReply.job_id/.application), task RPCs echo the job id
  back, and the data plane is job-scoped (``/data/<job>/...``).
* ``ServiceServer`` — the HTTP surface: ``POST /jobs`` (submit, returns
  job_id), ``GET /jobs/<id>`` (state/progress/metrics), ``GET
  /jobs/<id>/result``, ``POST /jobs/<id>/cancel``, service-level ``GET
  /status`` (queue depth, running jobs, per-worker engine state incl. the
  compiled-model-cache counters piggybacked on heartbeats), plus the
  ``/rpc`` + ``/data`` planes workers drive.
* ``ServiceLocalTransport`` — in-process workers for the daemon (the
  ``dgrep serve --workers N`` default on a single host); HTTP workers
  attach with ``dgrep worker --addr`` unchanged (run_http_worker detects
  the service via /status and scopes its data plane per job).

Exactly-once semantics are per job and unchanged: each job keeps its own
work dir, journal, commit records, and timeout sweeper, so a worker death
mid-job-A re-executes only A's attempt while job B streams on.  The
cross-job compiled-model cache lives in ops/engine.cached_engine — a
repeated pattern's second submit skips model compile and the per-shape
compile-grace path, with hit/miss/eviction counters surfaced here.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from dataclasses import replace as _dc_replace
from http.server import ThreadingHTTPServer
from pathlib import Path

from distributed_grep_tpu.runtime import daemon_log as daemon_log_mod
from distributed_grep_tpu.runtime import fusion as fusion_mod
from distributed_grep_tpu.runtime import result_cache as result_cache_mod
from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.runtime.http_coordinator import (
    DataPlaneHandler,
    long_poll_window_s,
)
from distributed_grep_tpu.runtime.journal import TaskJournal
from distributed_grep_tpu.runtime.peer import env_peer_shuffle
from distributed_grep_tpu.runtime.scheduler import (
    Scheduler,
    WorkerHealth,
    _Deadline,
)
from distributed_grep_tpu.runtime.store import make_store
from distributed_grep_tpu.runtime.types import TaskState
from distributed_grep_tpu.utils import lockdep
from distributed_grep_tpu.utils import metrics as metrics_mod
from distributed_grep_tpu.utils import spans as spans_mod
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.io import WorkDir, resolve_input_path
from distributed_grep_tpu.utils.logging import get_logger
from distributed_grep_tpu.utils.metrics import Metrics

log = get_logger("service")

DEFAULT_MAX_JOBS = 4
DEFAULT_QUEUE_DEPTH = 64

# Bounded daemon state over an unbounded job stream: terminal JobRecords
# kept for /status + /jobs/<id> history (oldest-finished evicted beyond
# this), worker-table rows dropped after this much heartbeat silence
# (an attached idle worker refreshes at every long-poll retry, so only
# truly departed workers age out), and per-worker span-seq dedup sets
# pruned to a recency window (seqs are monotonic per worker buffer — a
# retry of a batch thousands of seqs old cannot happen).
_MAX_TERMINAL_RECORDS = 256
_WORKER_EXPIRE_S = 3600.0
# scale_advice capacity freshness: a worker row older than this is not
# counted as an attached worker when sizing the pool (the row itself
# lives until _WORKER_EXPIRE_S — operators still see it in /status)
_SCALE_FRESH_S = 90.0
_SPAN_SEQ_WINDOW = 4096

# How long an idle service-level AssignTask waits between sweeps over the
# running jobs' schedulers.  New-work transitions (submit, job start, map
# phase completion, timeout re-enqueue) wake the wait early via the
# schedulers' on_change hook, so this only bounds staleness for
# transitions with no hook (nothing known today) — not assignment latency.
_ASSIGN_SWEEP_S = 0.25

# Typed job-lifecycle instruments (utils/metrics.py round 15), served as
# Prometheus text at GET /metrics — the live scale signal the elastic
# scale-out item needs (queue depth + queue-wait latency + throughput
# rates), where /status keeps lifetime totals.  Every name is declared
# in utils/metrics.SERIES (analyze rule `metrics-registry`); instrument
# locks are leaves, safe to touch under the service lock.
_C_SUBMITTED = metrics_mod.counter("dgrep_jobs_submitted_total")
_C_REJECTED = metrics_mod.counter("dgrep_jobs_rejected_total")
_C_DONE = metrics_mod.counter("dgrep_jobs_done_total")
_C_FAILED = metrics_mod.counter("dgrep_jobs_failed_total")
_C_CANCELLED = metrics_mod.counter("dgrep_jobs_cancelled_total")
_H_QUEUE_WAIT = metrics_mod.histogram("dgrep_queue_wait_seconds")
_H_JOB_RUN = metrics_mod.histogram("dgrep_job_run_seconds")
_H_JOB_E2E = metrics_mod.histogram("dgrep_job_e2e_seconds")
_H_FINALIZE = metrics_mod.histogram("dgrep_finalize_seconds")
_H_SVC_ASSIGN_POLL = metrics_mod.histogram("dgrep_assign_poll_seconds")

# Monotonic piggybacked counters the rolling-rate tracker follows (the
# model/corpus/index/fusion telemetry the workers already ship).
_TRACKED_COUNTERS = (
    "compile_cache_hits", "compile_cache_misses",
    "corpus_cache_hits", "corpus_cache_misses",
    "index_shards_pruned", "index_bytes_skipped",
    "fused_queries", "fusion_bytes_saved",
)


def env_service_max_jobs(default: int = DEFAULT_MAX_JOBS) -> int:
    """Concurrent running-job cap — the ONE parser of
    DGREP_SERVICE_MAX_JOBS (operator override; malformed or < 1 keeps the
    default, matching env_batch_bytes' shrug-off policy)."""
    raw = os.environ.get("DGREP_SERVICE_MAX_JOBS")
    if raw is None or raw == "":
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


def env_service_queue(default: int = DEFAULT_QUEUE_DEPTH) -> int:
    """Queued-submission cap (admission control) — the ONE parser of
    DGREP_SERVICE_QUEUE.  0 means no queueing: submits beyond the running
    cap are rejected outright."""
    raw = os.environ.get("DGREP_SERVICE_QUEUE")
    if raw is None or raw == "":
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        return default


def env_service_resume(default: bool = True) -> bool:
    """Crash-recovery resume switch — the ONE parser of
    DGREP_SERVICE_RESUME.  On (the default), a restarted daemon replays
    the work root's jobs.jsonl registry: terminal jobs reload as history,
    queued jobs re-admit, running jobs resume from their per-job journals
    and commit records.  "0"/"false"/"no" disables re-admission/resume —
    a restart starts serving fresh (the registry still replays for the
    job-id counter, so old work dirs are never clobbered)."""
    raw = os.environ.get("DGREP_SERVICE_RESUME")
    if raw is None or raw == "":
        return default
    return raw.strip().lower() not in ("0", "false", "no")


class ServiceRegistry:
    """Append-only ``jobs.jsonl`` under the service work root — the
    daemon's durable job table.  One JSON line per event (submit with the
    full JobConfig, then state transitions), fsync'd per append and
    torn-tail-truncated on reopen via the TaskJournal mechanics (the same
    durability discipline the per-job task journal rides).  A restarted
    daemon replays it to rebuild everything the old process held only in
    memory; per-job progress stays where it always was — the job's own
    journal + commit records."""

    FILENAME = "jobs.jsonl"

    def __init__(self, work_root: Path):
        self.path = Path(work_root) / self.FILENAME
        self._journal = TaskJournal(self.path)
        # A dedicated I/O-serialization lock (io_ok): holding it across
        # the fsync'ing append IS its purpose — appends come from RPC
        # threads, watcher threads, and submit, and TaskJournal itself is
        # not locked.
        self._lock = lockdep.make_lock("service-registry", io_ok=True)

    def record_submit(self, job_id: str, config: JobConfig) -> None:
        with self._lock:
            self._journal.record({
                "kind": "job_submit", "job_id": job_id,
                "config": json.loads(config.to_json()), "t": time.time(),
            })

    def record_state(self, job_id: str, state: str, error: str = "",
                     outputs: list[str] | None = None) -> None:
        entry: dict = {"kind": "job_state", "job_id": job_id,
                       "state": state, "t": time.time()}
        if error:
            entry["error"] = error
        if outputs is not None:
            entry["outputs"] = outputs
        with self._lock:
            self._journal.record(entry)

    def record_workers(self, rows: dict[str, dict]) -> None:
        """Worker-table snapshot (round 18 HA only — written from the
        lease renewal thread, change-gated): the last snapshot before a
        failover seeds the promoted daemon's worker table, so
        scale_advice does not advise grow against an attached-but-not-
        yet-reconnected fleet.  Replay treats the LAST record as truth;
        compaction drops them (a compacted registry just means the next
        promotion seeds nothing — workers re-register on their first
        poll anyway)."""
        with self._lock:
            self._journal.record({
                "kind": "workers", "rows": rows, "t": time.time(),
            })

    def close(self) -> None:
        with self._lock:
            self._journal.close()

    @staticmethod
    def replay_workers(work_root: Path) -> dict[str, dict]:
        """The newest worker-table snapshot in the registry (see
        record_workers), or {}.  Read BEFORE compaction at startup —
        compact drops snapshot records."""
        path = Path(work_root) / ServiceRegistry.FILENAME
        rows: dict[str, dict] = {}
        for e in TaskJournal.replay(path):
            if e.get("kind") == "workers" and isinstance(e.get("rows"), dict):
                rows = e["rows"]
        return rows

    @staticmethod
    def replay(work_root: Path) -> tuple[dict[str, dict], int]:
        """(jobs, id_floor): job_id -> {"config": dict | None, "state":
        str, "error": str, "outputs": [...], "t": float} in submit order
        (dict preserves insertion), plus the first job NUMBER a new
        incarnation may mint (max of explicit ``id_floor`` records and
        every registered numeric id, +1) — compaction drops old terminal
        jobs, so the floor record is what keeps their work dirs from
        ever being re-minted.  State records for unknown job ids are
        dropped."""
        path = Path(work_root) / ServiceRegistry.FILENAME
        jobs: dict[str, dict] = {}
        floor = 1
        for e in TaskJournal.replay(path):
            if e.get("kind") == "id_floor":
                try:
                    floor = max(floor, int(e.get("next", 1)))
                except (TypeError, ValueError):
                    pass
                continue
            jid = e.get("job_id")
            if not isinstance(jid, str):
                continue
            tail = jid.rpartition("-")[2]
            if tail.isdigit():
                floor = max(floor, int(tail) + 1)
            if e.get("kind") == "job_submit":
                jobs[jid] = {
                    "config": e.get("config"), "state": JobState.QUEUED,
                    "error": "", "outputs": [], "t": e.get("t", 0.0),
                }
            elif e.get("kind") == "job_state" and jid in jobs:
                rec = jobs[jid]
                rec["state"] = e.get("state", rec["state"])
                rec["error"] = e.get("error", "")
                if e.get("outputs") is not None:
                    rec["outputs"] = e["outputs"]
                rec["t"] = e.get("t", rec["t"])
        return jobs, floor

    @staticmethod
    def trim(jobs: dict[str, dict],
             keep_terminal: int = _MAX_TERMINAL_RECORDS) -> dict[str, dict]:
        """Bound a replayed job map the way the live table is bounded:
        every non-terminal job, plus the newest ``keep_terminal``
        terminal records — a restart must not reload (or re-persist) a
        lifetime of history the running daemon would have pruned."""
        terminal = [jid for jid, info in jobs.items()
                    if info["state"] in _TERMINAL]
        excess = len(terminal) - keep_terminal
        if excess <= 0:
            return dict(jobs)
        terminal.sort(key=lambda jid: jobs[jid].get("t", 0.0))
        dropped = set(terminal[:excess])
        return {jid: info for jid, info in jobs.items()
                if jid not in dropped}

    @staticmethod
    def compact(work_root: Path, jobs: dict[str, dict],
                id_floor: int) -> None:
        """Rewrite jobs.jsonl from a (trimmed) replayed map — an
        append-only log over an unbounded job stream otherwise grows, and
        every restart would re-read the whole history.  Runs at startup
        BEFORE the append handle opens; atomic (tmp + fsync + rename);
        the id_floor record preserves the id space of every job the trim
        dropped."""
        path = Path(work_root) / ServiceRegistry.FILENAME
        if not path.exists():
            return
        tmp = path.with_name(path.name + ".compact")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps({"kind": "id_floor", "next": id_floor},
                               sort_keys=True) + "\n")
            for jid, info in jobs.items():
                if not isinstance(info.get("config"), dict):
                    continue
                f.write(json.dumps(
                    {"kind": "job_submit", "job_id": jid,
                     "config": info["config"], "t": info["t"]},
                    sort_keys=True) + "\n")
                if info["state"] != JobState.QUEUED:
                    entry: dict = {"kind": "job_state", "job_id": jid,
                                   "state": info["state"], "t": info["t"]}
                    if info.get("error"):
                        entry["error"] = info["error"]
                    if info.get("outputs"):
                        entry["outputs"] = info["outputs"]
                    f.write(json.dumps(entry, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)


class AdmissionError(RuntimeError):
    """Submission rejected by admission control (queue full / shutdown)."""


class JobState:
    QUEUED = "queued"
    RUNNING = "running"
    DONE = "done"
    CANCELLED = "cancelled"
    FAILED = "failed"


_TERMINAL = (JobState.DONE, JobState.CANCELLED, JobState.FAILED)

# Canonical constants by value: registry replay loads states as FRESH json
# strings, while the runtime compares with ``is`` against the JobState
# literals — resumed records must carry the canonical objects.
_CANON_STATE = {
    s: s for s in (JobState.QUEUED, JobState.RUNNING, *_TERMINAL)
}


@dataclass
class JobRecord:
    """One submitted job's runtime state: exactly the single-job machinery
    (scheduler, work dir, journal, event log), owned by the service."""

    job_id: str
    config: JobConfig
    state: str = JobState.QUEUED
    scheduler: Scheduler | None = None
    workdir: WorkDir | None = None
    journal: TaskJournal | None = None
    event_log: spans_mod.EventLog | None = None
    metrics: Metrics = field(default_factory=Metrics)
    input_allowlist: frozenset = frozenset()
    submitted_at: float = 0.0
    started_at: float | None = None
    finished_at: float | None = None
    error: str = ""
    outputs: list[str] = field(default_factory=list)
    # map splits precomputed at SUBMIT time, outside the service lock:
    # plan_map_splits stats every input file, and _start_job_locked runs
    # under the lock every control-plane RPC contends on — one tenant's
    # many-small-files submit must not stall every other tenant's
    # heartbeats while the kernel walks its tree.
    map_splits: list = field(default_factory=list)
    # Cross-tenant scan fusion (round 13, runtime/fusion.py): this job's
    # eligibility key, per-split content identities (the CorpusCache
    # realpath+stat validator tuples), and identity -> map-task index —
    # all computed alongside map_splits at submit/resume time, OUTSIDE
    # the service lock (stat work; checked: locked-blocking).  Empty
    # when fusion is off or the job can never fuse.
    fusion_key: tuple | None = None
    split_identities: list = field(default_factory=list)
    fuse_index: dict = field(default_factory=dict)
    # Shard-index planning tallies (index.plan.SplitPruner at submit/
    # resume): kept on the record because the job's Metrics object is
    # built later, at start flush — the builder seeds these into it so
    # /jobs/<id> and dgrep submit's final line can surface routing.
    index_shards_pruned: int = 0
    index_bytes_skipped: int = 0
    # Query-result cache (round 20, runtime/result_cache.py): the
    # submit-time cache plan.  When present, map_splits has been REDUCED
    # to the drifted remainder (the incremental re-query) — the original
    # full split list lives on result_plan.splits; a FULL hit answers at
    # start flush with no scheduler at all.  Tallies ride the record for
    # the same reason as the index ones (Metrics is built later).
    result_plan: object = None
    result_splits_reused: int = 0
    result_bytes_unscanned: int = 0
    result_revalidations: int = 0
    # Streaming tier (round 17, runtime/follow.py): the standing-query
    # runner of a follow job — such records have NO scheduler (every
    # assign-loop/consumer already None-guards it); the runner owns the
    # wake loop, the durable cursor log, and the subscriber ring behind
    # GET /jobs/<id>/stream.  A follow job holds its running slot until
    # cancelled (admission control therefore bounds standing queries
    # exactly like batch jobs).
    follow: object = None
    # set by _resume_replayed for a follow job that was RUNNING when the
    # daemon died: the start flush then keeps the workdir (cursor log!)
    # instead of clearing it, and the runner resumes from its cursors
    resume_follow: bool = False


class GrepService:
    """The multiplexing core: job queue + admission control + service-level
    control plane dispatching onto per-job schedulers."""

    def __init__(
        self,
        work_root: str | Path,
        max_jobs: int | None = None,
        queue_depth: int | None = None,
        spans: bool = False,
        task_timeout_s: float | None = None,
        sweep_interval_s: float | None = None,
        rpc_timeout_s: float = 60.0,
        resume: bool | None = None,
        lease=None,
        daemon_log=None,
    ):
        self.work_root = Path(work_root)
        self.work_root.mkdir(parents=True, exist_ok=True)
        # env knobs win over constructor values (operator override — the
        # same precedence as DGREP_BATCH_BYTES vs JobConfig.batch_bytes)
        self.max_jobs = env_service_max_jobs(
            max_jobs if max_jobs is not None else DEFAULT_MAX_JOBS
        )
        self.queue_depth = env_service_queue(
            queue_depth if queue_depth is not None else DEFAULT_QUEUE_DEPTH
        )
        # Service-wide span switch: governs whether attached workers buffer
        # spans at all (a worker attaches once, before any job exists, so
        # the flag cannot be per-job on the worker side).  Per-job event
        # logs additionally honor the job config's own spans flag.
        self.spans = spans
        # Per-job detector overrides (tests shrink them); None keeps each
        # job config's own values.
        self._task_timeout_s = task_timeout_s
        self._sweep_interval_s = sweep_interval_s
        self.rpc_timeout_s = rpc_timeout_s
        # Active/standby failover (round 18, runtime/lease.py): when a
        # WorkRootLease is attached, every durable-write flush batch
        # (registry, per-job journals, follow logs) re-verifies ownership
        # before writing — a deposed active's late staged flush is
        # DROPPED, never interleaved with the promoted daemon's records.
        # None (the default, single-daemon deployments) is a true no-op:
        # no lease file, no fence reads, byte-identical /status.
        self._lease = lease
        self._deposed = False
        self.deposed_event = threading.Event()
        self._last_worker_snapshot: dict[str, dict] | None = None
        # Daemon lifecycle event log (round 19, runtime/daemon_log.py):
        # None (DGREP_DAEMON_LOG=0, or in-process embedding) is a true
        # no-op — every event site is None-guarded, no staged list
        # exists.  Event sites under the service/scheduler locks only
        # stage() (leaf-lock list append); _flush_daemon_log runs next
        # to the other post-release flushes, through the lease write
        # fence.
        self._daemon_log = daemon_log
        self._last_scale_advice: str | None = None

        self._lock = lockdep.make_lock("service")
        self._cond = threading.Condition(self._lock)
        self._jobs: dict[str, JobRecord] = {}
        self._queue: list[str] = []  # submitted, awaiting a running slot
        self._running: list[str] = []  # assign round-robin order
        # Job starts claimed under the lock but BUILT outside it
        # (checked: locked-blocking): _maybe_start_locked only moves
        # state (queue pop, RUNNING, slot), staging the record here; the
        # filesystem half — work-dir clear, journal/event-log open,
        # scheduler construction — runs in _flush_starts after release.
        # The start-flush lock (io_ok: its purpose is serializing that
        # I/O) keeps setups in staging order.
        self._pending_starts: list[JobRecord] = []
        self._start_flush_lock = lockdep.make_lock("start-flush", io_ok=True)
        # Fused follow tier (round 21): daemon-scope FollowGroupRegistry,
        # built lazily under the start-flush lock by the first follow
        # start when DGREP_FOLLOW_FUSE is on.  None until then — and
        # FOREVER when the knob is off (the true-no-op pin: no group
        # state, no /status key, solo runners byte-identical to pre-21).
        self._follow_groups = None
        # Journal/event-log closes staged by _close_job_locked and run by
        # _flush_closes after release — a file close flushes buffers,
        # filesystem work the service lock must not hold.
        self._pending_closes: list[tuple] = []
        self._rr = 0
        self._ids = itertools.count(1)
        self._stopped = False
        self.started_at = time.time()

        # Service-global worker table and id allocator: per-job schedulers
        # each allocate worker ids from 0, so the SERVICE must own identity
        # for workers that serve many jobs (two jobs' "worker 0" would
        # otherwise be different processes).
        self._next_worker_id = 0
        self.workers: dict[int, dict] = {}
        # submit_token -> job_id dedup map (round 18 satellite): the CLI
        # sends a client-generated token with failover-aware submits so a
        # re-POST to the promoted daemon lands on the SAME job instead of
        # a duplicate.  Rebuilt from registry submit lines at resume
        # (the token rides JobConfig, wire-elided when absent); pruned
        # with the terminal-job table.
        self._tokens: dict[str, str] = {}

        # Span-batch dedup across RPC retries, service-level: batches are
        # drained per WORKER buffer, and one batch may carry records from
        # several jobs' attempts — dedup must happen before the per-job
        # split, not inside any one job's scheduler.
        self._span_seqs: dict[int, set[int]] = {}
        self._span_seq_lock = lockdep.make_lock("span-seq")

        # Rolling-window rate tracker over the piggybacked engine-cache
        # counters: sources keyed by the workers' per-process PROC_TOKEN
        # (fallback: service worker id), first report baselines — a
        # reconnect under a fresh id or N same-process loops can neither
        # double-count nor regress the windowed totals.
        self._cache_rates = metrics_mod.CounterDeltaTracker(
            _TRACKED_COUNTERS
        )

        # ONE flaky-worker quarantine tracker shared by every job's
        # scheduler (runtime/scheduler.WorkerHealth): the service owns
        # worker identity, so a worker going dark under job A must stop
        # receiving job B's tasks too.
        self._health = WorkerHealth()
        if self._daemon_log is not None:
            # quarantine enter/expire/clear land on the fleet timeline
            # exactly once per episode (the tracker is shared by every
            # job's scheduler); staging only — flushed at the service's
            # post-release flush points
            self._health.on_event = self._daemon_event

        # Cross-tenant fusion planning counters (GET /status "fusion"):
        # fused_jobs = participant tasks served by shared attempts,
        # fused_dispatches = fused attempts handed out, fusion_bytes_saved
        # = split bytes co-tenants did NOT re-scan.  Leaf lock.
        self._fusion_lock = lockdep.make_lock("fusion-stats")
        self._fusion_stats = {
            "fused_jobs": 0, "fused_dispatches": 0, "fusion_bytes_saved": 0,
        }

        # Peer-to-peer shuffle accounting (round 16, GET /status
        # "shuffle" + the dgrep_daemon_shuffle_bytes gauge): intermediate
        # bytes that actually transited THIS daemon's HTTP data plane
        # (relay PUTs by producers + relay GETs by reducers).  With peer
        # shuffle on these stay ~0 — the counter IS the receipt that the
        # star topology is gone.  Leaf lock.
        self._shuffle_lock = lockdep.make_lock("shuffle-stats")
        self._shuffle_stats = {
            "daemon_shuffle_bytes": 0, "relay_puts": 0, "relay_gets": 0,
        }

        # Shard-index planning counters (GET /status "index"): shards the
        # split planner dropped (no map task, no worker open), bytes those
        # shards would have scanned, and summaries that answered "maybe".
        # Planner-side only — the engine-side counters ride each worker's
        # heartbeat piggyback rows, exactly like fusion.  Leaf lock.
        self._index_lock = lockdep.make_lock("index-stats")
        self._index_stats = {
            "index_shards_pruned": 0, "index_bytes_skipped": 0,
            "index_maybe_scans": 0,
        }

        # Query-result cache planning counters (round 20, GET /status
        # "result_cache"): jobs answered wholly from cache, partial
        # (incremental re-query) hits, splits served without a scan,
        # bytes those splits would have scanned, and publications dropped
        # because the split drifted mid-job.  Planner-side, leaf lock.
        # DGREP_RESULT_CACHE=0 (or a zero budget) leaves the store None —
        # a TRUE no-op: no results/ dir, no /status key, no instants.
        self._result_lock = lockdep.make_lock("result-stats")
        self._result_stats = {
            "result_hits": 0, "result_partial_hits": 0,
            "result_splits_reused": 0, "result_bytes_unscanned": 0,
            "result_revalidations": 0,
        }
        self._result_store = (
            result_cache_mod.ResultStore(self.work_root / "results")
            if result_cache_mod.env_result_cache()
            and result_cache_mod.env_result_bytes() > 0
            else None
        )

        # Durable job registry (jobs.jsonl) + staged transition records:
        # appends are fsync'd, so they happen OUTSIDE the service lock —
        # state changes decided under the lock stage here and flush after
        # release (`_flush_registry`).  Crash-ordering argument: a job is
        # registered BEFORE its id is returned to the client (submit), and
        # a missing later transition only makes a restart redo work whose
        # journals/commit records then short-circuit it — never lose or
        # duplicate a result.
        replayed, id_floor = ServiceRegistry.replay(self.work_root)
        if self._lease is not None:
            # HA promotion (satellite): seed the worker table from the
            # deposed active's last renewal-time snapshot — read BEFORE
            # compaction drops the snapshot records.  Without this,
            # scale_advice on the promoted daemon counts zero attached
            # workers until each one's next poll and advises grow
            # against an invisible-but-attached fleet.
            self._seed_workers(ServiceRegistry.replay_workers(self.work_root))
        # bound + compact BEFORE the append handle opens: the registry is
        # append-only over an unbounded job stream, so each restart
        # rewrites it down to the live jobs + the newest terminal history
        # (the id_floor record keeps dropped jobs' ids retired forever)
        replayed = ServiceRegistry.trim(replayed)
        ServiceRegistry.compact(self.work_root, replayed, id_floor)
        self._registry = ServiceRegistry(self.work_root)
        self._registry_pending: list[tuple] = []
        # Orders FLUSH BATCHES end to end (swap + append as one unit):
        # staging is ordered under the service lock, but two concurrent
        # flushers writing their swapped batches unlocked could land
        # "cancelled" before the older "running" — and replay trusts the
        # LAST state.  Outer to self._lock; nothing takes them reversed.
        # io_ok: holding it across the fsync'ing appends is its purpose.
        self._registry_flush_lock = lockdep.make_lock("registry-flush",
                                                      io_ok=True)
        # the id counter continues past every id ever registered no
        # matter what: even a resume-disabled restart must never mint an
        # id whose work dir an earlier incarnation owns
        self._ids = itertools.count(id_floor)
        if self._daemon_log is not None:
            # fleet timeline: one "start" line per daemon incarnation
            # (the resume line with replay counts follows when the
            # registry held live jobs)
            self._daemon_event(
                "start", work_root=str(self.work_root),
                max_jobs=self.max_jobs, queue_depth=self.queue_depth,
            )
            self._flush_daemon_log()
        if env_service_resume() if resume is None else resume:
            self._resume_replayed(replayed)

    # ---------------------------------------------------------------- resume
    def _resume_replayed(self, replayed: dict[str, dict]) -> None:
        """Rebuild the job table from the registry at construction time
        (single-threaded: the HTTP surface and workers attach later, so
        no lock discipline applies yet).  Terminal jobs reload as history
        rows; jobs that never started re-admit to the queue; jobs that
        were RUNNING resume from their per-job journal + commit records —
        completed tasks replay as done, in-flight attempts at crash time
        simply re-run (their eventual duplicate commits resolve to one
        winner, the PR-1 invariant)."""
        for jid, info in replayed.items():
            cfg_dict = info.get("config")
            if not isinstance(cfg_dict, dict):
                continue
            try:
                cfg = JobConfig(**cfg_dict)
            except (TypeError, ValueError) as e:
                log.warning("registry job %s has an unloadable config "
                            "(%s); dropping", jid, e)
                continue
            state = _CANON_STATE.get(info["state"])
            if state is None:
                log.warning("registry job %s has unknown state %r; "
                            "dropping", jid, info["state"])
                continue
            if getattr(cfg, "submit_token", ""):
                # rebuild the submit dedup map: a client re-POSTing its
                # token to the promoted daemon must land on THIS job
                self._tokens[cfg.submit_token] = jid
            rec = JobRecord(job_id=jid, config=cfg, state=state,
                            submitted_at=info.get("t", 0.0))
            if state in _TERMINAL:
                rec.finished_at = info.get("t", 0.0)
                rec.error = info.get("error", "")
                rec.outputs = list(info.get("outputs") or [])
                self._jobs[jid] = rec
                continue
            if getattr(cfg, "follow", False):
                # Standing query (round 17): no planning, and a missing
                # input is legal (the cursor waits for creation).  A
                # RUNNING row resumes through the normal start flush
                # with resume_follow set — the workdir is KEPT and the
                # runner restores every cursor from follow.jsonl (the
                # no-duplicate/no-lost-line restart contract).
                self._jobs[jid] = rec
                if state == JobState.RUNNING:
                    rec.state = JobState.RUNNING
                    rec.started_at = time.time()
                    rec.resume_follow = True
                    self._running.append(jid)
                    self._pending_starts.append(rec)
                else:
                    rec.state = JobState.QUEUED
                    self._queue.append(jid)
                continue
            # queued or running: the work must be (re)scheduled.  Re-run
            # submit's readability validation FIRST — an input deleted
            # during the outage would otherwise re-enqueue its map task
            # forever (plan_map_splits itself shrugs stat failures off,
            # so no exception guard can catch this) and pin a running
            # slot until the next restart.
            missing = [f for f in cfg.input_files
                       if not os.access(f, os.R_OK)]
            if missing:
                rec.state = JobState.FAILED
                rec.error = f"inputs unreadable at resume: {missing}"
                rec.finished_at = time.time()
                _C_FAILED.inc()
                self._jobs[jid] = rec
                self._registry_pending.append(
                    (jid, JobState.FAILED, rec.error, None)
                )
                continue
            # both re-plan splits (the plan is deterministic for
            # unchanged inputs; changed inputs fail replay's member-list
            # guard and re-run — correct either way).  BOTH states prune
            # against the restart-surviving summary store (the "warm
            # survives the process" contract): for a job that was pruned
            # at submit the store still holds the same summaries, so the
            # re-plan REPRODUCES the submit-time split list and journal
            # replay keeps every committed task; a plan that still
            # drifts (summaries that appeared/evicted during the outage)
            # only re-runs the drifted splits — pruned files produce no
            # output either way, so every plan is output-identical.
            from distributed_grep_tpu.runtime.job import plan_map_splits

            pruner = self._index_pruner(cfg)
            rec.map_splits = plan_map_splits(
                list(cfg.input_files), cfg.effective_batch_bytes(),
                pruner=pruner,
            )
            self._stamp_index_plan(rec, pruner)
            # result cache survives the restart (the "warm survives the
            # process" contract): re-plan against the persisted store so
            # a resumed job reuses every still-valid split result — a
            # full hit resumes straight through the start flush with no
            # scheduler, like a follow resume
            rec.result_plan = self._result_plan(cfg, rec.map_splits)
            if rec.result_plan is not None:
                rec.map_splits = rec.result_plan.remaining
            self._stamp_result_plan(rec)
            (rec.fusion_key, rec.split_identities,
             rec.fuse_index) = self._fusion_plan(cfg, rec.map_splits)
            self._jobs[jid] = rec
            if state == JobState.RUNNING:
                if rec.result_plan is not None and rec.result_plan.full:
                    rec.state = JobState.RUNNING
                    rec.started_at = time.time()
                    self._running.append(jid)
                    self._pending_starts.append(rec)
                else:
                    self._resume_running_job(rec)
            else:
                rec.state = JobState.QUEUED
                self._queue.append(jid)
        # start queued jobs into free slots now so a restarted daemon is
        # serving the backlog before the first worker even attaches
        with self._cond:
            self._maybe_start_locked()
        self._flush_starts()
        self._flush_registry()
        if self._jobs:
            self._daemon_event(
                "resume", jobs=len(self._jobs),
                running=len(self._running), queued=len(self._queue),
            )
            self._flush_daemon_log()
            log.info(
                "service resume: %d jobs from registry (%d running, %d "
                "queued)", len(self._jobs), len(self._running),
                len(self._queue),
            )

    def _resume_running_job(self, rec: JobRecord) -> None:
        """Re-open a job that was RUNNING when the daemon died: same work
        dir (NOT cleared), journal replayed so completed tasks stay done,
        commit records re-resolved as the unit of truth, event log
        appended (one job, one log across daemon restarts)."""
        cfg = rec.config
        store = make_store(cfg.store)
        rec.workdir = WorkDir(cfg.work_dir, store=store)
        resume_entries = None
        if cfg.journal:
            resume_entries = TaskJournal.replay(rec.workdir.journal_path())
            rec.journal = TaskJournal(rec.workdir.journal_path())
        spans_on = spans_mod.enabled(cfg.spans) or self.spans
        rec.event_log = (
            spans_mod.EventLog(
                rec.workdir.root / spans_mod.EventLog.FILENAME, fresh=False
            )
            if spans_on else None
        )
        rec.input_allowlist = frozenset(cfg.input_files)
        rec.metrics = Metrics()
        if rec.index_shards_pruned:
            # seed the resume re-plan's shard-index tallies (same
            # contract as the start-flush parts builder): a resumed
            # job's /jobs view and submit-client JSON keep the routing
            rec.metrics.inc("index_shards_pruned", rec.index_shards_pruned)
            rec.metrics.inc("index_bytes_skipped", rec.index_bytes_skipped)
        if rec.result_splits_reused:
            rec.metrics.inc("result_splits_reused", rec.result_splits_reused)
            rec.metrics.inc("result_bytes_unscanned",
                            rec.result_bytes_unscanned)
        rec.scheduler = Scheduler(
            files=rec.map_splits,
            n_reduce=cfg.n_reduce,
            task_timeout_s=cfg.task_timeout_s,
            sweep_interval_s=cfg.sweep_interval_s,
            app_options=cfg.effective_app_options(),
            journal=rec.journal,
            resume_entries=resume_entries,
            metrics=rec.metrics,
            commit_resolver=rec.workdir.resolve_task_commit,
            event_log=rec.event_log,
            on_change=self._wake,
            worker_health=self._health,
            journal_gate=self._write_gate(),
            daemon_events=self._job_daemon_events(rec.job_id),
        )
        rec.state = JobState.RUNNING
        rec.started_at = time.time()
        self._running.append(rec.job_id)
        if rec.event_log is not None:
            rec.event_log.write({
                "t": "instant", "name": "resume", "cat": "service",
                "ts": time.time(), "job": rec.job_id,
                "args": {"replayed_entries": len(resume_entries or [])},
            })
        threading.Thread(
            target=self._watch_job, args=(rec,), daemon=True,
            name=f"svc-watch-{rec.job_id}",
        ).start()
        log.info("job %s resumed (%d journal entries replayed)",
                 rec.job_id, len(resume_entries or []))

    # -------------------------------------------------------- registry I/O
    def _stage_state(self, rec: JobRecord,
                     outputs: list[str] | None = None) -> None:
        """Stage a state-transition record under the service lock; written
        by `_flush_registry` after release (appends fsync — never inside
        a `_locked` method)."""
        self._registry_pending.append(
            (rec.job_id, rec.state, rec.error, outputs)
        )
        if rec.state in _TERMINAL:
            # every terminal transition — done, failed, cancelled, the
            # enqueue-recheck 429, stop()'s mass-cancel — lands on the
            # fleet timeline through this one staging point
            self._daemon_event(
                "job_terminal", job=rec.job_id, state=rec.state,
                **({"error": rec.error} if rec.error else {}),
            )

    def _flush_registry(self) -> None:
        """Write staged registry records outside the service lock.  The
        flush lock makes swap + append one ordered unit — without it a
        preempted flusher could append its older batch AFTER a newer one
        and replay would trust the stale last state.  Never raises: a
        full disk must degrade crash-recovery, not take the control
        plane down."""
        with self._registry_flush_lock:
            with self._lock:
                if not self._registry_pending:
                    return
                pending, self._registry_pending = self._registry_pending, []
            if not self._lease_ok():
                # The daemon-scope write fence (round 18): a standby
                # stole the lease while this batch sat staged — we are
                # deposed.  DROP the batch (the promoted daemon owns
                # these jobs' records now; an interleaved stale append
                # would become replay's trusted last state) and fence
                # the rest of the daemon.  Split-brain loses at most
                # this one unflushed batch.
                log.warning("registry flush fenced: lease lost, %d staged "
                            "records dropped", len(pending))
                self._on_lease_lost()
                return
            for job_id, state, error, outputs in pending:
                try:
                    self._registry.record_state(
                        job_id, state, error=error, outputs=outputs
                    )
                except Exception:  # noqa: BLE001
                    log.exception("registry append failed for job %s",
                                  job_id)

    def _daemon_event(self, kind: str, **payload) -> None:
        """Stage one fleet-timeline event (runtime/daemon_log.py).  Leaf-
        lock list append only — safe under the service lock; written by
        `_flush_daemon_log` after release.  No-op when the log is off."""
        dl = self._daemon_log
        if dl is not None:
            dl.stage(kind, **payload)

    def _job_daemon_events(self, job_id: str):
        """The per-job Scheduler's fleet-timeline hook: stage with the
        job tag folded in, or None when the daemon log is off (the
        scheduler then skips the call entirely)."""
        if self._daemon_log is None:
            return None

        def stage(kind: str, **payload) -> None:
            self._daemon_event(kind, job=job_id, **payload)

        return stage

    def _flush_daemon_log(self) -> None:
        """Write staged daemon events outside the service lock, through
        the round-18 lease write fence (a deposed daemon's late events
        are dropped whole, never interleaved with the promoted
        daemon's)."""
        dl = self._daemon_log
        if dl is not None:
            dl.flush(self._write_gate())

    # ------------------------------------------------------------- HA lease
    def _lease_ok(self) -> bool:
        """The daemon-scope write fence: no lease (single-daemon) is
        always OK; with one attached, the on-disk record must still name
        this incarnation.  File read — called from flush context (inside
        the io_ok flush locks) or unlocked paths only, never under the
        service lock (locked-blocking)."""
        lease = self._lease
        if lease is None:
            return True
        return not self._deposed and lease.verify()

    def _on_lease_lost(self) -> None:
        """A standby stole the lease: fence this daemon.  Idempotent and
        I/O-free — flips the deposed flag, closes admission (_stopped),
        and signals the serve loop (deposed_event) to demote this
        process back to standby.  Jobs keep their on-disk state; the
        promoted daemon resumed them already."""
        with self._cond:
            if self._deposed:
                return
            self._deposed = True
            self._stopped = True
            self._cond.notify_all()
        log.warning("daemon deposed: durable writes fenced, admission "
                    "closed (work root %s)", self.work_root)
        # Staged for completeness; the write fence DROPS it (a deposed
        # daemon's late events never interleave) — the thief's
        # lease_steal line is the durable record of this transition.
        self._daemon_event("lease_lost")
        self.deposed_event.set()

    def _write_gate(self):
        """The per-job durable-write fence (Scheduler journal_gate /
        FollowRunner write_gate): None when no lease is attached (the
        single-daemon no-op — schedulers skip the check entirely), else
        a callable the journal/follow flush paths consult before
        writing.  A False answer both drops that batch and deposes the
        daemon."""
        if self._lease is None:
            return None

        def gate() -> bool:
            if self._lease_ok():
                return True
            self._on_lease_lost()
            return False

        return gate

    def lease_renewed(self) -> None:
        """Renewal-thread hook (WorkRootLease.start_renewal on_renew):
        persist a change-gated worker-table snapshot so a failover
        inherits the fleet view (see ServiceRegistry.record_workers).
        Runs with no service lock held; the registry append serializes
        on the registry's own io_ok lock."""
        with self._lock:
            rows = {
                str(wid): {
                    k: info[k]
                    for k in ("job", "task", "metrics", "data_endpoint")
                    if info.get(k) is not None
                }
                for wid, info in self.workers.items()
            }
        if rows == self._last_worker_snapshot:
            return
        try:
            self._registry.record_workers(rows)
        except Exception:  # noqa: BLE001 — telemetry, never fatal
            log.exception("worker-table snapshot append failed")
            return
        self._last_worker_snapshot = rows

    def _seed_workers(self, rows: dict[str, dict]) -> None:
        """Adopt a replayed worker-table snapshot at promotion: rows get
        FRESH seen stamps (monotonic clocks are process-local) so
        scale_advice counts the attached fleet as capacity immediately;
        the 1 h expiry still ages out workers that never reconnect.  The
        id allocator jumps past every seeded id — reconnecting workers
        that kept their old ids must not collide with fresh allocations."""
        if not rows:
            return
        now = time.monotonic()
        for wid_str, row in rows.items():
            try:
                wid = int(wid_str)
            except (TypeError, ValueError):
                continue
            info: dict = {"job": None, "task": None, "seen": now}
            if isinstance(row, dict):
                for k in ("job", "task", "metrics", "data_endpoint"):
                    if row.get(k) is not None:
                        info[k] = row[k]
            self.workers[wid] = info
            self._next_worker_id = max(self._next_worker_id, wid + 1)
        self._last_worker_snapshot = dict(rows)
        log.info("promotion seeded %d worker rows from registry snapshot",
                 len(self.workers))

    # ---------------------------------------------------------------- submit
    def submit(self, config: JobConfig) -> str:
        """Admit a job: validate, queue, start if a slot is free.  Raises
        AdmissionError when the queue is full or the service is stopping,
        ValueError for configs that could never complete (missing inputs
        would re-enqueue their map task forever)."""
        from distributed_grep_tpu.runtime.job import plan_map_splits

        # submit_token dedup (round 18): a failover-aware client re-POSTs
        # its submit to the promoted daemon with the SAME token — answer
        # the job the first delivery registered instead of admitting a
        # duplicate.  Checked again (and claimed) under the lock at mint.
        token = getattr(config, "submit_token", "")
        if token:
            with self._lock:
                dup = self._tokens.get(token)
            if dup is not None:
                return dup
        # admission FIRST: 429-destined submits in the overload regime —
        # the exact traffic load-shedding exists for — must be rejected
        # before this submit pays any filesystem walk over its inputs.
        # Re-checked under the lock at enqueue: the walk window can race
        # other submits past the cap.
        try:
            self._check_admission_locked_or_raise()
        except AdmissionError as e:
            _C_REJECTED.inc()
            self._daemon_event("admission_reject", reason=str(e))
            self._flush_daemon_log()
            raise
        if getattr(config, "follow", False):
            # Standing query (round 17): no map/reduce planning, no
            # fusion, no index injection — the follow runner suffix-scans
            # the inputs itself.  Inputs MAY be missing (a standing query
            # over a log that does not exist yet is the tail -F shape;
            # the cursor waits for creation).  Validation instead gates
            # on what the follow scanner can actually serve.
            self._validate_follow_config(config)
            pruner = None
            splits: list = []
            result_plan = None
            fuse_key, identities, fuse_index = None, [], {}
        else:
            missing = [f for f in config.input_files
                       if not os.access(f, os.R_OK)]
            if missing:
                raise ValueError(f"unreadable input files: {missing}")
            # Shard index (distributed_grep_tpu/index): thread the
            # service's persistence root through the grep app BEFORE
            # planning, so the stored config (registry), the fusion key,
            # and the workers all see one consistent option set; with
            # DGREP_INDEX=0 nothing is injected and the daemon is
            # byte-for-byte pre-index.
            idx_dir = self._index_app_dir(config)
            if idx_dir is not None:
                config = _dc_replace(
                    config,
                    app_options={**config.app_options, "index_dir": idx_dir},
                )
            # splits depend only on (input_files, batch window) — stat the
            # inputs here, outside the lock (see JobRecord.map_splits); the
            # index pruner's summary/store reads run here too (never under
            # the service lock — locked-blocking)
            pruner = self._index_pruner(config)
            splits = plan_map_splits(
                list(config.input_files), config.effective_batch_bytes(),
                pruner=pruner,
            )
            # Query-result cache (round 20): look every planned split up
            # with a fresh stat per member — still outside the lock, the
            # same locked-blocking contract.  A hit REDUCES the split
            # list to the drifted remainder (the incremental re-query)
            # BEFORE fusion planning, so fuse_index task ids line up
            # with the scheduler the reduced list builds.
            result_plan = self._result_plan(config, splits)
            if result_plan is not None:
                splits = result_plan.remaining
            fuse_key, identities, fuse_index = self._fusion_plan(
                config, splits
            )
        with self._cond:
            if token:
                # the planning window above is unlocked: a concurrent
                # duplicate may have claimed the token first
                dup = self._tokens.get(token)
                if dup is not None:
                    return dup
            self._check_admission_locked_or_raise(locked=True)
            job_id = f"job-{next(self._ids)}"
            if token:
                self._tokens[token] = job_id
            # The service owns job identity and placement: the work dir is
            # ALWAYS <work_root>/<job_id> (two submits naming one work_dir
            # would corrupt each other's commits) and the span job tag is
            # the service job id.
            cfg = _dc_replace(
                config,
                work_dir=str(self.work_root / job_id),
                job_id=job_id,
                **({"task_timeout_s": self._task_timeout_s}
                   if self._task_timeout_s is not None else {}),
                **({"sweep_interval_s": self._sweep_interval_s}
                   if self._sweep_interval_s is not None else {}),
            )
            rec = JobRecord(job_id=job_id, config=cfg,
                            submitted_at=time.time(), map_splits=splits,
                            fusion_key=fuse_key,
                            split_identities=identities,
                            fuse_index=fuse_index,
                            result_plan=result_plan)
        self._stamp_index_plan(rec, pruner)
        self._stamp_result_plan(rec)
        # Durability BEFORE visibility: the registry append (fsync)
        # happens outside the lock and before the id is handed to the
        # client — from this line on a daemon crash re-admits the job at
        # restart instead of silently forgetting an acknowledged submit.
        if not self._lease_ok():
            # deposed mid-submit: this daemon must not durably register
            # a job the promoted active will never learn about — the
            # client's rotation retries the POST against the new active
            # (the submit_token makes the re-POST safe either way)
            self._on_lease_lost()
            with self._lock:
                if token:
                    self._tokens.pop(token, None)
            _C_REJECTED.inc()
            raise AdmissionError("daemon deposed: lease lost")
        try:
            self._registry.record_submit(job_id, cfg)
        except (OSError, ValueError) as e:
            # closed registry (stop() won the race) or a dead disk: a job
            # we cannot durably register is a job we must not accept
            with self._lock:
                if token:
                    self._tokens.pop(token, None)
            _C_REJECTED.inc()
            self._daemon_event("admission_reject", job=job_id,
                               reason=f"cannot register job: {e}")
            self._flush_daemon_log()
            raise AdmissionError(f"cannot register job: {e}") from e
        rejected: AdmissionError | None = None
        with self._cond:
            # admission re-check AT ENQUEUE: the fsync window above is
            # unlocked, so N concurrent submits could all have passed the
            # earlier check against the same queue depth — without this,
            # the overload regime the 429 cap exists for overshoots it.
            try:
                self._check_admission_locked_or_raise(locked=True)
            except AdmissionError as e:
                # already durably registered: record the rejection so a
                # restart does not re-admit a job the client saw 429'd
                rejected = e
                rec.state = JobState.CANCELLED
                rec.error = "rejected by admission control at enqueue"
                rec.finished_at = time.time()
                self._jobs[job_id] = rec
                self._stage_state(rec)
                self._daemon_event("admission_reject", job=job_id,
                                   reason=rec.error)
                self._prune_terminal_locked()
            else:
                self._jobs[job_id] = rec
                self._queue.append(job_id)
                self._maybe_start_locked()
            self._cond.notify_all()
        self._flush_starts()
        self._flush_registry()
        self._flush_daemon_log()
        if rejected is not None:
            _C_REJECTED.inc()
            raise rejected
        _C_SUBMITTED.inc()
        return job_id

    def _check_admission_locked_or_raise(self, locked: bool = False) -> None:
        if not locked:
            with self._lock:
                return self._check_admission_locked_or_raise(locked=True)
        if self._stopped:
            raise AdmissionError("service is shutting down")
        if len(self._queue) >= max(0, self.queue_depth) and (
            len(self._running) >= self.max_jobs
        ):
            raise AdmissionError(
                f"admission control: {len(self._running)} running "
                f"(cap {self.max_jobs}), {len(self._queue)} queued "
                f"(cap {self.queue_depth})"
            )

    @staticmethod
    def _validate_follow_config(config: JobConfig) -> None:
        """Reject standing-query configs the follow scanner cannot serve
        honestly — at SUBMIT, not at first wake (a standing query that
        can never emit must not silently hold a running slot)."""
        opts = config.effective_app_options()
        if opts.get("pattern") is None and not opts.get("patterns"):
            raise ValueError("follow jobs need a pattern (or patterns) "
                             "app option")
        if not config.input_files:
            raise ValueError("follow jobs need at least one input file")
        unsupported = [k for k in ("word_regexp", "line_regexp",
                                   "max_errors", "mesh_shape")
                       if opts.get(k)]
        if unsupported:
            raise ValueError(
                f"app options unsupported with follow: {unsupported}"
            )

    def _maybe_start_locked(self) -> None:
        """Claim queued jobs into free running slots.  Only STATE moves
        here (queue pop, RUNNING, the slot, the registry record); the
        filesystem half of a start is staged for _flush_starts — one
        tenant's job start must not stall every other tenant's RPCs on
        its work-dir I/O (checked: locked-blocking).  Until the flush
        publishes rec.scheduler, readers treat the job as running-but-
        not-yet-assignable (every consumer None-guards the scheduler)."""
        while self._queue and len(self._running) < self.max_jobs:
            rec = self._jobs[self._queue.pop(0)]
            rec.state = JobState.RUNNING
            rec.started_at = time.time()
            if rec.submitted_at:
                # submit-to-start queue wait — the scale-out signal
                # (a growing p95 here means the running-slot cap or the
                # worker pool is the bottleneck, not the scans)
                _H_QUEUE_WAIT.observe(rec.started_at - rec.submitted_at)
            self._running.append(rec.job_id)
            self._stage_state(rec)  # "running" — flushed post-lock
            self._pending_starts.append(rec)

    def _build_job_runtime(self, rec: JobRecord) -> tuple:
        """The filesystem-heavy half of a job start (no service lock
        held): work dir (cleared — job ids are unique, but stay
        defensive), journal + event log, metrics, scheduler.  Returns
        the parts for the locked publish in _flush_starts."""
        cfg = rec.config
        store = make_store(cfg.store)
        workdir = WorkDir(cfg.work_dir, store=store)
        workdir.clear()
        journal = (
            TaskJournal(workdir.journal_path()) if cfg.journal else None
        )
        spans_on = spans_mod.enabled(cfg.spans) or self.spans
        event_log = (
            spans_mod.EventLog(
                workdir.root / spans_mod.EventLog.FILENAME, fresh=True
            )
            if spans_on else None
        )
        rec.input_allowlist = frozenset(cfg.input_files)
        metrics = Metrics()
        if rec.index_shards_pruned:
            # seed the planning-time shard-index tallies (stamped at
            # submit, before this Metrics object existed)
            metrics.inc("index_shards_pruned", rec.index_shards_pruned)
            metrics.inc("index_bytes_skipped", rec.index_bytes_skipped)
        if rec.result_splits_reused:
            # same contract for the result-cache planning tallies
            metrics.inc("result_splits_reused", rec.result_splits_reused)
            metrics.inc("result_bytes_unscanned",
                        rec.result_bytes_unscanned)
        if rec.result_plan is not None and event_log is not None:
            # a job reaching this builder with a plan is a partial hit
            # (full hits dispatch in _flush_starts) or a clean miss —
            # say which, so dgrep explain can fold the verdict in
            plan = rec.result_plan
            event_log.write({
                "t": "instant",
                "name": "result:partial" if plan.cached else "result:miss",
                "cat": "service", "ts": time.time(), "job": rec.job_id,
                "args": {
                    "splits_reused": plan.splits_reused,
                    "splits_scanned": len(plan.remaining),
                    "bytes_unscanned": plan.bytes_unscanned,
                },
            })
        scheduler = Scheduler(
            files=rec.map_splits,
            n_reduce=cfg.n_reduce,
            task_timeout_s=cfg.task_timeout_s,
            sweep_interval_s=cfg.sweep_interval_s,
            app_options=cfg.effective_app_options(),
            journal=journal,
            metrics=metrics,
            commit_resolver=workdir.resolve_task_commit,
            event_log=event_log,
            on_change=self._wake,
            worker_health=self._health,
            journal_gate=self._write_gate(),
            daemon_events=self._job_daemon_events(rec.job_id),
        )
        return workdir, journal, event_log, metrics, scheduler

    def _flush_starts(self) -> None:
        """Run staged job starts outside the service lock.  The
        start-flush lock (io_ok) orders setups in staging order; the
        locked tail publishes the runtime fields in one step — or tears
        the fresh parts down when a cancel/stop won the race mid-setup.
        A failed setup records FAILED exactly like the old in-lock path
        (a read-only work_root fails every start; the table stays
        bounded)."""
        with self._lock:
            # fast path: nothing staged — don't serialize this caller
            # behind another tenant's in-flight job build (entries are
            # only handled by the flusher that observes them, so an
            # empty list here is safe to skip)
            if not self._pending_starts:
                return
        with self._start_flush_lock:
            while True:
                with self._cond:
                    while self._pending_starts and (
                        self._pending_starts[0].state is not JobState.RUNNING
                    ):
                        self._pending_starts.pop(0)  # cancelled pre-setup
                    if not self._pending_starts:
                        return
                    rec = self._pending_starts.pop(0)
                if getattr(rec.config, "follow", False):
                    self._flush_follow_start(rec)
                    continue
                if rec.result_plan is not None and rec.result_plan.full:
                    # Query-result cache FULL hit: every split answered
                    # from the store at plan time — the job completes
                    # right here with no scheduler, no worker dispatch,
                    # no watcher thread.  A failed cache materialization
                    # falls back to the normal scan path with the plan
                    # dropped (never inject cached blobs on top of a
                    # full rescan — that would duplicate records).
                    if self._flush_result_hit(rec):
                        continue
                    rec.map_splits = rec.result_plan.splits
                    rec.result_splits_reused = 0
                    rec.result_bytes_unscanned = 0
                    rec.result_plan = None
                    # fusion was planned against the (empty) reduced
                    # list — a stale fuse_index would map identities to
                    # wrong task ids, so this job just never fuses
                    rec.fusion_key = None
                    rec.split_identities = []
                    rec.fuse_index = {}
                try:
                    parts = self._build_job_runtime(rec)
                except Exception as e:  # noqa: BLE001 — bad job, healthy service
                    log.exception("job %s failed to start", rec.job_id)
                    with self._cond:
                        if rec.state is JobState.RUNNING:
                            # a cancel/stop that won the race already
                            # recorded ITS terminal state — don't
                            # overwrite cancelled with failed
                            rec.state = JobState.FAILED
                            rec.error = str(e)
                            rec.finished_at = time.time()
                            _C_FAILED.inc()
                            if rec.job_id in self._running:
                                self._running.remove(rec.job_id)
                            self._stage_state(rec)
                            self._prune_terminal_locked()
                            self._maybe_start_locked()  # refill the slot
                            self._cond.notify_all()
                    continue
                workdir, journal, event_log, metrics, scheduler = parts
                published = False
                with self._cond:
                    if rec.state is JobState.RUNNING:
                        rec.workdir = workdir
                        rec.journal = journal
                        rec.event_log = event_log
                        rec.metrics = metrics
                        rec.scheduler = scheduler
                        published = True
                        self._cond.notify_all()
                if not published:
                    # cancel/stop won the race mid-setup: tear down the
                    # parts that never became visible
                    scheduler.stop()
                    scheduler.close_journal()
                    if event_log is not None:
                        event_log.close()
                    continue
                threading.Thread(
                    target=self._watch_job, args=(rec,), daemon=True,
                    name=f"svc-watch-{rec.job_id}",
                ).start()
                log.info(
                    "job %s started (%d map tasks, %d reduce, %d running, "
                    "%d queued)",
                    rec.job_id, len(scheduler.map_tasks), rec.config.n_reduce,
                    len(self._running), len(self._queue),
                )

    def _flush_follow_start(self, rec: JobRecord) -> None:
        """The follow half of _flush_starts (no service lock held): build
        the workdir + event log + FollowRunner (journal open and cursor
        replay are filesystem work), publish under the lock, start the
        wake loop.  A cancel/stop that won the race mid-setup tears the
        fresh runner down exactly like the scheduler path.

        Fused tier (round 21): runs under the start-flush lock (the
        _flush_starts contract), so the lazy FollowGroupRegistry build
        below cannot race — DGREP_FOLLOW_FUSE=0 leaves it None forever
        and every runner keeps the pre-round-21 solo path."""
        from distributed_grep_tpu.runtime import follow as follow_mod
        from distributed_grep_tpu.runtime.follow import FollowRunner

        if self._follow_groups is None and follow_mod.env_follow_fuse():
            self._follow_groups = follow_mod.FollowGroupRegistry(
                write_gate=self._write_gate()
            )
        cfg = rec.config
        event_log = None
        try:
            store = make_store(cfg.store)
            workdir = WorkDir(cfg.work_dir, store=store)
            if not rec.resume_follow:
                workdir.clear()  # fresh standing query: no stale cursors
            spans_on = spans_mod.enabled(cfg.spans) or self.spans
            event_log = (
                spans_mod.EventLog(
                    workdir.root / spans_mod.EventLog.FILENAME,
                    fresh=not rec.resume_follow,
                )
                if spans_on else None
            )
            runner = FollowRunner(
                rec.job_id, cfg, workdir.root,
                event_log=event_log, on_fail=self._fail_follow_job,
                write_gate=self._write_gate(),
                groups=self._follow_groups,
            )
        except Exception as e:  # noqa: BLE001 — bad job, healthy service
            log.exception("follow job %s failed to start", rec.job_id)
            if event_log is not None:
                # the runner construction failed AFTER the event log
                # opened: close it here or the fd leaks for the daemon's
                # lifetime (the published path hands it to the close flush)
                try:
                    event_log.close()
                except Exception:  # noqa: BLE001 — teardown must not raise
                    log.exception("event log close failed for %s",
                                  rec.job_id)
            with self._cond:
                if rec.state is JobState.RUNNING:
                    rec.state = JobState.FAILED
                    rec.error = str(e)
                    rec.finished_at = time.time()
                    _C_FAILED.inc()
                    if rec.job_id in self._running:
                        self._running.remove(rec.job_id)
                    self._stage_state(rec)
                    self._prune_terminal_locked()
                    self._maybe_start_locked()
                    self._cond.notify_all()
            return
        published = False
        with self._cond:
            if rec.state is JobState.RUNNING:
                rec.workdir = workdir
                rec.event_log = event_log
                rec.metrics = Metrics()
                rec.follow = runner
                published = True
                self._cond.notify_all()
        if not published:
            runner.close()
            if event_log is not None:
                event_log.close()
            return
        runner.start()  # standing: no completion watcher — the job runs
        # until cancel/stop (or an engine-build failure fails it)
        log.info(
            "follow job %s standing over %d inputs (poll %.3gs%s)",
            rec.job_id, len(cfg.input_files), runner.poll_s,
            ", resumed" if runner.resumed else "",
        )

    def _fail_follow_job(self, job_id: str, error: str) -> None:
        """Runner-thread callback: a standing query whose engine cannot
        build (bad pattern reaching the compile) fails like any job.
        Takes the service lock — the runner calls it with NO follow
        locks held (lock-order: service is never inner to follow)."""
        rec = self._jobs.get(job_id)
        if rec is None:
            return
        with self._cond:
            if rec.state is not JobState.RUNNING:
                return
            rec.state = JobState.FAILED
            rec.error = error
            rec.finished_at = time.time()
            _C_FAILED.inc()
            self._stage_state(rec)
            self._close_job_locked(rec)
            self._maybe_start_locked()
            self._cond.notify_all()
        self._flush_starts()
        self._flush_closes()
        self._flush_registry()
        self._flush_daemon_log()

    def _watch_job(self, rec: JobRecord) -> None:
        """Per-running-job completion watcher: finalize when the job's
        scheduler reports done; bail when the job left RUNNING some other
        way (cancel)."""
        while True:
            if rec.scheduler.wait_done(timeout=0.2):
                break
            with self._lock:
                if rec.state is not JobState.RUNNING:
                    return
        self._finalize(rec)

    def _finalize(self, rec: JobRecord) -> None:
        # the scheduler is done: every reduce is committed, so the output
        # listing is final — resolve it BEFORE taking the lock (store
        # resolution reads commit records; one job's finalize must not
        # stall every tenant's RPCs on that I/O).  Wasted work only if a
        # cancel races us, in which case the locked section discards it.
        t_fin = time.perf_counter()
        outputs = [str(p) for p in rec.workdir.list_outputs()]
        cache_error = ""
        if rec.result_plan is not None:
            # Query-result cache, still outside the lock (store I/O):
            # publish the freshly scanned splits' results — only now, at
            # finalize, when every reduce is committed (the fallback/
            # rescue discipline: a crashed job publishes nothing) — then
            # materialize the cached splits' blobs next to the scanned
            # outputs so the incremental re-query's result is complete.
            self._publish_results(rec, outputs)
            try:
                outputs = outputs + self._materialize_cached(rec)
            except OSError as e:
                # cached blobs we could not write = an INCOMPLETE result;
                # serving it as DONE would silently drop matches
                cache_error = f"result-cache materialization failed: {e}"
        _H_FINALIZE.observe(time.perf_counter() - t_fin)
        with self._cond:
            if rec.state is not JobState.RUNNING:
                return
            if cache_error:
                rec.state = JobState.FAILED
                rec.error = cache_error
                rec.finished_at = time.time()
                _C_FAILED.inc()
                self._stage_state(rec)
            else:
                rec.state = JobState.DONE
                rec.finished_at = time.time()
                rec.outputs = outputs
                _C_DONE.inc()
                if rec.submitted_at:
                    _H_JOB_E2E.observe(rec.finished_at - rec.submitted_at)
                if rec.started_at:
                    _H_JOB_RUN.observe(rec.finished_at - rec.started_at)
                self._stage_state(rec, outputs=outputs)
            self._close_job_locked(rec)
            self._maybe_start_locked()
            self._cond.notify_all()
        self._flush_starts()
        self._flush_closes()
        self._flush_registry()
        self._flush_daemon_log()
        log.info(
            "job %s %s in %.3fs (%d outputs)", rec.job_id, rec.state,
            rec.finished_at - (rec.started_at or rec.finished_at),
            len(rec.outputs),
        )

    def _close_job_locked(self, rec: JobRecord) -> None:
        # stop() is pure state + notify (no I/O); the file closes are
        # STAGED — flushing buffers under the service lock would stall
        # every tenant's RPCs on the work-dir disk (checked:
        # locked-blocking).
        if rec.scheduler is not None:
            rec.scheduler.stop()
        if rec.follow is not None:
            # pure state (Event.set): the wake loop exits at its next
            # check; the blocking teardown — thread join, log close,
            # subscriber wakeup — is staged below (locked-blocking)
            rec.follow.request_stop()
        if (rec.journal is not None or rec.event_log is not None
                or rec.follow is not None):
            self._pending_closes.append(
                (rec.scheduler, rec.journal, rec.event_log, rec.follow)
            )
        if rec.job_id in self._running:
            self._running.remove(rec.job_id)
        self._prune_terminal_locked()

    def _flush_closes(self) -> None:
        """Close staged journals/event logs outside the service lock.
        Journal closes route through Scheduler.close_journal — it drains
        that job's staged completions under the journal-flush lock before
        closing, so a finalize can never lose the last reduce_done entry
        to the close.  A late writer racing the event-log close is
        absorbed (EventLog drops writes on a closed file).  Never
        raises."""
        with self._lock:
            if not self._pending_closes:
                return
            pending, self._pending_closes = self._pending_closes, []
        for scheduler, journal, event_log, follow in pending:
            try:
                if follow is not None:
                    # stops the wake loop, wakes long-polling stream
                    # subscribers, closes the cursor log
                    follow.close()
                if scheduler is not None and journal is not None:
                    scheduler.close_journal()
                elif journal is not None:
                    journal.close()
                if event_log is not None:
                    event_log.close()
            except Exception:  # noqa: BLE001 — teardown must not fail RPCs
                log.exception("job teardown close failed")

    def _prune_terminal_locked(self) -> None:
        """Bound the job table over an unbounded stream: keep the newest
        _MAX_TERMINAL_RECORDS terminal records (status/result history),
        evict the rest oldest-finished-first.  Evicted job ids answer 404
        from then on — their committed outputs stay on disk under
        <work_root>/<job_id>/out/."""
        terminal = [r for r in self._jobs.values() if r.state in _TERMINAL]
        excess = len(terminal) - _MAX_TERMINAL_RECORDS
        if excess <= 0:
            return
        terminal.sort(key=lambda r: r.finished_at or 0.0)
        for rec in terminal[:excess]:
            del self._jobs[rec.job_id]
        if self._tokens:
            # keep the submit-token dedup map bounded with the table: a
            # token whose job was evicted answers like a fresh submit
            # (the job is 404 history either way)
            self._tokens = {t: j for t, j in self._tokens.items()
                            if j in self._jobs}

    # ---------------------------------------------------------------- cancel
    def cancel(self, job_id: str) -> str:
        """Cancel a queued or running job; terminal jobs are left as they
        are.  Running cancels stop the scheduler (workers mid-task finish
        their attempt; the completion RPC is absorbed idempotently) and
        never touch any OTHER job's state.  Returns the resulting state."""
        rec = self.record(job_id)
        with self._cond:
            if rec.state is JobState.QUEUED:
                self._queue.remove(job_id)
                rec.state = JobState.CANCELLED
                rec.finished_at = time.time()
                _C_CANCELLED.inc()
                self._stage_state(rec)
                # terminal without a close: bound the table here too (a
                # submit-then-cancel client loop never reaches _close)
                self._prune_terminal_locked()
            elif rec.state is JobState.RUNNING:
                rec.state = JobState.CANCELLED
                rec.finished_at = time.time()
                _C_CANCELLED.inc()
                self._stage_state(rec)
                self._close_job_locked(rec)
                self._maybe_start_locked()
            self._cond.notify_all()
        self._flush_starts()
        self._flush_closes()
        self._flush_registry()
        self._flush_daemon_log()
        log.info("job %s cancelled", job_id)
        return rec.state

    # ------------------------------------------------------------- accessors
    def record(self, job_id: str) -> JobRecord:
        rec = self._jobs.get(job_id)
        if rec is None:
            raise KeyError(f"unknown job: {job_id}")
        return rec

    def wait_job(self, job_id: str, timeout: float | None = None) -> bool:
        """Block until the job reaches a terminal state (tests/CLI)."""
        rec = self.record(job_id)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while rec.state not in _TERMINAL:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=0.2 if remaining is None
                                else min(0.2, remaining))
        return True

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def count_shuffle_bytes(self, direction: str, n_bytes: int) -> None:
        """Account one relay shuffle transfer through the daemon's HTTP
        data plane (direction: "relay_puts" | "relay_gets").  Called by
        the service handler per intermediate PUT/GET — with peer shuffle
        on, nothing calls it and the gauge stays at 0 (the receipt)."""
        with self._shuffle_lock:
            self._shuffle_stats["daemon_shuffle_bytes"] += int(n_bytes)
            if direction in self._shuffle_stats:
                self._shuffle_stats[direction] += 1

    def _worker_seen(self, worker_id: int, job: str | None = ...,
                     task: str | None = ..., metrics: dict | None = None,
                     data_endpoint: str | None = None) -> None:
        if worker_id < 0:
            return
        if metrics is not None:
            # rolling-rate feed, BEFORE the service lock (leaf metric
            # locks only, but there is no reason to hold the hot lock
            # over it).  "proc" is the worker's per-process source token
            # — consumed here, never stored into the /status rows.
            src = metrics.pop("proc", None)
            self._cache_rates.observe(
                src if src is not None else float(worker_id), metrics
            )
        with self._lock:
            info = self.workers.setdefault(
                worker_id, {"job": None, "task": None}
            )
            info["seen"] = time.monotonic()
            if job is not ...:
                info["job"] = job
            if task is not ...:
                info["task"] = task
            if metrics is not None:
                info["metrics"] = metrics
            if data_endpoint:
                # the worker's advertised peer-shuffle endpoint
                # (AssignTaskArgs.peer_endpoint): operators see who holds
                # spool state before draining a worker
                info["data_endpoint"] = data_endpoint

    # ---------------------------------------------------------- control plane
    def assign_task(self, args: rpc.AssignTaskArgs,
                    timeout: float = 30.0) -> rpc.AssignTaskReply:
        """Service-level long-poll: sweep the RUNNING jobs' schedulers
        round-robin (fairness across tenants) with non-blocking per-job
        polls; wait on the service condition between sweeps.  Replies
        carry job_id + application so one attached worker serves every
        job; JOB_DONE only on service shutdown — an idle service parks
        workers in retry long-polls, it does not dismiss them."""
        t0 = time.monotonic()
        try:
            return self._assign_task_inner(args, timeout)
        finally:
            # the OUTER poll wall only: the per-job scheduler sweeps
            # inside run with timeout=0 and observe nothing
            _H_SVC_ASSIGN_POLL.observe(time.monotonic() - t0)

    def _assign_task_inner(self, args: rpc.AssignTaskArgs,
                           timeout: float) -> rpc.AssignTaskReply:
        deadline = _Deadline(timeout)
        with self._lock:
            worker_id = args.worker_id
            if worker_id < 0 or worker_id not in self.workers:
                # fresh attach — or a reconnect across a daemon restart:
                # the new incarnation's table does not know the echoed id,
                # and honoring it could collide with this incarnation's
                # own allocations, so the worker gets a FRESH
                # service-allocated id (it adopts reply.worker_id).  The
                # row registers at allocation: identity exists from here,
                # not from the first completed RPC.  The skip-loop covers
                # rows a stale worker's task RPC re-created post-restart.
                while self._next_worker_id in self.workers:
                    self._next_worker_id += 1
                worker_id = self._next_worker_id
                self._next_worker_id += 1
                self.workers[worker_id] = {
                    "job": None, "task": None, "seen": time.monotonic(),
                }
                self._daemon_event("worker_attach", worker=worker_id)
                # an attach is the natural moment to drop rows (and
                # dedup sets) of workers long gone — attached-but-idle
                # workers refresh their row every long-poll retry, so
                # only the truly departed age past the expiry
                now = time.monotonic()
                stale = [
                    wid for wid, info in self.workers.items()
                    if now - info.get("seen", now) > _WORKER_EXPIRE_S
                ]
                for wid in stale:
                    del self.workers[wid]
                    self._daemon_event("worker_expire", worker=wid)
                if stale:
                    with self._span_seq_lock:
                        for wid in stale:
                            self._span_seqs.pop(wid, None)
        # a poll is evidence the worker is alive and NOT running a task
        # (single-threaded loops) — the lost-reply discriminator the
        # sweeper's quarantine attribution reads (WorkerHealth.saw)
        self._health.saw(worker_id)
        if getattr(args, "peer_endpoint", ""):
            # peer shuffle: every poll re-advertises the worker's data
            # endpoint (a reconnect under a fresh id re-registers it)
            self._worker_seen(worker_id, data_endpoint=args.peer_endpoint)
        while True:
            # Quarantined workers park here: no scheduler sweep, no
            # assignment — wait out the window (or the long-poll), then
            # answer retry with the re-probation hint so the worker backs
            # off client-side too (WorkerLoop sleeps on retry_after_s).
            quarantine_s = self._health.quarantine_remaining(worker_id)
            if quarantine_s > 0:
                remaining = deadline.remaining()
                if remaining <= 0:
                    self._worker_seen(worker_id)
                    return rpc.AssignTaskReply(
                        assignment="retry", task_id=-2, worker_id=worker_id,
                        retry_after_s=round(quarantine_s, 3),
                    )
                with self._cond:
                    if not self._stopped:
                        self._cond.wait(
                            min(remaining, quarantine_s, _ASSIGN_SWEEP_S)
                        )
            with self._lock:
                if self._stopped:
                    return rpc.AssignTaskReply(
                        assignment=rpc.Assignment.JOB_DONE,
                        worker_id=worker_id,
                    )
                if quarantine_s > 0:
                    continue  # re-check the quarantine clock first
                order = list(self._running)
                start = self._rr
                self._rr += 1
            for i in range(len(order)):
                rec = self._jobs.get(order[(start + i) % len(order)])
                if rec is None or rec.state is not JobState.RUNNING or (
                    rec.scheduler is None  # start staged, setup in flight
                ):
                    continue
                reply = rec.scheduler.assign_task(
                    rpc.AssignTaskArgs(worker_id=worker_id), timeout=0.0
                )
                if reply.assignment in (rpc.Assignment.MAP,
                                        rpc.Assignment.REDUCE):
                    reply.job_id = rec.job_id
                    reply.application = rec.config.application
                    if reply.assignment == rpc.Assignment.MAP:
                        # cross-tenant scan fusion: co-running jobs with
                        # an idle map task over the SAME content join
                        # this assignment (runs outside the service
                        # lock — claim + event-log writes are I/O-adjacent)
                        self._plan_fused_assignment(rec, reply, worker_id,
                                                    order)
                    self._worker_seen(
                        worker_id, job=rec.job_id,
                        task=f"{reply.assignment}:{reply.task_id}",
                    )
                    return reply
            remaining = deadline.remaining()
            if remaining <= 0:
                self._worker_seen(worker_id)
                return rpc.AssignTaskReply(
                    assignment="retry", task_id=-2, worker_id=worker_id
                )
            with self._cond:
                if not self._stopped:
                    self._cond.wait(min(remaining, _ASSIGN_SWEEP_S))

    @staticmethod
    def _fusion_plan(config: JobConfig, splits: list) -> tuple:
        """(fusion_key, split_identities, fuse_index) for a job —
        eligibility plus per-split content identity (runtime/fusion.py).
        Stat work: callers run it OUTSIDE the service lock, alongside
        plan_map_splits.  All-empty when fusion is disabled (the
        disabled daemon does not even pay the stats) or the job can
        never fuse."""
        if not fusion_mod.env_service_fuse():
            return None, [], {}
        key = fusion_mod.fusion_key(config)
        if key is None:
            return None, [], {}
        identities, index = fusion_mod.plan_identities(splits)
        return key, identities, index

    # ----------------------------------------------------- shard index
    def _index_app_dir(self, config: JobConfig) -> str | None:
        """The index persistence root to thread through the grep app's
        ``index_dir`` option, or None — index off (DGREP_INDEX=0 is a
        true no-op: no option injected, payloads byte-identical to the
        pre-index daemon), a non-grep application, or the submitter
        already chose a dir."""
        from distributed_grep_tpu.index.plan import GREP_APPLICATION
        from distributed_grep_tpu.index.summary import env_index_enabled

        if not env_index_enabled():
            return None
        if getattr(config, "application", None) != GREP_APPLICATION:
            return None
        if config.app_options.get("index_dir"):
            return None
        return str(self.work_root / "index")

    def _index_pruner(self, config: JobConfig):
        """A shard-index SplitPruner for this job's planning pass, or
        None (index.plan owns the gating: index off, unprunable
        semantics — invert/count/presence —, ineligible query).  The
        pruner consults the SAME store the job's workers publish to —
        the app-option ``index_dir`` when the submitter (or this
        daemon's injection) set one, else the daemon default — so
        planner and workers can never read/write different stores.  Its
        summary/store reads run at plan time in the caller, outside
        every service/scheduler lock (locked-blocking)."""
        from distributed_grep_tpu.index import plan as index_plan

        try:
            index_dir = (
                config.effective_app_options().get("index_dir")
                or self.work_root / "index"
            )
            return index_plan.pruner_for_job(config, index_dir)
        except Exception:  # noqa: BLE001 — a broken index must degrade
            # to unpruned planning, never take submits down
            log.exception("index pruner construction failed; "
                          "planning unpruned")
            return None

    def _stamp_index_plan(self, rec: JobRecord, pruner) -> None:
        """Fold one planning pass's prune tallies into the job's metrics
        (the /jobs/<id> view and dgrep submit's final JSON read them)
        and the service-level /status "index" counters."""
        if pruner is None or not (
            pruner.shards_pruned or pruner.maybe_scans
        ):
            return
        # onto the RECORD, not rec.metrics: the job's Metrics object is
        # built at start flush and would wipe a direct inc — the parts
        # builder seeds these fields into it instead
        rec.index_shards_pruned += pruner.shards_pruned
        rec.index_bytes_skipped += pruner.bytes_skipped
        with self._index_lock:
            self._index_stats["index_shards_pruned"] += pruner.shards_pruned
            self._index_stats["index_bytes_skipped"] += pruner.bytes_skipped
            self._index_stats["index_maybe_scans"] += pruner.maybe_scans
        # planner-side prunes feed the rolling window DIRECTLY (they are
        # per-plan deltas, not lifetime totals, and the pruned files
        # never reach a worker — the piggybacked engine-side counters
        # cannot double-count them)
        if pruner.shards_pruned:
            self._cache_rates.window.add(
                "index_shards_pruned", float(pruner.shards_pruned)
            )
            self._cache_rates.window.add(
                "index_bytes_skipped", float(pruner.bytes_skipped)
            )

    # ------------------------------------------------ query-result cache
    def _result_plan(self, config: JobConfig, splits: list):
        """A submit/resume-time ResultPlan for this job, or None — tier
        off (store None), ineligible config, or a lookup that broke.
        Store/stat I/O: callers run it OUTSIDE the service lock,
        alongside plan_map_splits (locked-blocking)."""
        if self._result_store is None or not splits:
            return None
        try:
            key = result_cache_mod.result_key(config)
            if key is None:
                return None
            return result_cache_mod.plan_lookup(
                self._result_store, key, splits
            )
        except Exception:  # noqa: BLE001 — a broken cache must degrade
            # to a plain scan, never take submits down
            log.exception("result-cache lookup failed; planning uncached")
            return None

    def _stamp_result_plan(self, rec: JobRecord) -> None:
        """Fold one result-cache planning pass into the record tallies
        (seeded into the job Metrics later — the _stamp_index_plan
        contract), the /status "result_cache" counters, and the
        dgrep_result_* metrics (created lazily at the event site: an
        idle daemon's /metrics keeps its golden bytes).  PARTIAL hits
        only: a full hit stamps in _flush_result_hit AFTER its cached
        blobs materialize — the materialization-failure fallback
        rescans, and counters stamped at plan time would over-count
        /status and /metrics forever."""
        plan = rec.result_plan
        if plan is None or not plan.cached or plan.full:
            return
        self._stamp_result_counters(rec, plan)

    def _stamp_result_counters(self, rec: JobRecord, plan) -> None:
        rec.result_splits_reused += plan.splits_reused
        rec.result_bytes_unscanned += plan.bytes_unscanned
        full = plan.full
        with self._result_lock:
            if full:
                self._result_stats["result_hits"] += 1
            else:
                self._result_stats["result_partial_hits"] += 1
            self._result_stats["result_splits_reused"] += plan.splits_reused
            self._result_stats["result_bytes_unscanned"] += (
                plan.bytes_unscanned
            )
        if full:
            metrics_mod.counter("dgrep_result_hits_total").inc()
        else:
            metrics_mod.counter("dgrep_result_partial_hits_total").inc()
        metrics_mod.counter("dgrep_result_splits_reused_total").inc(
            plan.splits_reused
        )
        metrics_mod.counter("dgrep_result_bytes_unscanned_total").inc(
            plan.bytes_unscanned
        )

    @staticmethod
    def _materialize_cached(rec: JobRecord) -> list[str]:
        """Write the plan's cached split blobs under the job's work dir
        (``out-cached/result-<i>`` — deliberately NOT mr-*, which
        readers must resolve through the store) and return their paths.
        Result consumers read output paths directly, and each blob is
        itself (file, line)-sorted, so the k-way ``fileline_sorted``
        merge over scanned + cached outputs is byte-identical to a full
        scan.  Raises OSError — the caller decides whether that fails
        the job."""
        plan = rec.result_plan
        if not plan.cached:
            return []
        out_dir = rec.workdir.root / "out-cached"
        out_dir.mkdir(parents=True, exist_ok=True)
        paths = []
        for i, blob in plan.cached:
            p = out_dir / f"result-{i}"
            with open(p, "wb") as f:
                f.write(blob)
            paths.append(str(p))
        return paths

    def _flush_result_hit(self, rec: JobRecord) -> bool:
        """Complete a FULL result-cache hit (start-flush context, no
        service lock held): fresh work dir, cached blobs materialized as
        the job's outputs, DONE published under the lock with
        _finalize's accounting — no scheduler, no watcher thread.
        Returns False on any failure; the caller falls back to the
        normal scan path with the plan dropped."""
        cfg = rec.config
        event_log = None
        try:
            store = make_store(cfg.store)
            workdir = WorkDir(cfg.work_dir, store=store)
            workdir.clear()
            rec.workdir = workdir  # _materialize_cached reads it
            outputs = self._materialize_cached(rec)
            spans_on = spans_mod.enabled(cfg.spans) or self.spans
            if spans_on:
                # one-instant event log: dgrep explain's verdict for a
                # job no worker ever touched — closed right here (the
                # record never publishes it, so no staged close)
                event_log = spans_mod.EventLog(
                    workdir.root / spans_mod.EventLog.FILENAME, fresh=True
                )
                event_log.write({
                    "t": "instant", "name": "result:hit",
                    "cat": "service", "ts": time.time(), "job": rec.job_id,
                    "args": {
                        "splits_reused": rec.result_plan.splits_reused,
                        "bytes_unscanned": rec.result_plan.bytes_unscanned,
                    },
                })
                event_log.close()
                event_log = None
        except Exception:  # noqa: BLE001 — fall back to a real scan
            log.exception("job %s result-cache hit failed; rescanning",
                          rec.job_id)
            if event_log is not None:
                try:
                    event_log.close()
                except Exception:  # noqa: BLE001 — teardown must not raise
                    log.exception("event log close failed for %s",
                                  rec.job_id)
            rec.workdir = None
            return False
        # counters stamp only now, with the cached blobs materialized —
        # the _stamp_result_plan contract: a fallback rescan must leave
        # /status and /metrics untouched
        self._stamp_result_counters(rec, rec.result_plan)
        metrics = Metrics()
        metrics.inc("result_splits_reused", rec.result_splits_reused)
        metrics.inc("result_bytes_unscanned", rec.result_bytes_unscanned)
        if rec.index_shards_pruned:
            metrics.inc("index_shards_pruned", rec.index_shards_pruned)
            metrics.inc("index_bytes_skipped", rec.index_bytes_skipped)
        with self._cond:
            if rec.state is not JobState.RUNNING:
                # cancel/stop won the race: its terminal state stands,
                # the materialized outputs are discarded history
                return True
            rec.metrics = metrics
            rec.input_allowlist = frozenset(cfg.input_files)
            rec.state = JobState.DONE
            rec.finished_at = time.time()
            rec.outputs = outputs
            _C_DONE.inc()
            if rec.submitted_at:
                _H_JOB_E2E.observe(rec.finished_at - rec.submitted_at)
            if rec.started_at:
                _H_JOB_RUN.observe(rec.finished_at - rec.started_at)
            self._stage_state(rec, outputs=outputs)
            self._close_job_locked(rec)
            self._maybe_start_locked()
            self._cond.notify_all()
        # staged registry records flush at every caller's post-release
        # tail (_flush_starts callers all flush the registry) — flushing
        # here would nest registry-flush under start-flush (lock-order)
        log.info(
            "job %s done from result cache (%d splits, %d bytes unscanned)",
            rec.job_id, rec.result_plan.splits_reused,
            rec.result_plan.bytes_unscanned,
        )
        return True

    def _publish_results(self, rec: JobRecord,
                         fresh_outputs: list[str]) -> None:
        """Publish the freshly scanned splits' per-split results — at
        finalize ONLY, after every reduce committed (a failed/crashed
        job publishes nothing: the chaos pin).  Each split's submit-time
        identity is REVALIDATED with a fresh stat first — a member that
        drifted while the job ran is skipped, its entry would be stale
        the moment it landed.  Record attribution is all-or-nothing
        (bucket_records): custom record shapes publish nothing.  Never
        raises — publication is best-effort warm-up, not correctness."""
        plan = rec.result_plan
        if self._result_store is None or plan is None or not plan.remaining:
            return
        try:
            buckets = result_cache_mod.bucket_records(
                fresh_outputs, plan.remaining
            )
            if buckets is None:
                return
            revalidated = 0
            for split, ident, blob in zip(
                plan.remaining, plan.remaining_identities, buckets
            ):
                if ident is None:
                    # unstattable/oversize at plan time: never cached
                    continue
                if fusion_mod.split_identity(split) != ident:
                    revalidated += 1
                    if rec.event_log is not None:
                        members = (split if isinstance(split, (list, tuple))
                                   else [split])
                        rec.event_log.write({
                            "t": "instant", "name": "result:revalidate",
                            "cat": "service", "ts": time.time(),
                            "job": rec.job_id,
                            "args": {"split": [str(m) for m in members]},
                        })
                    continue
                self._result_store.save(
                    result_cache_mod.ResultKey(plan.query_key, split, ident),
                    blob,
                )
            if revalidated:
                rec.result_revalidations += revalidated
                rec.metrics.inc("result_revalidations", revalidated)
                with self._result_lock:
                    self._result_stats["result_revalidations"] += revalidated
        except Exception:  # noqa: BLE001 — best-effort, see docstring
            log.exception("job %s result publication failed", rec.job_id)

    def _plan_fused_assignment(self, rec: JobRecord,
                               reply: rpc.AssignTaskReply, worker_id: int,
                               order: list[str]) -> None:
        """Attach co-tenant map tasks to a MAP assignment: every OTHER
        running job with the same fusion key and an idle first-attempt
        map task over the same split content claims its task onto this
        reply (Scheduler.claim_map_task), so ONE worker scan serves all
        of them.  Runs with NO service lock held — unlocked job-table
        reads follow the assign loop's existing precedent, claims take
        only the target scheduler's own lock, and event-log writes are
        plain file appends.  A fused attempt that later dies simply
        times out per job and re-runs solo (claim gates on attempts==0).
        No-op when fusion is off — the reply (and its wire form) is then
        byte-identical to the pre-fusion protocol."""
        if rec.fusion_key is None or not fusion_mod.env_service_fuse():
            return
        idents = rec.split_identities
        tid = reply.task_id
        ident = idents[tid] if 0 <= tid < len(idents) else None
        if ident is None:
            return
        # FRESH revalidation, the corpus cache's contract (stale bytes
        # are never served): identities were captured at submit, and a
        # path can stop resolving to the same content before the scan —
        # an atomic deploy flip retargets a symlink, an append moves
        # mtime.  A drifted primary fuses nothing; a drifted co-tenant
        # is skipped (its task runs solo over ITS OWN current paths).
        # Stat work — this method runs with no service lock held.
        if fusion_mod.split_identity(rec.map_splits[tid]) != ident:
            return
        cap = fusion_mod.env_fuse_max_queries()
        planned: list[dict] = []
        for jid2 in order:
            if len(planned) + 1 >= cap:
                break
            if jid2 == rec.job_id:
                continue
            rec2 = self._jobs.get(jid2)
            if (rec2 is None or rec2.state is not JobState.RUNNING
                    or rec2.scheduler is None
                    or rec2.fusion_key != rec.fusion_key):
                continue
            tid2 = rec2.fuse_index.get(ident)
            if tid2 is None:
                continue
            # the co-tenant's OWN paths must still resolve to this
            # content too (they may reach it through a different route)
            if fusion_mod.split_identity(rec2.map_splits[tid2]) != ident:
                continue
            info = rec2.scheduler.claim_map_task(tid2, worker_id)
            if info is None:
                continue
            planned.append({"job_id": rec2.job_id, **info})
        if not planned:
            return
        reply.fused = planned
        n_bytes = fusion_mod.split_n_bytes(ident)
        with self._fusion_lock:
            self._fusion_stats["fused_jobs"] += 1 + len(planned)
            self._fusion_stats["fused_dispatches"] += 1
            self._fusion_stats["fusion_bytes_saved"] += (
                len(planned) * n_bytes
            )
        # fuse:plan instant in EACH participant's events.jsonl — every
        # fused tenant's trace shows the shared attempt (split_by_job
        # routes worker-side fuse:split records the same way)
        parts = [(rec.job_id, tid)] + [
            (p["job_id"], p["task_id"]) for p in planned
        ]
        now = time.time()
        for jid_p, tid_p in parts:
            r = self._jobs.get(jid_p)
            if r is None or r.event_log is None:
                continue
            try:
                r.event_log.write({
                    "t": "instant", "name": "fuse:plan", "cat": "fuse",
                    "ts": now, "job": jid_p,
                    "args": {
                        "task": tid_p, "queries": len(parts),
                        "worker": worker_id, "bytes": n_bytes,
                        "participants": [j for j, _ in parts],
                    },
                })
            except Exception:  # noqa: BLE001 — telemetry must not fail assigns
                log.exception("fuse:plan event write failed for %s", jid_p)
        log.info(
            "fused map assignment: %d queries share task %s:%d (worker %d,"
            " %d bytes scanned once)", len(parts), rec.job_id, tid,
            worker_id, n_bytes,
        )

    def _route_spans(self, args) -> None:
        """Service-level span persistence: dedup the batch by (worker,
        seq) BEFORE splitting — one drained batch may carry records from
        several jobs' attempts (the buffer flushes on whatever RPC goes
        next) — then write each record group to ITS job's event log.
        Consumes args.spans so the per-job scheduler cannot double-write
        the batch into the RPC's own job log."""
        recs = getattr(args, "spans", None)
        if not recs:
            return
        args.spans = []
        seq = getattr(args, "spans_seq", -1)
        wid = getattr(args, "worker_id", -1)
        if seq >= 0 and wid >= 0:
            with self._span_seq_lock:
                seen = self._span_seqs.setdefault(wid, set())
                if seq in seen:
                    return
                seen.add(seq)
                # seqs are monotonic per worker buffer: a transport retry
                # replays a RECENT seq, never one thousands back — prune
                # to a recency window so a long-lived worker's dedup set
                # stays bounded
                if len(seen) > 2 * _SPAN_SEQ_WINDOW:
                    floor = max(seen) - _SPAN_SEQ_WINDOW
                    self._span_seqs[wid] = {s for s in seen if s >= floor}
        for jid, group in spans_mod.split_by_job(
            recs, default=getattr(args, "job_id", "")
        ).items():
            rec = self._jobs.get(jid)
            if rec is None or rec.event_log is None:
                continue  # job unknown/terminal or spans off: drop
            try:
                rec.event_log.write_many(group)
            except Exception:  # noqa: BLE001 — telemetry must not fail RPCs
                log.exception("event log write failed for job %s", jid)

    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        self._route_spans(args)
        self._worker_seen(args.worker_id, task=None, metrics=args.metrics)
        rec = self._jobs.get(args.job_id)
        if rec is None or rec.scheduler is None:
            return rpc.TaskFinishedReply(ok=False)  # job gone: absorbed
        return rec.scheduler.map_finished(args)

    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        self._route_spans(args)
        self._worker_seen(args.worker_id, task=None, metrics=args.metrics)
        rec = self._jobs.get(args.job_id)
        if rec is None or rec.scheduler is None:
            return rpc.TaskFinishedReply(ok=False)
        return rec.scheduler.reduce_finished(args)

    def reduce_next_file(self, args: rpc.ReduceNextFileArgs,
                         timeout: float = 30.0) -> rpc.ReduceNextFileReply:
        rec = self._jobs.get(args.job_id)
        if rec is None or rec.scheduler is None or (
            rec.state is not JobState.RUNNING
        ):
            # Job finalized/cancelled/gone mid-reduce: ABORT the attempt.
            # Answering done=True here (the pre-round-17 behavior) let a
            # LATE DUPLICATE reduce attempt — spawned by timeout churn,
            # still mid-shuffle when the first attempt finalized the job —
            # treat its partial cursor as complete and rename a SHORT
            # output over the finalized job's committed file (posix
            # rename-last-wins; caught by the chaos matrix as a
            # byte-identity failure).  TaskAborted walks the worker away
            # with NO commit record and no rename.
            return rpc.ReduceNextFileReply(abort=True)
        return rec.scheduler.reduce_next_file(args, timeout=timeout)

    def heartbeat(self, args: rpc.HeartbeatArgs) -> None:
        self._route_spans(args)
        self._worker_seen(args.worker_id, metrics=args.metrics)
        rec = self._jobs.get(args.job_id)
        if rec is not None and rec.scheduler is not None:
            rec.scheduler.heartbeat(
                args.task_type, args.task_id, grace_s=args.grace_s, args=args
            )

    # ----------------------------------------------------------------- status
    def job_status(self, job_id: str) -> dict:
        rec = self.record(job_id)
        out: dict = {
            "job_id": rec.job_id,
            "state": rec.state,
            "submitted_at": rec.submitted_at,
            "started_at": rec.started_at,
            "finished_at": rec.finished_at,
        }
        if rec.error:
            out["error"] = rec.error
        if rec.scheduler is not None:
            s = rec.scheduler
            out["map"] = {
                "total": len(s.map_tasks),
                "completed": sum(
                    t.state is TaskState.COMPLETED for t in s.map_tasks
                ),
            }
            out["reduce"] = {
                "total": len(s.reduce_tasks),
                "completed": sum(
                    t.state is TaskState.COMPLETED for t in s.reduce_tasks
                ),
            }
            out["metrics"] = rec.metrics.snapshot()
        elif rec.follow is None and rec.state is JobState.DONE:
            # a FULL result-cache hit completes with no scheduler — its
            # Metrics (result_splits_reused / result_bytes_unscanned)
            # must still reach GET /jobs/<id>, the submit client's one
            # counter source; nonzero-only so queued/terminal jobs with
            # empty Metrics keep the scheduler-gated payload shape
            snap = rec.metrics.snapshot()
            if snap.get("counters"):
                out["metrics"] = snap
        if rec.follow is not None:
            # standing query: wake/cursor/stream state instead of phase
            # progress (nonzero-only gate not needed — the key only
            # exists on follow jobs, so batch payloads keep their shape)
            out["follow"] = rec.follow.status()
        if rec.state is JobState.DONE:
            out["outputs"] = rec.outputs
        return out

    def job_stream(self, job_id: str, cursor: int = 0,
                   timeout: float = 25.0) -> dict:
        """Long-poll one page of a standing query's record stream
        (GET /jobs/<id>/stream?cursor=N): records with seq > cursor (each
        carries its seq — the client passes the reply's ``next`` back),
        plus an explicit ``dropped`` count when the subscriber fell
        behind the bounded ring (oldest-first shed — the records are
        gone from the ring; the full history stays in follow.jsonl).
        Raises RuntimeError for non-follow jobs (HTTP answers 409).
        A terminal follow job drains its remaining ring, then answers
        empty pages with its state — clients stop on it."""
        rec = self.record(job_id)
        runner = rec.follow
        if runner is None:
            if getattr(rec.config, "follow", False):
                # queued (admission-full) or start flush in flight: the
                # runner is not published yet — an empty page with the
                # state, not a 409 (the subscriber simply polls again).
                # A waiting client is PACED (no ring to long-poll on, so
                # an immediate empty answer would let it hot-spin against
                # a daemon that may be busy replaying the cursor log);
                # no lock is held here.
                if timeout > 0:
                    time.sleep(min(timeout, 0.5))
                return {"job_id": job_id, "state": rec.state,
                        "records": [], "next": max(0, int(cursor))}
            raise RuntimeError(f"job {job_id} is not a follow job")
        if rec.state is not JobState.RUNNING:
            timeout = 0.0  # terminal: drain, never park the client
        records, nxt, dropped = runner.ring.read_since(
            cursor, timeout=max(0.0, min(timeout, 60.0))
        )
        out: dict = {
            "job_id": job_id,
            "state": rec.state,
            "records": records,
            "next": nxt,
        }
        if dropped:
            out["dropped"] = dropped
            # stream-ring shed: the subscriber fell behind the bounded
            # buffer — a fleet-timeline event (no lock held here, so
            # stage + flush directly)
            self._daemon_event("stream_shed", job=job_id, dropped=dropped)
            self._flush_daemon_log()
        return out

    def job_result(self, job_id: str) -> dict:
        """Committed outputs + final metrics of a DONE job; raises
        RuntimeError for non-terminal jobs (HTTP surface answers 409)."""
        rec = self.record(job_id)
        if rec.state is not JobState.DONE:
            raise RuntimeError(
                f"job {job_id} has no result: state={rec.state}"
            )
        return {
            "job_id": rec.job_id,
            "state": rec.state,
            "outputs": rec.outputs,
            "metrics": rec.metrics.snapshot(),
        }

    def status(self) -> dict:
        """Service-level view: queue depth, running jobs, per-job progress,
        the service worker table (with piggybacked engine metrics — the
        compile_cache_* / corpus_cache_* counters land here via the
        heartbeat piggyback), and this process's own compiled-model and
        device-corpus cache counters (authoritative for in-process
        workers; HTTP workers report theirs per row).  The cache modules
        are sys.modules-gated like the worker piggyback
        (worker._engine_cache_counters): a daemon whose workers are all
        REMOTE never builds an engine, and its first /status must not
        import the whole ops stack (jax included) just to report two
        empty dicts."""
        import sys as _sys

        eng = _sys.modules.get("distributed_grep_tpu.ops.engine")
        lay = _sys.modules.get("distributed_grep_tpu.ops.layout")
        model_cache_counters = (
            eng.model_cache_counters if eng is not None else dict
        )
        corpus_cache_counters = (
            lay.corpus_cache_counters if lay is not None else dict
        )

        now = time.monotonic()
        quarantine = self._health.snapshot()
        with self._shuffle_lock:
            # nonzero-only: a daemon whose shuffle never transited its
            # data plane (pure peer, or no HTTP workers) keeps the exact
            # pre-peer /status shape
            shuffle_stats = (
                dict(self._shuffle_stats)
                if any(self._shuffle_stats.values()) else {}
            )
        with self._fusion_lock:
            # nonzero-only, like the cache counter dicts: a fusion-free
            # (or fusion-disabled) daemon's /status keeps its exact
            # pre-fusion shape
            fusion_stats = (
                dict(self._fusion_stats)
                if any(self._fusion_stats.values()) else {}
            )
        with self._index_lock:
            # planner-side shard-index counters, same nonzero-only
            # contract (DGREP_INDEX=0 — or a never-pruning corpus —
            # keeps the pre-index /status shape); engine-side counters
            # ride the per-worker heartbeat piggyback rows
            index_stats = (
                dict(self._index_stats)
                if any(self._index_stats.values()) else {}
            )
        with self._result_lock:
            # query-result cache planner counters (round 20), same
            # nonzero-only contract: DGREP_RESULT_CACHE=0 — or a daemon
            # that never hit — keeps the pre-result /status shape
            result_stats = (
                dict(self._result_stats)
                if any(self._result_stats.values()) else {}
            )
        if self._result_store is not None:
            # store-side eviction telemetry (lockless approximate
            # reads), gated on its OWN nonzero-ness: a daemon that
            # published and evicted but never yet hit must still
            # surface it (the nonzero-only /status contract holds —
            # all-zero still omits the result_cache key)
            if self._result_store.stale_evictions:
                result_stats["result_stale_evictions"] = (
                    self._result_store.stale_evictions
                )
            if self._result_store.lru_evictions:
                result_stats["result_lru_evictions"] = (
                    self._result_store.lru_evictions
                )
        with self._lock:
            jobs = {
                jid: {"state": rec.state}
                for jid, rec in self._jobs.items()
            }
            queued = len(self._queue)
            running = list(self._running)
            standing = [
                rec.job_id for rec in self._jobs.values()
                if rec.state is JobState.RUNNING and rec.follow is not None
            ]
            tasks_requeued = sum(
                rec.metrics.counters.get("tasks_requeued", 0)
                for rec in self._jobs.values()
            )
            maps_lost = sum(
                rec.metrics.counters.get("maps_lost_output", 0)
                for rec in self._jobs.values()
            )
            workers = {}
            for wid, info in sorted(self.workers.items()):
                row: dict = {
                    "last_heartbeat_age_s": round(now - info["seen"], 3),
                    # the freshness signal scale_advice gates capacity on
                    # (_SCALE_FRESH_S compares this same age) — exposed
                    # so `dgrep top` and operators read what the advisor
                    # reads instead of inferring it
                    "last_event_age_s": round(now - info["seen"], 3),
                    "job": info.get("job"),
                    "task": info.get("task"),
                }
                if info.get("metrics") is not None:
                    row["metrics"] = info["metrics"]
                if info.get("data_endpoint"):
                    # peer shuffle: who holds spool state (spool size
                    # rides the metrics row as peer_spool_bytes)
                    row["data_endpoint"] = info["data_endpoint"]
                if str(wid) in quarantine["active"]:
                    row["quarantined_s"] = quarantine["active"][str(wid)]
                workers[str(wid)] = row
        if maps_lost:
            # lost peer outputs recovered by map re-execution — part of
            # the shuffle story, so it rides (and un-gates) the same view
            shuffle_stats["maps_lost_output"] = int(maps_lost)
        # elastic scale signal (round 16): queue-depth / pending-task /
        # in-flight-age derived advice — computed outside the service
        # lock (it takes the running schedulers' own locks).  Gated on a
        # non-idle daemon so an empty /status keeps its pre-peer shape.
        scale = (
            self.scale_advice()
            if (queued or running or workers) else {}
        )
        # Streaming tier (round 17): standing-query view — nonzero-only
        # (a follow-free daemon keeps the exact pre-follow /status shape),
        # sys.modules-gated like the cache dicts so a batch daemon never
        # imports the follow module just to report nothing.
        fol = _sys.modules.get("distributed_grep_tpu.runtime.follow")
        follow_view: dict = {}
        if standing or (fol is not None and fol.follow_counters()):
            follow_view = {"standing": len(standing)}
            if standing:
                follow_view["jobs"] = standing
            if fol is not None:
                follow_view.update(fol.follow_counters())
        # fused follow tier (round 21): group rows (members, shared
        # cursor bytes, cadence, wake lag) + the fused counters —
        # nonzero-only, and ALWAYS absent when DGREP_FOLLOW_FUSE=0 (the
        # registry is then never built: the no-op /status pin).  Group
        # status snapshots membership under the registry's own leaf
        # lock — computed outside the service lock like the rest.
        if fol is not None and follow_view:
            follow_view.update(fol.follow_fused_counters())
            groups_reg = self._follow_groups
            if groups_reg is not None:
                group_rows = groups_reg.status_rows()
                if group_rows:
                    follow_view["groups"] = group_rows
        for jid in jobs:
            rec = self._jobs.get(jid)  # pruning may race this unlocked read
            if rec is not None and rec.scheduler is not None:
                jobs[jid]["map_completed"] = sum(
                    t.state is TaskState.COMPLETED
                    for t in rec.scheduler.map_tasks
                )
                jobs[jid]["map_total"] = len(rec.scheduler.map_tasks)
        # compact latency summary from the round-15 histograms — health
        # without a Prometheus scraper.  Nonzero-only: a daemon that has
        # recorded nothing keeps the exact pre-metrics /status shape.
        latency: dict = {}
        for key, hist in (("queue_wait_s", _H_QUEUE_WAIT),
                          ("job_e2e_s", _H_JOB_E2E)):
            p50 = hist.quantile(0.5)
            if p50 is None:
                continue
            p95 = hist.quantile(0.95)
            latency[key] = {
                "p50": round(p50, 6),
                "p95": round(p95 if p95 is not None else p50, 6),
                "count": hist.snapshot()[2],
            }
        return {
            "service": True,
            # HA role advertisement (round 18): present ONLY when a lease
            # is attached — single-daemon /status keeps its exact
            # pre-lease shape (golden-pinned).  Workers and clients read
            # it to distinguish the active from a parked standby.
            **({"role": "deposed" if self._deposed else "active"}
               if self._lease is not None else {}),
            # peer-shuffle capability advertisement (round 16): a NEW
            # worker only sends AssignTaskArgs.peer_endpoint (and starts
            # its data server) when the daemon it attached to answers
            # True here — a pre-peer daemon's AssignTaskArgs(**payload)
            # would TypeError on the unknown key, so with the knob
            # default-ON the worker must not assume support (the elide
            # contract's "only fails when actually switched on", kept).
            # Nonzero-only: DGREP_PEER_SHUFFLE=0 keeps the pre-peer
            # /status shape byte for byte.
            **({"peer": True} if env_peer_shuffle() else {}),
            "uptime_s": round(time.time() - self.started_at, 3),
            "max_jobs": self.max_jobs,
            "queue_depth_cap": self.queue_depth,
            "queued": queued,
            "running": running,
            "jobs": jobs,
            "workers": workers,
            # robustness counters (round 10): requeued-task total across
            # the retained jobs, plus the quarantine tracker's view
            # (episodes ever entered + currently parked workers)
            "tasks_requeued": tasks_requeued,
            "workers_quarantined": quarantine["quarantined_total"],
            "quarantine": quarantine["active"],
            "compile_cache": model_cache_counters(),
            "corpus_cache": corpus_cache_counters(),
            # cross-tenant scan fusion (round 13): planning-side counters
            # (fused_jobs / fused_dispatches / fusion_bytes_saved);
            # engine-side counters ride the per-worker heartbeat
            # piggyback rows (runtime/worker._engine_cache_counters)
            **({"fusion": fusion_stats} if fusion_stats else {}),
            # shard-index routing (planner side): shards never dispatched
            # because their trigram summary ruled the query out
            **({"index": index_stats} if index_stats else {}),
            # query-result cache (round 20): jobs answered from stored
            # results — full hits, incremental re-queries, splits/bytes
            # served without a scan, drift-dropped publications
            **({"result_cache": result_stats} if result_stats else {}),
            # streaming tier (round 17): standing queries + the follow
            # wake/suffix/shed counters (nonzero-only — a follow-free
            # daemon keeps the exact pre-follow /status shape)
            **({"follow": follow_view} if follow_view else {}),
            # peer-to-peer shuffle (round 16): relay bytes that transited
            # THIS daemon's data plane (~0 with peer shuffle on) + lost
            # peer outputs recovered by map re-execution
            **({"shuffle": shuffle_stats} if shuffle_stats else {}),
            # elastic scale advice (grow/shrink/hold + the inputs it was
            # derived from) — `dgrep serve --max-workers` follows it
            **({"scale": scale} if scale else {}),
            # p50/p95 from the round-15 lifecycle histograms (GET /metrics
            # carries the full bucket vectors)
            **({"latency": latency} if latency else {}),
        }

    # ---------------------------------------------------------- /metrics
    def metrics_text(self) -> str:
        """Prometheus text exposition (GET /metrics): the process-global
        typed instruments, plus scrape-time gauges for the live scale
        signal (queue depth / running / worker count), lifetime cache
        totals, and the rolling-window cache rates.  The cache modules
        are sys.modules-gated like status(); no I/O and no jax under any
        lock (plain list lengths read under the service lock, module
        counters and rendering outside it)."""
        import sys as _sys

        with self._lock:
            queued = len(self._queue)
            running = len(self._running)
            workers = len(self.workers)
            standing = sum(
                1 for rec in self._jobs.values()
                if rec.state is JobState.RUNNING and rec.follow is not None
            )
        metrics_mod.gauge("dgrep_queue_depth").set(queued)
        metrics_mod.gauge("dgrep_jobs_running").set(running)
        metrics_mod.gauge("dgrep_workers_attached").set(workers)

        # Streaming tier (round 17): follow gauges are touched only when
        # the tier has activity — an untouched instrument never renders,
        # so a follow-free daemon's /metrics stays byte-identical to the
        # round-15 exposition (the golden pin).  Explicit string-constant
        # creation sites (metrics-registry rule).
        fol = _sys.modules.get("distributed_grep_tpu.runtime.follow")
        fc = fol.follow_counters() if fol is not None else {}
        if standing or fc:
            metrics_mod.gauge("dgrep_follow_standing").set(standing)
            metrics_mod.gauge("dgrep_follow_wakes").set(
                fc.get("follow_wakes", 0))
            metrics_mod.gauge("dgrep_follow_suffix_bytes").set(
                fc.get("suffix_bytes_scanned", 0))
            metrics_mod.gauge("dgrep_stream_dropped_records").set(
                fc.get("stream_dropped_records", 0))

        counters: dict = {}
        eng = _sys.modules.get("distributed_grep_tpu.ops.engine")
        if eng is not None:
            counters.update(eng.model_cache_counters())
        lay = _sys.modules.get("distributed_grep_tpu.ops.layout")
        if lay is not None:
            counters.update(lay.corpus_cache_counters())
        fuse = _sys.modules.get("distributed_grep_tpu.ops.fuse")
        if fuse is not None:
            counters.update(fuse.fusion_counters())
        idx = _sys.modules.get("distributed_grep_tpu.index.summary")
        if idx is not None:
            counters.update(idx.index_counters())
        if counters:
            # this process's own counters feed the SAME tracker the
            # piggybacks feed, under the same PROC_TOKEN — in-process
            # worker loops and scrape-time reads dedup to one source
            self._cache_rates.observe(metrics_mod.PROC_TOKEN, counters)
        # explicit string-constant creation sites, one per series: the
        # `metrics-registry` rule audits names lexically, so the names
        # stay greppable and un-aliased here on purpose
        def _c(name: str) -> float:
            return float(counters.get(name, 0))

        metrics_mod.gauge("dgrep_model_cache_hits").set(
            _c("compile_cache_hits"))
        metrics_mod.gauge("dgrep_model_cache_misses").set(
            _c("compile_cache_misses"))
        metrics_mod.gauge("dgrep_corpus_cache_hits").set(
            _c("corpus_cache_hits"))
        metrics_mod.gauge("dgrep_corpus_cache_misses").set(
            _c("corpus_cache_misses"))
        metrics_mod.gauge("dgrep_corpus_cache_bytes_resident").set(
            _c("corpus_cache_bytes_resident"))

        with self._shuffle_lock:
            shuffle_bytes = self._shuffle_stats["daemon_shuffle_bytes"]
        # the P2P receipt gauge: intermediate bytes that transited this
        # daemon's data plane — ~0 with peer shuffle on
        metrics_mod.gauge("dgrep_daemon_shuffle_bytes").set(shuffle_bytes)

        w = self._cache_rates.window_totals()
        metrics_mod.gauge("dgrep_window_model_cache_hits").set(
            w.get("compile_cache_hits", 0.0))
        metrics_mod.gauge("dgrep_window_model_cache_misses").set(
            w.get("compile_cache_misses", 0.0))
        metrics_mod.gauge("dgrep_window_corpus_cache_hits").set(
            w.get("corpus_cache_hits", 0.0))
        metrics_mod.gauge("dgrep_window_corpus_cache_misses").set(
            w.get("corpus_cache_misses", 0.0))
        metrics_mod.gauge("dgrep_window_index_shards_pruned").set(
            w.get("index_shards_pruned", 0.0))
        metrics_mod.gauge("dgrep_window_index_bytes_skipped").set(
            w.get("index_bytes_skipped", 0.0))
        metrics_mod.gauge("dgrep_window_fused_queries").set(
            w.get("fused_queries", 0.0))
        metrics_mod.gauge("dgrep_window_fusion_bytes_saved").set(
            w.get("fusion_bytes_saved", 0.0))

        def _ratio(hits: float, misses: float) -> float:
            total = hits + misses
            return hits / total if total else 0.0

        metrics_mod.gauge("dgrep_model_cache_hit_ratio").set(_ratio(
            w.get("compile_cache_hits", 0.0),
            w.get("compile_cache_misses", 0.0)))
        metrics_mod.gauge("dgrep_corpus_cache_hit_ratio").set(_ratio(
            w.get("corpus_cache_hits", 0.0),
            w.get("corpus_cache_misses", 0.0)))

        if self._lease is not None:
            # HA role SLO gauge (round 19): touched only when a lease is
            # attached, so non-HA daemons keep the round-15 golden
            # exposition bytes (same contract as the follow gauges)
            metrics_mod.gauge("dgrep_daemon_role").set(
                0 if self._deposed else 1)
        return metrics_mod.render_prometheus()

    # ----------------------------------------------------------- explain
    def job_explain(self, job_id: str) -> dict:
        """Per-query routing report for one job (``dgrep explain``):
        events.jsonl aggregation + the record's planning tallies, one
        JSON-ready dict.  Reads the job's event log OUTSIDE every lock
        (record() only locks the table lookup)."""
        from distributed_grep_tpu.runtime import explain as explain_mod

        rec = self.record(job_id)
        events: list = []
        workdir = rec.workdir
        if workdir is not None:
            path = workdir.root / spans_mod.EventLog.FILENAME
            if path.exists():
                events = spans_mod.EventLog.read(path)
        daemon_events = None
        if self._daemon_log is not None:
            # Fresh view for still-running jobs: drain staged lifecycle
            # events first (unlocked site), then read the durable file.
            self._flush_daemon_log()
            daemon_events = daemon_log_mod.DaemonLog.read(self.work_root)
        return explain_mod.assemble(
            job_id=rec.job_id,
            config=rec.config,
            state=rec.state,
            submitted_at=rec.submitted_at,
            started_at=rec.started_at,
            finished_at=rec.finished_at,
            metrics_counters=rec.metrics.piggyback(),
            events=events,
            index_shards_pruned=rec.index_shards_pruned,
            index_bytes_skipped=rec.index_bytes_skipped,
            result_splits_reused=rec.result_splits_reused,
            result_bytes_unscanned=rec.result_bytes_unscanned,
            result_revalidations=rec.result_revalidations,
            daemon_events=daemon_events,
        )

    # --------------------------------------------------- elastic scale
    def scale_advice(self) -> dict:
        """Queue-depth / pending-task / in-flight-age derived pool
        advice: "grow" when assignable demand exceeds the attached
        workers (or recovery is stalling — old in-flight heartbeats with
        no idle capacity), "shrink" when the daemon is idle with workers
        attached, else "hold".  ``dgrep serve --max-workers`` follows it
        for the local pool; operators of remote fleets read it from
        GET /status.  Snapshots under the service lock, then consults
        the running schedulers OUTSIDE it (their own locks)."""
        with self._lock:
            queued = len(self._queue)
            running = list(self._running)
            recs = [self._jobs.get(jid) for jid in running]
            # Only FRESH rows count as capacity: the worker table keeps
            # rows for 1 h of silence, but a drained local loop or a
            # dead remote worker stops polling immediately — counting
            # its stale row as an idle worker suppresses grow advice
            # exactly when recovery needs it.  Live workers refresh
            # every long-poll retry, so a generous multiple of the poll
            # cadence bounds the staleness.
            now = time.monotonic()
            workers = sum(
                1 for info in self.workers.values()
                if now - info["seen"] <= _SCALE_FRESH_S
            )
        pending = 0
        in_flight = 0
        oldest_age = 0.0
        for rec in recs:
            if rec is not None and getattr(rec.config, "follow", False):
                # standing queries scan daemon-side: they occupy a
                # running slot but never produce worker tasks — counting
                # one as demand would advise "grow" forever
                continue
            if rec is None or rec.scheduler is None:
                # start staged, setup in flight: at least its tasks are
                # coming — count it as demand like a queued job
                pending += 1
                continue
            b = rec.scheduler.backlog()
            pending += b["unassigned"]
            in_flight += b["in_flight"]
            oldest_age = max(oldest_age, b["oldest_inflight_age_s"])
        demand = pending + queued
        if demand > 0 and demand > max(0, workers - in_flight):
            advice, reason = "grow", "assignable demand exceeds idle workers"
        elif workers and not running and not queued:
            advice, reason = "shrink", "no jobs queued or running"
        else:
            advice, reason = "hold", ""
        out = {
            "advice": advice,
            "queued_jobs": queued,
            "running_jobs": len(running),
            "pending_tasks": pending,
            "in_flight_tasks": in_flight,
            "oldest_inflight_age_s": oldest_age,
            "workers_attached": workers,
        }
        if reason:
            out["reason"] = reason
        if advice != self._last_scale_advice:
            # verdict CHANGES only — /status polls this every scrape and
            # a steady-state "hold" per poll would flood the timeline
            self._last_scale_advice = advice
            self._daemon_event(
                "scale_advice", advice=advice, pending_tasks=pending,
                workers=workers, **({"reason": reason} if reason else {}),
            )
            self._flush_daemon_log()
        return out

    def local_pool_size(self) -> int:
        """In-process worker loops not yet draining."""
        return len([
            lp for lp in getattr(self, "_local_loops", [])
            if not lp.drain.is_set()
        ])

    def scale_local_pool(self, target: int) -> int:
        """Grow or shrink the in-process worker pool toward ``target``;
        returns the delta actually applied.  Grow attaches fresh loops
        (attach is always safe — service-allocated ids); shrink DRAINS
        the newest loops: each exits at its next idle poll, never
        mid-task, and its id simply ages out of the worker table."""
        target = max(0, int(target))
        self._prune_local_pool()
        loops = [lp for lp in getattr(self, "_local_loops", [])
                 if not lp.drain.is_set()]
        if target > len(loops):
            self.start_local_workers(target - len(loops))
            self._scale_action("grow", target - len(loops))
            return target - len(loops)
        if target < len(loops):
            for lp in loops[target:]:
                lp.drain.set()
            self._wake()  # long-polling drainees re-check at next wake
            self._scale_action("drain", len(loops) - target)
            return target - len(loops)
        return 0

    def _scale_action(self, action: str, n: int) -> None:
        """One applied elastic-pool action: SLO counter (created lazily —
        an inelastic daemon never renders the series) + fleet-timeline
        event.  Runs unlocked (scale_local_pool call sites)."""
        metrics_mod.counter("dgrep_scale_actions_total").inc()
        self._daemon_event("scale_action", action=action, workers=n)
        self._flush_daemon_log()

    def _prune_local_pool(self) -> None:
        """Drop local pool entries whose loop drained AND whose thread
        exited — grow/shrink cycles must not grow the lists (and the
        retained WorkerLoop transports/metrics) for the daemon's
        lifetime.  The two lists extend in lockstep (start_local_workers
        is the only writer), so index i pairs loop i with thread i;
        anything still alive — or desynced lists — is kept untouched."""
        loops = getattr(self, "_local_loops", [])
        threads = getattr(self, "_local_workers", [])
        if not loops or len(loops) != len(threads):
            return
        kept = [
            (lp, t) for lp, t in zip(loops, threads)
            if not (lp.drain.is_set() and not t.is_alive())
        ]
        if len(kept) != len(loops):
            self._local_loops = [lp for lp, _ in kept]
            self._local_workers = [t for _, t in kept]

    # ------------------------------------------------------------- lifecycle
    def start_local_workers(
        self,
        n: int,
        fault_hooks_per_worker: list[dict] | None = None,
    ) -> list[threading.Thread]:
        """Attach N in-process worker loops (the single-host serving shape;
        remote hosts attach via ``dgrep worker --addr``).  One shared
        Metrics instance, like run_job — the piggyback aggregates across
        local workers."""
        from distributed_grep_tpu.runtime.worker import WorkerKilled, WorkerLoop

        metrics = Metrics()
        loops = [
            WorkerLoop(
                ServiceLocalTransport(self, rpc_timeout_s=self.rpc_timeout_s),
                app=None,  # resolved per assignment (reply.application)
                metrics=metrics,
                fault_hooks=(fault_hooks_per_worker or [{}] * n)[i],
                spans_enabled=self.spans,
            )
            for i in range(n)
        ]

        def worker_main(idx: int) -> None:
            try:
                loops[idx].run()
            except WorkerKilled:
                log.info("service worker %d killed by fault injection", idx)
            except Exception:
                log.exception("service worker %d crashed", idx)

        threads = [
            threading.Thread(target=worker_main, args=(i,),
                             name=f"svc-worker-{i}", daemon=True)
            for i in range(n)
        ]
        for t in threads:
            t.start()
        self._local_workers = getattr(self, "_local_workers", [])
        self._local_workers.extend(threads)
        # tracked for the elastic pool (scale_local_pool drains the tail)
        self._local_loops = getattr(self, "_local_loops", [])
        self._local_loops.extend(loops)
        return threads

    def stop(self, join_timeout_s: float = 10.0) -> None:
        """Shut the service down: stop every non-terminal job's scheduler,
        dismiss long-polling workers (JOB_DONE), join local workers."""
        with self._cond:
            self._stopped = True
            for jid in list(self._queue):
                rec = self._jobs[jid]
                rec.state = JobState.CANCELLED
                rec.finished_at = time.time()
                _C_CANCELLED.inc()
                self._stage_state(rec)
            self._queue.clear()
            for jid in list(self._running):
                rec = self._jobs[jid]
                rec.state = JobState.CANCELLED
                rec.finished_at = time.time()
                _C_CANCELLED.inc()
                self._stage_state(rec)
                self._close_job_locked(rec)
            self._cond.notify_all()
        self._flush_starts()  # drains (and tears down) cancelled pendings
        self._flush_closes()
        if self._follow_groups is not None:
            # safety net: the runners' close() discards already emptied
            # every group — this only stops a loop orphaned by a raced
            # teardown (pure state; never constructed with fusion off)
            self._follow_groups.close()
        self._flush_registry()
        if self._daemon_log is not None:
            # graceful stop is a timeline event; a deposed daemon's stop
            # is fenced at flush (the promoted daemon owns the file now)
            self._daemon_event("stop")
            self._flush_daemon_log()
            if self._lease_ok():
                self._daemon_log.close()
        for t in getattr(self, "_local_workers", []):
            t.join(timeout=join_timeout_s)
        self._registry.close()
        if self._lease is not None:
            # graceful handoff: delete the lease iff still ours so a
            # standby promotes immediately instead of waiting out the
            # TTL.  A deposed daemon's release is a no-op (the token no
            # longer matches — never unlink the winner's lease).
            self._lease.release()


# ---------------------------------------------------------------- transports
class ServiceLocalTransport:
    """In-process worker transport against a GrepService: direct control
    plane calls + per-job shared-filesystem data plane (the LocalTransport
    shape with a job-scoped work dir that follows bind_job)."""

    is_local = True

    def __init__(self, service: GrepService, rpc_timeout_s: float = 30.0):
        self.service = service
        self.rpc_timeout_s = rpc_timeout_s
        self._job = ""
        self._wd: WorkDir | None = None

    def bind_job(self, job_id: str) -> None:
        if job_id == self._job and self._wd is not None:
            return
        rec = self.service.record(job_id)
        if rec.workdir is None:
            raise RuntimeError(f"job {job_id} has no work dir (not started)")
        self._job = job_id
        self._wd = rec.workdir

    # control plane
    def assign_task(self, args: rpc.AssignTaskArgs) -> rpc.AssignTaskReply:
        return self.service.assign_task(args, timeout=self.rpc_timeout_s)

    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return self.service.map_finished(args)

    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return self.service.reduce_finished(args)

    def reduce_next_file(self, args: rpc.ReduceNextFileArgs) -> rpc.ReduceNextFileReply:
        return self.service.reduce_next_file(args, timeout=self.rpc_timeout_s)

    def heartbeat(self, args: rpc.HeartbeatArgs) -> float:
        self.service.heartbeat(args)
        return 0.0  # same process, same clock (see LocalTransport)

    # data plane (job-scoped)
    def read_input(self, filename: str) -> bytes:
        return resolve_input_path(filename, self._wd).read_bytes()

    def read_input_path(self, filename: str):
        return resolve_input_path(filename, self._wd), False

    def write_intermediate(self, name: str, data: bytes) -> None:
        self._wd.store.put(self._wd.root / "intermediate" / name, data)

    def read_intermediate(self, name: str) -> bytes:
        return self._wd.store.get(self._wd.root / "intermediate" / name)

    def write_output(self, name: str, data: bytes) -> None:
        self._wd.store.put(self._wd.root / "out" / name, data)

    def write_output_from_file(self, name: str, path: str) -> None:
        self._wd.store.put_from_file(self._wd.root / "out" / name, path)

    def publish_task_commit(self, kind: str, task_id: int, attempt: str,
                            payload: dict) -> None:
        self._wd.store.commit_task(
            self._wd.commits_dir(), kind, task_id, attempt, payload
        )


# --------------------------------------------------------------- HTTP server
class ServiceServer:
    """HTTP surface for a GrepService: the job API (POST /jobs, GET
    /jobs/<id>[/result], POST /jobs/<id>/cancel, GET /status) plus the
    worker planes (POST /rpc/<verb>, job-scoped GET/PUT /data/<job>/...,
    GET /config worker bootstrap)."""

    def __init__(self, service: GrepService, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self._httpd = ThreadingHTTPServer((host, port), _make_service_handler(self))
        self._httpd.daemon_threads = True
        self.host = host
        self._serve_thread: threading.Thread | None = None
        # built once: handle_rpc derives the long-poll window from it per
        # request, and /config serves it as the worker bootstrap
        self._bootstrap = self.bootstrap_config()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-service", daemon=True
        )
        self._serve_thread.start()
        log.info(
            "service serving on %s:%d (max %d concurrent jobs, queue %d)",
            self.host, self.port, self.service.max_jobs,
            self.service.queue_depth,
        )

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # worker bootstrap: run_http_worker fetches /config once at attach; the
    # real application + options arrive per assignment, so this only names
    # a default app and the transport/span knobs.
    def bootstrap_config(self) -> JobConfig:
        return JobConfig(
            input_files=[],
            application="distributed_grep_tpu.apps.grep",
            work_dir=str(self.service.work_root),
            spans=self.service.spans,
            rpc_timeout_s=self.service.rpc_timeout_s,
        )

    def handle_rpc(self, verb: str, payload: dict) -> dict:
        window = long_poll_window_s(self._bootstrap)
        if verb == rpc.Verb.ASSIGN_TASK:
            reply = self.service.assign_task(
                rpc.AssignTaskArgs(**payload), timeout=window
            )
        elif verb == rpc.Verb.MAP_FINISHED:
            reply = self.service.map_finished(rpc.TaskFinishedArgs(**payload))
        elif verb == rpc.Verb.REDUCE_FINISHED:
            reply = self.service.reduce_finished(rpc.TaskFinishedArgs(**payload))
        elif verb == rpc.Verb.REDUCE_NEXT_FILE:
            reply = self.service.reduce_next_file(
                rpc.ReduceNextFileArgs(**payload), timeout=window
            )
        elif verb == rpc.Verb.HEARTBEAT:
            self.service.heartbeat(rpc.HeartbeatArgs(**payload))
            reply = rpc.HeartbeatReply()
        else:
            raise KeyError(f"unknown RPC verb: {verb}")
        # historical asdict shape, NEW reply fields elided at defaults
        # (rpc.reply_to_dict) — fusion-off payloads stay byte-identical
        return rpc.reply_to_dict(reply)


def _safe_segment(name: str) -> str:
    name = urllib.parse.unquote(name)
    if "/" in name or name.startswith("."):
        raise ValueError(f"invalid path segment: {name!r}")
    return name


def _make_service_handler(server: ServiceServer):
    service = server.service

    class Handler(DataPlaneHandler):
        def do_POST(self):
            try:
                if self.path.startswith("/rpc/"):
                    verb = self.path[len("/rpc/") :]
                    payload = json.loads(self._read_body() or b"{}")
                    self._send_json(server.handle_rpc(verb, payload))
                elif self.path == "/jobs":
                    try:
                        cfg = JobConfig.from_json(
                            (self._read_body() or b"{}").decode("utf-8",
                                                                "strict")
                        )
                        job_id = service.submit(cfg)
                    except AdmissionError as e:
                        self._send_json({"error": str(e)}, 429)
                        return
                    except (TypeError, ValueError) as e:
                        self._send_json({"error": f"bad job config: {e}"}, 400)
                        return
                    self._send_json({"job_id": job_id}, 202)
                elif self.path.startswith("/jobs/") and self.path.endswith("/cancel"):
                    job_id = _safe_segment(
                        self.path[len("/jobs/") : -len("/cancel")]
                    )
                    try:
                        state = service.cancel(job_id)
                    except KeyError:
                        self._send_json({"error": f"unknown job: {job_id}"}, 404)
                        return
                    self._send_json({"ok": True, "state": state})
                else:
                    self._drain_body()
                    self._send_json({"error": "not found"}, 404)
            except BrokenPipeError:
                pass  # client gave up on a long-poll; service state is safe
            except Exception as e:  # noqa: BLE001 — report, don't kill the server
                log.exception("service rpc error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        def do_GET(self):
            self._streaming_body = False  # per request (keep-alive reuses us)
            try:
                if self.path == "/config":
                    self._send_json(json.loads(server._bootstrap.to_json()))
                elif self.path == "/status":
                    self._send_json(service.status())
                elif self.path == "/metrics":
                    # Prometheus text exposition (stable sort, byte-
                    # stable): job-lifecycle histograms + the live scale
                    # signal + rolling cache-hit rates
                    self._send_text(service.metrics_text())
                elif self.path.startswith("/jobs/") and (
                    urllib.parse.urlsplit(self.path).path.endswith("/stream")
                ):
                    # standing-query subscription (round 17): long-poll a
                    # page of records past ?cursor=N; the reply's "next"
                    # is the cursor to pass back.  Bounded server state —
                    # the subscriber's only identity IS its cursor.
                    parsed = urllib.parse.urlsplit(self.path)
                    job_id = _safe_segment(
                        parsed.path[len("/jobs/") : -len("/stream")]
                    )
                    q = urllib.parse.parse_qs(parsed.query)

                    def _q(name: str, default: float) -> float:
                        try:
                            return float(q.get(name, [default])[0])
                        except (TypeError, ValueError):
                            return default

                    try:
                        self._send_json(service.job_stream(
                            job_id, cursor=int(_q("cursor", 0)),
                            timeout=_q("timeout", 25.0),
                        ))
                    except KeyError:
                        self._send_json(
                            {"error": f"unknown job: {job_id}"}, 404)
                    except RuntimeError as e:
                        self._send_json({"error": str(e)}, 409)
                elif self.path.startswith("/jobs/"):
                    rest = self.path[len("/jobs/") :]
                    if rest.endswith("/result"):
                        job_id = _safe_segment(rest[: -len("/result")])
                        try:
                            self._send_json(service.job_result(job_id))
                        except KeyError:
                            self._send_json(
                                {"error": f"unknown job: {job_id}"}, 404)
                        except RuntimeError as e:
                            self._send_json({"error": str(e)}, 409)
                    elif rest.endswith("/explain"):
                        job_id = _safe_segment(rest[: -len("/explain")])
                        try:
                            self._send_json(service.job_explain(job_id))
                        except KeyError:
                            self._send_json(
                                {"error": f"unknown job: {job_id}"}, 404)
                    else:
                        job_id = _safe_segment(rest)
                        try:
                            self._send_json(service.job_status(job_id))
                        except KeyError:
                            self._send_json(
                                {"error": f"unknown job: {job_id}"}, 404)
                elif self.path.startswith("/data/"):
                    job_id, kind, name = self._data_parts()
                    rec = service.record(job_id)
                    if kind == "input":
                        if name not in rec.input_allowlist:
                            self._send_json(
                                {"error": f"not an input split: {name}"}, 403)
                            return
                        p = resolve_input_path(name, rec.workdir)
                        if not p.exists():
                            self._send_json(
                                {"error": f"no such input: {name}"}, 404)
                            return
                        self._send_file(p)
                    elif kind == "intermediate":
                        p = rec.workdir.store.resolve(
                            rec.workdir.root / "intermediate" / name
                        )
                        if p is None:
                            self._send_json(
                                {"error": f"no such file: {name}"}, 404)
                            return
                        # relay shuffle byte accounting (round 16): with
                        # peer shuffle on, reducers never GET here and
                        # the counter stays flat — the P2P receipt
                        service.count_shuffle_bytes(
                            "relay_gets", p.stat().st_size
                        )
                        self._send_file(p)
                    else:
                        self._send_json({"error": "not found"}, 404)
                else:
                    self._send_json({"error": "not found"}, 404)
            except BrokenPipeError:
                self.close_connection = True
            except KeyError as e:
                self._send_json({"error": str(e)}, 404)
            except Exception as e:  # noqa: BLE001
                self.close_connection = True
                log.exception("service get error on %s", self.path)
                if getattr(self, "_streaming_body", False):
                    return  # headers out: never splice JSON into a body
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        def do_PUT(self):
            try:
                if not self.path.startswith("/data/"):
                    self._drain_body()
                    self._send_json({"error": "not found"}, 404)
                    return
                job_id, kind, name = self._data_parts()
                rec = service.record(job_id)
                wd = rec.workdir
                if kind == "intermediate":
                    length = int(self.headers.get("Content-Length", 0))
                    self._receive_file(wd.store, wd.root / "intermediate" / name)
                    # relay shuffle byte accounting (see the GET branch)
                    service.count_shuffle_bytes("relay_puts", length)
                    self._send_json({"ok": True})
                elif kind == "out":
                    self._receive_file(wd.store, wd.root / "out" / name)
                    self._send_json({"ok": True})
                elif kind == "commit":
                    self._put_commit(wd.store, wd.commits_dir(), name)
                else:
                    self._drain_body()
                    self._send_json({"error": "not found"}, 404)
            except KeyError as e:
                self._drain_body()
                self._send_json({"error": str(e)}, 404)
            except Exception as e:  # noqa: BLE001
                self.close_connection = True
                log.exception("service put error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        def _data_parts(self) -> tuple[str, str, str]:
            """('/data/<job>/<kind>/<name>') -> (job, kind, name).  Job and
            kind are traversal-checked segments; input names may be full
            filesystem paths (they arrive %2F-quoted as one segment and are
            gated by the job's input allowlist, exactly like the one-shot
            coordinator's /data/input/ route), every other kind keeps the
            slash-free _safe_segment rule."""
            rest = self.path[len("/data/") :]
            parts = rest.split("/", 2)
            if len(parts) != 3:
                raise ValueError(f"bad data path: {self.path!r}")
            job_id = _safe_segment(parts[0])
            kind = _safe_segment(parts[1])
            if kind == "input":
                name = urllib.parse.unquote(parts[2])
            else:
                name = _safe_segment(parts[2])
            return job_id, kind, name

    return Handler


# ------------------------------------------------------------ standby surface
class StandbyServer:
    """Park surface of a daemon WAITING on the work-root lease (round 18
    active/standby failover, runtime/lease.py).  NO service state lives
    behind it — everything a client or worker can hit answers "not me,
    yet": ``/status`` names the role plus the active's advertised address
    read from the lease file (run_http_worker parks-and-polls on it
    instead of erroring), assign polls get a plain retry +
    ``retry_after_s`` reply (the WorkerLoop sleeps on it and re-polls —
    rotation then finds whichever daemon holds the lease), reduce pulls
    get ``abort=True`` (the attempt abandons cleanly, exactly the zombie
    fence's answer), and submits/data traffic get 503 (the CLI's address
    rotation retries against the active).  Promotion shuts this server
    down and binds the real ServiceServer on the same (host, port)."""

    PARK_RETRY_S = 2.0

    def __init__(self, work_root: str | Path, host: str = "127.0.0.1",
                 port: int = 0):
        self.work_root = Path(work_root)
        self._httpd = ThreadingHTTPServer(
            (host, port), _make_standby_handler(self)
        )
        self._httpd.daemon_threads = True
        self.host = host
        self._serve_thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "StandbyServer":
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="http-standby",
            daemon=True,
        )
        self._serve_thread.start()
        log.info("standby parked on %s:%d (watching %s)",
                 self.host, self.port, self.work_root)
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def status(self) -> dict:
        from distributed_grep_tpu.runtime.lease import WorkRootLease

        rec = WorkRootLease.read(self.work_root) or {}
        # "service": true keeps the readiness probes (tests/service_proc)
        # and worker sniffing working; "role" is what distinguishes us.
        return {
            "service": True,
            "role": "standby",
            "active": rec.get("addr", ""),
        }

    def rpc_reply(self, verb: str, payload: dict):
        if verb == rpc.Verb.ASSIGN_TASK:
            # echo the caller's worker id — the WorkerLoop adopts
            # reply.worker_id unconditionally, and the default -1 would
            # un-register a parked worker
            return rpc.AssignTaskReply(
                assignment="retry",
                task_id=-2,
                worker_id=int(payload.get("worker_id", -1)),
                retry_after_s=self.PARK_RETRY_S,
            )
        if verb == rpc.Verb.REDUCE_NEXT_FILE:
            return rpc.ReduceNextFileReply(abort=True)
        if verb in (rpc.Verb.MAP_FINISHED, rpc.Verb.REDUCE_FINISHED):
            return rpc.TaskFinishedReply()
        if verb == rpc.Verb.HEARTBEAT:
            return rpc.HeartbeatReply()
        raise KeyError(f"unknown RPC verb: {verb}")


def _make_standby_handler(server: StandbyServer):
    class Handler(DataPlaneHandler):
        def do_POST(self):
            try:
                if self.path.startswith("/rpc/"):
                    verb = self.path[len("/rpc/") :]
                    payload = json.loads(self._read_body() or b"{}")
                    self._send_json(
                        rpc.reply_to_dict(server.rpc_reply(verb, payload))
                    )
                else:
                    self._drain_body()
                    self._send_json(
                        {"error": "standby: no lease held here"}, 503)
            except BrokenPipeError:
                pass
            except KeyError as e:
                self._send_json({"error": str(e)}, 404)
            except Exception as e:  # noqa: BLE001 — report, don't die
                log.exception("standby rpc error on %s", self.path)
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        def do_GET(self):
            self._streaming_body = False
            try:
                if self.path == "/status":
                    self._send_json(server.status())
                else:
                    self._send_json(
                        {"error": "standby: no lease held here"}, 503)
            except BrokenPipeError:
                self.close_connection = True
            except Exception as e:  # noqa: BLE001
                self.close_connection = True
                try:
                    self._send_json({"error": str(e)}, 500)
                except OSError:
                    pass

        def do_PUT(self):
            try:
                self._drain_body()
                self._send_json(
                    {"error": "standby: no lease held here"}, 503)
            except (BrokenPipeError, OSError):
                pass

    return Handler

"""FaultTransport — deterministic network-fault injection around any
Transport, the control/data-plane sibling of ``runtime/store.FaultStore``.

``FaultStore`` proved the STORAGE commit protocol against crashes at every
interruptible instruction; production traffic, however, traverses the HTTP
control plane (four RPC verbs) and the ``/data/`` plane, where the network
itself misbehaves: requests vanish before reaching the daemon, replies
vanish after the daemon acted (the duplicate-commit generator), packets
stall, and retried requests arrive twice.  This wrapper injects exactly
those four behaviors at the transport boundary, deterministically, so the
chaos matrix (tests/test_chaos.py) can assert the system-level guarantees
the retry/idempotency design promises: byte-identical outputs and
exactly-once task registration under any interleaving.

Design mirrors FaultStore: ``hooks`` maps FaultPoint -> callable(ctx) with
ctx = the wrapped method's name (``"map_finished"``, ``"read_input"``,
...).  A hook returns truthy to inject at its point, falsy to let the call
through untouched — so one hook can target one verb, fire once, or fire on
a seeded-random schedule.  Injection semantics per point:

* DROP_REQUEST — the call is NOT made; ConnectionResetError raises (the
  request died on the wire before the peer saw it).
* DROP_REPLY — the call IS made and its reply DISCARDED;
  ConnectionResetError raises (the peer acted, the client cannot know —
  whoever retries produces a duplicate delivery, which the idempotent
  commit layer must absorb).
* DELAY — the hook's truthy return is a float: sleep that many seconds,
  then proceed (congestion/straggler links; exercises the failure
  detector against slow-but-alive traffic).
* DUPLICATE — the call is made TWICE, the first reply discarded (a retry
  racing its own original: both deliveries arrive, the second answer
  wins client-side).

Injected errors surface to the CALLER exactly like a real broken
connection surfaces from a transport whose retry schedule is exhausted:
a worker loop built on this wrapper dies like a worker whose network
died, and the scheduler's timeout/re-execution + quarantine machinery —
not the wrapper — is what the chaos tests then hold to account.
``heartbeat`` is wrapped like everything else; the worker's advisory
contract (never raises) already absorbs its failures.
"""

from __future__ import annotations

import time
from typing import Callable

from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("fault_transport")


class FaultPoint:
    """Injection points for FaultTransport — each models one way the
    network can betray an RPC or data-plane call."""

    DROP_REQUEST = "drop_request"
    DROP_REPLY = "drop_reply"
    DELAY = "delay"
    DUPLICATE = "duplicate"

    ALL = (DROP_REQUEST, DROP_REPLY, DELAY, DUPLICATE)


# Every Transport-protocol method FaultTransport wraps: the four control
# verbs + heartbeat, and the data plane (optional methods are wrapped only
# when the base transport has them — hasattr probes must keep answering
# the truth for the worker's feature detection).
_WRAPPED = (
    "assign_task", "map_finished", "reduce_finished", "reduce_next_file",
    "heartbeat",
    "read_input", "read_input_path", "write_intermediate",
    "read_intermediate", "write_output", "write_output_from_file",
    "publish_task_commit",
    # peer-to-peer shuffle fetch (round 16): present only on transports
    # that expose it — the hasattr gate keeps feature probes truthful
    "fetch_peer",
)


class FaultTransport:
    """Deterministic network-fault injection around any Transport."""

    def __init__(self, base, hooks: dict[str, Callable]):
        self.base = base
        self.hooks = dict(hooks)
        unknown = set(self.hooks) - set(FaultPoint.ALL)
        if unknown:
            raise ValueError(f"unknown fault points: {sorted(unknown)}")
        for name in _WRAPPED:
            if hasattr(base, name):
                setattr(self, name, self._wrap(name))

    def __getattr__(self, name: str):
        # everything un-wrapped (is_local, bind_job, fetch_config,
        # retry_count, ...) delegates — feature probes see the base's truth
        return getattr(self.base, name)

    def _wrap(self, name: str) -> Callable:
        fn = getattr(self.base, name)

        def call(*args, **kwargs):
            delay_hook = self.hooks.get(FaultPoint.DELAY)
            if delay_hook:
                delay = delay_hook(name)
                if delay:
                    time.sleep(float(delay))
            drop_req = self.hooks.get(FaultPoint.DROP_REQUEST)
            if drop_req and drop_req(name):
                log.debug("fault: dropping request %s", name)
                raise ConnectionResetError(
                    f"injected fault: {name} request dropped"
                )
            dup = self.hooks.get(FaultPoint.DUPLICATE)
            if dup and dup(name):
                log.debug("fault: duplicating %s", name)
                fn(*args, **kwargs)  # first delivery's reply discarded
            out = fn(*args, **kwargs)
            drop_reply = self.hooks.get(FaultPoint.DROP_REPLY)
            if drop_reply and drop_reply(name):
                log.debug("fault: dropping reply of %s", name)
                raise ConnectionResetError(
                    f"injected fault: {name} reply dropped"
                )
            return out

        call.__name__ = name
        return call


def seeded_schedule(seed: int, rates: dict[str, float],
                    only: tuple[str, ...] = ()) -> dict[str, Callable]:
    """A reproducible chaos plan: hooks firing with the given per-point
    probability from one seeded RNG stream.  ``rates`` maps FaultPoint ->
    probability (DELAY's draws scale a 0-50 ms sleep); ``only`` restricts
    injection to the named methods (empty = all).  One RNG is shared
    across points and calls, so a (seed, rates) pair names ONE exact
    fault interleaving per call sequence."""
    import random

    rng = random.Random(seed)

    def mk(point: str, rate: float) -> Callable:
        def hook(ctx: str):
            if only and ctx not in only:
                return 0
            draw = rng.random()
            if draw >= rate:
                return 0
            if point == FaultPoint.DELAY:
                return 0.05 * draw / max(rate, 1e-9)
            return 1

        return hook

    return {point: mk(point, rate) for point, rate in rates.items()}

"""Task state tables — the coordinator's core bookkeeping.

Mirrors the reference's task model: state enum Unassigned/InProgress/
Completed (helper_types.go:144-148), per-map-task {state, timestamp, file}
(MapData, helper_types.go:150-154) and per-reduce-task {state, timestamp,
registered intermediate files} (ReduceData, helper_types.go:156-161).
Timestamps drive the 10s failure detector (coordinator.go:97-124).
Tasks — not workers — are the tracked entities; workers join implicitly by
asking for work (a genuine elasticity capability of the reference design).
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field


class TaskType(enum.Enum):
    MAP = "map"
    REDUCE = "reduce"


class TaskState(enum.Enum):
    UNASSIGNED = "unassigned"
    IN_PROGRESS = "in_progress"
    COMPLETED = "completed"


@dataclass
class MapTask:
    task_id: int
    file: str  # single-file tasks: the input path; batched splits: the
    # split's display label (scheduler._split_label) — ``files`` then
    # carries the member paths
    state: TaskState = TaskState.UNASSIGNED
    timestamp: float = 0.0  # heartbeat; stamped at assignment + mid-task
    attempts: int = 0
    # One-shot extension of the sweep window, declared by a heartbeat ahead
    # of a known-long silent phase (a cold device compile blocks 20-40 s
    # with no observable progress).  Any later stamp resets it to 0, so
    # steady-state failure detection keeps the plain task_timeout_s — the
    # grace bounds only the declared window (VERDICT r3 item 3).
    grace_s: float = 0.0
    # Member files of a batched multi-file split (cross-file device
    # batching, runtime/job.plan_map_splits); () for ordinary tasks.
    files: tuple[str, ...] = ()
    # Worker holding the current attempt (-1 = none): lets the timeout
    # sweeper attribute the failure to the worker that went silent, the
    # input of the quarantine tracker (scheduler.WorkerHealth).
    worker: int = -1
    # True once the WORKER stamped this attempt (mid-task heartbeat /
    # shuffle fetch) — proof it actually received the assignment.  An
    # unstamped timeout might be a LOST ASSIGNMENT REPLY, not a dark
    # worker; the sweeper then charges the worker only if it also never
    # polled again (scheduler._sweep_loop).
    stamped: bool = False
    # True while the current attempt was claimed as a fused EXTRA
    # (Scheduler.claim_map_task, cross-tenant scan fusion): its timeout
    # is never charged to WorkerHealth — K participant schedulers share
    # one health tracker, and a single lost fused attempt must count as
    # ONE dark-worker event (the primary assignment's charge), not K.
    fused_claim: bool = False
    # Peer-to-peer shuffle (round 16, runtime/peer.py): where this map
    # task's committed output lives when it was spooled on the PRODUCING
    # worker instead of the coordinator — {"endpoint": "http://host:port",
    # "worker": service worker id, "parts": {partition: [size, crc32hex]}}.
    # None on relay commits (bytes on the coordinator, pre-peer behavior).
    # Cleared when a lost-output report re-enqueues the task.
    peer: dict | None = None

    def heartbeat(self, grace_s: float = 0.0) -> None:
        self.timestamp = time.monotonic()
        self.grace_s = grace_s


@dataclass
class ReduceTask:
    task_id: int
    state: TaskState = TaskState.UNASSIGNED
    timestamp: float = 0.0
    attempts: int = 0
    grace_s: float = 0.0  # see MapTask.grace_s
    worker: int = -1  # see MapTask.worker (quarantine attribution)
    stamped: bool = False  # see MapTask.stamped
    # Intermediate files registered as map tasks commit; reducers stream these
    # in arrival order (the pipelined shuffle, coordinator.go:159-174).
    task_files: list[str] = field(default_factory=list)

    def heartbeat(self, grace_s: float = 0.0) -> None:
        self.timestamp = time.monotonic()
        self.grace_s = grace_s

"""Shuffle encoding: partitioning and the intermediate-file wire format.

Partitioning is FNV-32a(key) % n_reduce, bit-compatible with the reference's
ihash (map_reduce/worker.go:13-17, :89).  Intermediate files are JSON-lines
of [key, value] records — the reference JSON-encodes a stream of KeyValue
structs per file (worker.go:45-70, :92-101); JSON-lines keeps that
inspectability while being trivially appendable and splittable.

Unlike the reference's writeMapOutput — which does one full pass over the
map output *per partition* (O(nReduce * |out|), worker.go:88-91) — this
bucketizes in a single pass.
"""

from __future__ import annotations

import json

from distributed_grep_tpu.apps.base import KeyValue
from distributed_grep_tpu.utils.native import partition


def bucketize(records: list[KeyValue], n_reduce: int) -> dict[int, list[KeyValue]]:
    """Single-pass partition of map output into reduce buckets."""
    buckets: dict[int, list[KeyValue]] = {}
    for kv in records:
        r = partition(kv.key, n_reduce)
        buckets.setdefault(r, []).append(kv)
    return buckets


def encode_records(records: list[KeyValue]) -> bytes:
    # surrogateescape: keys embed filenames, which on POSIX may contain
    # non-UTF8 bytes that argv/os decoding maps to lone surrogates — they
    # must round-trip the wire format (CLAUDE.md invariant), not crash it.
    return "".join(
        json.dumps([kv.key, kv.value], ensure_ascii=False) + "\n" for kv in records
    ).encode("utf-8", "surrogateescape")


def decode_records(data: bytes) -> list[KeyValue]:
    out: list[KeyValue] = []
    # Split on \n only: JSON escapes \r and \n inside strings but leaves
    #  /  literal with ensure_ascii=False, and splitlines() would
    # fragment records at those characters.
    for line in data.decode("utf-8", "surrogateescape").split("\n"):
        if line:
            k, v = json.loads(line)
            out.append(KeyValue(k, v))
    return out

"""Shuffle encoding: partitioning and the intermediate-file wire format.

Partitioning is FNV-32a(key) % n_reduce, bit-compatible with the reference's
ihash (map_reduce/worker.go:13-17, :89).  Intermediate files are JSON-lines
of [key, value] records — the reference JSON-encodes a stream of KeyValue
structs per file (worker.go:45-70, :92-101); JSON-lines keeps that
inspectability while being trivially appendable and splittable.

Unlike the reference's writeMapOutput — which does one full pass over the
map output *per partition* (O(nReduce * |out|), worker.go:88-91) — this
bucketizes in a single pass.
"""

from __future__ import annotations

import json

from distributed_grep_tpu.apps.base import KeyValue
from distributed_grep_tpu.utils.native import partition


def bucketize(records: list, n_reduce: int) -> dict[int, list]:
    """Single-pass partition of map output into reduce buckets.

    Records are KeyValue (per-record FNV of the key) or columnar
    LineBatch (runtime/columnar.py — the match-dense fast path; its
    per-record FNV gives the EXACT same record->partition mapping, so
    per-record and columnar maps shuffle identically).  Batch splitting
    is ONE native pass per batch when libdgrep is available
    (dgrep_build_records: hash + grouping + slab gather; round 8), and a
    DeferredBatch (the grep apps' whole-buffer emit) splits straight
    from its SOURCE bytes — the intermediate whole-batch slab is never
    built on this path."""
    from distributed_grep_tpu.runtime.columnar import LineBatch

    buckets: dict[int, list] = {}
    for rec in records:
        if isinstance(rec, LineBatch):
            for r, sub in rec.split_by_partition(n_reduce).items():
                buckets.setdefault(r, []).append(sub)
        else:
            r = partition(rec.key, n_reduce)
            buckets.setdefault(r, []).append(rec)
    return buckets


def encode_records(records: list) -> bytes:
    # surrogateescape: keys embed filenames, which on POSIX may contain
    # non-UTF8 bytes that argv/os decoding maps to lone surrogates — they
    # must round-trip the wire format (CLAUDE.md invariant), not crash it.
    # LineBatch records interleave as binary blocks (runtime/columnar.py);
    # a batch-free record list encodes byte-identically to round 4.
    from distributed_grep_tpu.runtime import columnar

    parts: list[bytes] = []
    jsonl: list[str] = []

    def flush_jsonl() -> None:
        if jsonl:
            parts.append("".join(jsonl).encode("utf-8", "surrogateescape"))
            jsonl.clear()

    for rec in records:
        if isinstance(rec, columnar.LineBatch):
            flush_jsonl()
            parts.append(columnar.encode_batch(rec))
        else:
            jsonl.append(
                json.dumps([rec.key, rec.value], ensure_ascii=False) + "\n"
            )
    flush_jsonl()
    return b"".join(parts)


def decode_records(data: bytes) -> list:
    """Inverse of encode_records: KeyValue per JSONL line, LineBatch per
    columnar block (kept columnar — expanding 500k records to Python
    objects is the cost this format exists to avoid).  JSONL lines always
    start with '[' and batch blocks with '#', so the two cannot be
    confused; batch-free data decodes exactly as before."""
    from distributed_grep_tpu.runtime import columnar

    if columnar.MARKER not in data:
        return _decode_jsonl(data)
    out: list = []
    pos = 0
    n = len(data)
    while pos < n:
        if data.startswith(columnar.MARKER, pos):
            batch, pos = columnar.decode_batch_at(data, pos)
            out.append(batch)
            continue
        # A marker is a block boundary only at a LINE START — a grep'd
        # line may itself contain the marker text, which JSON embeds
        # literally (but raw newlines are always escaped, so '\n'+MARKER
        # cannot occur inside a record).
        nxt = data.find(b"\n" + columnar.MARKER, pos)
        chunk = data[pos:] if nxt < 0 else data[pos : nxt + 1]
        out.extend(_decode_jsonl(chunk))
        pos = n if nxt < 0 else nxt + 1
    return out


def _decode_jsonl(data: bytes) -> list[KeyValue]:
    out: list[KeyValue] = []
    # Split on \n only: JSON escapes \r and \n inside strings but leaves
    #  /  literal with ensure_ascii=False, and splitlines() would
    # fragment records at those characters.
    for line in data.decode("utf-8", "surrogateescape").split("\n"):
        if line:
            k, v = json.loads(line)
            out.append(KeyValue(k, v))
    return out

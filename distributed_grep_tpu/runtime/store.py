"""Pluggable blob-store commit layer — exactly-once without atomic rename.

The reference's durability story is "write to temp, os.Rename" on a POSIX
filesystem (worker.go:103, worker.go:169), and the runtime inherited that
assumption everywhere bytes commit (utils/io.py, runtime/transport.py,
http_coordinator.py).  Object stores (GCS/S3-style) have no atomic rename:
a crash mid-"rename" can leave duplicate, torn, or phantom objects.  This
module makes the commit protocol a pluggable Store with two semantics:

* PosixStore — temp + fsync + rename, the protocol extracted from
  utils/io.py's atomic_write family plus a DELIBERATE fsync-before-rename
  upgrade (the old helpers renamed without fsync; the commit-record design
  promises blob durability before anything publishes, and a host crash
  must not leave a committed-but-empty file).  A blob is visible iff the
  rename happened; duplicate executions overwrite idempotently.
* NonAtomicStore — object-store semantics emulated on a local directory:
  there is NO rename.  A write lands as ``<name>.part.<attempt>`` (plain
  write — a crash can tear it), then publishes a small self-checksummed
  commit record ``<name>.commit.<attempt>``.  Readers resolve a logical
  name to exactly one winning attempt: the lexicographically smallest
  attempt whose record parses, checksums, and whose part file matches the
  recorded size.  Torn parts (no record), torn records (bad checksum), and
  racing duplicate attempts (two records) all resolve deterministically —
  a reader can never observe a torn or half-committed blob.

Exactly-once task commit layers on top: a worker publishes one *task
commit record* (``commits/<kind>-<task_id>.<attempt>``) after all its
blobs are durable and before notifying the coordinator.  The scheduler
treats that record — not the MapFinished RPC args, not mr-* file
existence — as the unit of truth when registering map outputs and when
replaying the journal, so a re-executed straggler whose late commit races
the sweeper's re-issue can never double-register or expose a torn file
(CLAUDE.md invariant, this round).

FaultStore wraps any store with deterministic crash injection at the four
points where the protocol can be interrupted (CrashPoint) — the pytest
crash matrix (tests/test_store_faults.py) drives it.

Scale note: resolution is glob-based (one directory scan per lookup), so a
job with N tasks does O(N) dirent work per completion/read — O(N^2)
total.  Fine to ~thousands of tasks; past that the known fix is an
in-memory attempt index keyed by logical name (built from one scandir),
deferred until a workload needs it.
"""

from __future__ import annotations

import fnmatch
import json
import os
import shutil
import tempfile
import uuid
import zlib
from pathlib import Path
from typing import Callable, Optional, Protocol

from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("store")


class CrashPoint:
    """Injection points for FaultStore — each models a worker dying at a
    distinct instruction of the commit protocol."""

    # blob bytes staged (temp/part file written + fsync'd) but not yet
    # published: rename not executed (posix) / blob record not written
    # (non-atomic).  The blob must be invisible to readers.
    AFTER_TEMP_WRITE = "after_temp_write"
    # all blobs committed, task commit record not yet published: the task
    # must re-run; its re-committed blobs must resolve to one winner.
    BEFORE_COMMIT_RECORD = "before_commit_record"
    # task commit record published, coordinator never notified (worker died
    # before the MapFinished/ReduceFinished RPC): a re-run commits a second
    # attempt; resolution must still pick exactly one.
    AFTER_COMMIT_BEFORE_ACK = "after_commit_before_ack"
    # the task commit record itself tears mid-write (non-atomic store
    # semantics): the torn record must parse as absent, never as truth.
    TORN_COMMIT_RECORD = "torn_commit_record"

    ALL = (AFTER_TEMP_WRITE, BEFORE_COMMIT_RECORD,
           AFTER_COMMIT_BEFORE_ACK, TORN_COMMIT_RECORD)


def new_attempt_id() -> str:
    """Attempt ids sort the way they were created only by accident — the
    winner pick is 'lexicographically smallest valid attempt', which is
    deterministic for every reader without any clock assumptions."""
    return uuid.uuid4().hex


# --------------------------------------------------------------- records
# One record format for blob commit markers and task commit records:
#   <json payload>\n<crc32 of the json bytes, 8 hex digits>\n
# A torn write (any prefix of the file) fails either the JSON parse or the
# checksum line and is treated as absent — tearing is detectable, which is
# all a non-atomic store can promise for a small single-block PUT.

def encode_record(payload: dict) -> bytes:
    body = json.dumps(payload, sort_keys=True).encode("utf-8")
    return body + b"\n" + f"{zlib.crc32(body):08x}".encode("ascii") + b"\n"


def decode_record(data: bytes) -> Optional[dict]:
    """The payload, or None for anything torn/invalid."""
    lines = data.split(b"\n")
    if len(lines) < 3:  # body, crc, trailing '' — anything shorter is torn
        return None
    body, crc_line = lines[0], lines[1]
    if crc_line != f"{zlib.crc32(body):08x}".encode("ascii"):
        return None
    try:
        payload = json.loads(body)
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None
    return payload if isinstance(payload, dict) else None


def task_commit_path(directory: Path, kind: str, task_id: int,
                     attempt: str) -> Path:
    return Path(directory) / f"{kind}-{task_id}.{attempt}"


def _fsync_file(f) -> None:
    f.flush()
    os.fsync(f.fileno())


# -------------------------------------------------------------- protocol
class Store(Protocol):
    """How blobs become visible.  Paths are the LOGICAL blob paths (e.g.
    <workdir>/intermediate/mr-3-1); a store may materialize them under
    decorated concrete names — readers go through get()/resolve()/
    list_committed() and only ever see fully-committed winners."""

    name: str

    # blob writes (visible-on-return, never torn for readers).
    # consume=True on put_from_file: the caller donates src and tolerates
    # it disappearing — a store MAY commit it by rename (PosixStore);
    # stores whose protocol needs staged copies simply ignore the flag.
    def put(self, path: Path, data: bytes) -> None: ...
    def put_from_file(self, path: Path, src: Path,
                      chunk_bytes: int = 1 << 20,
                      consume: bool = False) -> None: ...
    def put_from_stream(self, path: Path, stream, length: int,
                        chunk_bytes: int = 1 << 20) -> None: ...

    # blob reads
    def get(self, path: Path) -> bytes: ...
    def exists(self, path: Path) -> bool: ...
    def resolve(self, path: Path) -> Optional[Path]: ...
    def list_committed(self, directory: Path, pattern: str) -> list[Path]: ...

    # exactly-once task commit
    def commit_task(self, directory: Path, kind: str, task_id: int,
                    attempt: str, payload: dict) -> None: ...
    def resolve_task_commit(self, directory: Path, kind: str,
                            task_id: int) -> Optional[dict]: ...


# ----------------------------------------------------------------- posix
class PosixStore:
    """temp + fsync + rename — the reference's commit protocol
    (worker.go:103), extracted from utils/io.py with fsync added before
    the rename (a deliberate durability upgrade — see the module
    docstring; on the tmpfs-backed work dirs of tests/CI it is ~free).
    os.replace is atomic on POSIX, so duplicate executions overwrite
    idempotently and readers never see a torn blob."""

    name = "posix"

    def __init__(self, durable: bool = True):
        # durable=False skips the fsync-before-rename — the ATOMICITY
        # contract is unchanged (temp + rename; readers never see torn
        # blobs, duplicate attempts still overwrite idempotently), only
        # crash DURABILITY is waived.  For ephemeral work dirs only (the
        # CLI's unresumable temp dirs — the same round-5 argument that
        # disables the journal there): a blob lost to a power cut costs
        # a re-run, never corruption.  Resumable/service work dirs keep
        # the default; the dense receipt measured ~0.3 s of fsync per
        # 64 MB job on this box (31 calls x ~10 ms).
        self.durable = durable

    def _sync(self, f) -> None:
        if self.durable:
            _fsync_file(f)

    # --- two-phase internals (FaultStore injects between them) ----------
    def _stage_put(self, path: Path, data: bytes) -> str:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                self._sync(f)
        except BaseException:
            _unlink_quiet(tmp)
            raise
        return tmp

    def _stage_put_from_file(self, path: Path, src: Path,
                             chunk_bytes: int) -> str:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp")
        try:
            with os.fdopen(fd, "wb") as out, open(src, "rb") as f:
                shutil.copyfileobj(f, out, chunk_bytes)
                self._sync(out)
        except BaseException:
            _unlink_quiet(tmp)
            raise
        return tmp

    def _stage_put_from_stream(self, path: Path, stream, length: int,
                               chunk_bytes: int) -> str:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp")
        try:
            with os.fdopen(fd, "wb") as out:
                remaining = length
                while remaining > 0:
                    block = stream.read(min(chunk_bytes, remaining))
                    if not block:
                        raise ConnectionError(
                            f"short body: {remaining} of {length} bytes missing"
                        )
                    out.write(block)
                    remaining -= len(block)
                self._sync(out)
        except BaseException:
            _unlink_quiet(tmp)
            raise
        return tmp

    def _publish_put(self, path: Path, staged: str) -> None:
        try:
            os.replace(staged, path)
        except BaseException:
            _unlink_quiet(staged)
            raise

    # --- Store API ------------------------------------------------------
    def put(self, path: Path, data: bytes) -> None:
        self._publish_put(path, self._stage_put(path, data))

    def put_from_file(self, path: Path, src: Path,
                      chunk_bytes: int = 1 << 20,
                      consume: bool = False) -> None:
        # consume=True: the caller DONATES src (it tolerates the file
        # disappearing) — commit by RENAME instead of a full copy when
        # the filesystems allow (the worker's reduce spool was measured
        # as a second full write of the output, round 8).  Durability is
        # preserved: the durable path fsyncs src IN PLACE first — the
        # same fsync-before-rename ordering the copy path gives.
        # Cross-device renames (EXDEV) fall back to the copy.
        if consume:
            src = Path(src)
            try:
                if self.durable:
                    fd = os.open(src, os.O_RDONLY)
                    try:
                        os.fsync(fd)
                    finally:
                        os.close(fd)
                path = Path(path)
                path.parent.mkdir(parents=True, exist_ok=True)
                os.replace(src, path)
                return
            except OSError:
                pass  # cross-device or permissions: copy below
        self._publish_put(path, self._stage_put_from_file(path, src, chunk_bytes))

    def put_from_stream(self, path: Path, stream, length: int,
                        chunk_bytes: int = 1 << 20) -> None:
        self._publish_put(
            path, self._stage_put_from_stream(path, stream, length, chunk_bytes)
        )

    def get(self, path: Path) -> bytes:
        return Path(path).read_bytes()

    def exists(self, path: Path) -> bool:
        return Path(path).exists()

    def resolve(self, path: Path) -> Optional[Path]:
        path = Path(path)
        return path if path.exists() else None

    def list_committed(self, directory: Path, pattern: str) -> list[Path]:
        return sorted(Path(directory).glob(pattern))

    def commit_task(self, directory: Path, kind: str, task_id: int,
                    attempt: str, payload: dict) -> None:
        rec = dict(payload, kind=kind, task_id=task_id, attempt=attempt)
        self.put(task_commit_path(directory, kind, task_id, attempt),
                 encode_record(rec))

    def resolve_task_commit(self, directory: Path, kind: str,
                            task_id: int) -> Optional[dict]:
        return _resolve_task_commit(self, directory, kind, task_id)


# ------------------------------------------------------------ non-atomic
class NonAtomicStore:
    """Object-store commit semantics on a plain directory: no rename, no
    atomic overwrite — visibility comes from the marker protocol.

    write  : bytes -> <name>.part.<attempt> (plain write + fsync; a crash
             before the fsync returns can leave a torn part with no record)
    publish: <name>.commit.<attempt> — a small self-checksummed record
             naming the attempt and the part's size + crc32.  Emulates the
             atomic small-object PUT every real object store provides.
    resolve: smallest valid attempt whose part exists at the recorded
             size.  Size is re-checked on every resolve (a record without
             its part — e.g. partial cleanup — must not win); the part's
             content crc is recorded for audits but not re-read per
             resolve (the part was fsync'd before its record was
             published, so a valid record implies durable bytes).
    """

    name = "nonatomic"

    # --- two-phase internals --------------------------------------------
    def _stage_put(self, path: Path, data: bytes) -> tuple[Path, str, int, int]:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        attempt = new_attempt_id()
        part = path.parent / f"{path.name}.part.{attempt}"
        with open(part, "wb") as f:
            f.write(data)
            _fsync_file(f)
        return part, attempt, len(data), zlib.crc32(data)

    def _stage_put_stream_like(self, path: Path, writer) -> tuple[Path, str, int, int]:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        attempt = new_attempt_id()
        part = path.parent / f"{path.name}.part.{attempt}"
        crc = 0
        size = 0
        with open(part, "wb") as f:
            for block in writer():
                f.write(block)
                crc = zlib.crc32(block, crc)
                size += len(block)
            _fsync_file(f)
        return part, attempt, size, crc

    def _publish_put(self, path: Path, staged: tuple[Path, str, int, int]) -> None:
        _part, attempt, size, crc = staged
        path = Path(path)
        rec = {"name": path.name, "attempt": attempt, "size": size, "crc": crc}
        marker = path.parent / f"{path.name}.commit.{attempt}"
        with open(marker, "wb") as f:
            f.write(encode_record(rec))
            _fsync_file(f)

    def _stage_put_from_file(self, path: Path, src: Path,
                             chunk_bytes: int) -> tuple[Path, str, int, int]:
        def writer():
            with open(src, "rb") as f:
                while True:
                    block = f.read(chunk_bytes)
                    if not block:
                        return
                    yield block

        return self._stage_put_stream_like(path, writer)

    def _stage_put_from_stream(self, path: Path, stream, length: int,
                               chunk_bytes: int) -> tuple[Path, str, int, int]:
        def writer():
            remaining = length
            while remaining > 0:
                block = stream.read(min(chunk_bytes, remaining))
                if not block:
                    raise ConnectionError(
                        f"short body: {remaining} of {length} bytes missing"
                    )
                remaining -= len(block)
                yield block

        return self._stage_put_stream_like(path, writer)

    # --- Store API ------------------------------------------------------
    def put(self, path: Path, data: bytes) -> None:
        self._publish_put(path, self._stage_put(path, data))

    def put_from_file(self, path: Path, src: Path,
                      chunk_bytes: int = 1 << 20,
                      consume: bool = False) -> None:
        # consume is IGNORED here: the marker protocol's visibility rests
        # on the part file being fully fsync'd under its staged
        # .part.<attempt> name before the commit record lands — a rename
        # shortcut would skip that staging entirely.
        self._publish_put(path, self._stage_put_from_file(path, src, chunk_bytes))

    def put_from_stream(self, path: Path, stream, length: int,
                        chunk_bytes: int = 1 << 20) -> None:
        self._publish_put(
            path, self._stage_put_from_stream(path, stream, length, chunk_bytes)
        )

    def _valid_attempts(self, path: Path) -> list[tuple[str, Path, dict]]:
        """(attempt, part_path, record) for every committed attempt of a
        logical path, sorted by attempt id."""
        path = Path(path)
        out = []
        for marker in path.parent.glob(f"{path.name}.commit.*"):
            attempt = marker.name.rpartition(".commit.")[2]
            try:
                rec = decode_record(marker.read_bytes())
            except OSError:
                continue
            if not rec or rec.get("attempt") != attempt:
                continue
            part = path.parent / f"{path.name}.part.{attempt}"
            try:
                if part.stat().st_size != rec.get("size"):
                    continue  # record without its (whole) part: not a winner
            except OSError:
                continue
            out.append((attempt, part, rec))
        out.sort(key=lambda t: t[0])
        return out

    def resolve(self, path: Path) -> Optional[Path]:
        attempts = self._valid_attempts(path)
        return attempts[0][1] if attempts else None

    def get(self, path: Path) -> bytes:
        p = self.resolve(path)
        if p is None:
            raise FileNotFoundError(f"no committed attempt for {path}")
        return p.read_bytes()

    def exists(self, path: Path) -> bool:
        return self.resolve(path) is not None

    def list_committed(self, directory: Path, pattern: str) -> list[Path]:
        directory = Path(directory)
        logical: dict[str, Path] = {}
        for marker in directory.glob("*.commit.*"):
            name = marker.name.rpartition(".commit.")[0]
            if name in logical or not fnmatch.fnmatchcase(name, pattern):
                continue
            p = self.resolve(directory / name)
            if p is not None:
                logical[name] = p
        return [logical[name] for name in sorted(logical)]

    def commit_task(self, directory: Path, kind: str, task_id: int,
                    attempt: str, payload: dict) -> None:
        directory = Path(directory)
        directory.mkdir(parents=True, exist_ok=True)
        rec = dict(payload, kind=kind, task_id=task_id, attempt=attempt)
        # a small single-block PUT: plain write + fsync.  Tearing is
        # possible — and detectable, because the record self-checksums.
        with open(task_commit_path(directory, kind, task_id, attempt), "wb") as f:
            f.write(encode_record(rec))
            _fsync_file(f)

    def resolve_task_commit(self, directory: Path, kind: str,
                            task_id: int) -> Optional[dict]:
        return _resolve_task_commit(self, directory, kind, task_id)


def _resolve_task_commit(store, directory: Path, kind: str,
                         task_id: int) -> Optional[dict]:
    """Winner pick shared by both stores: smallest attempt whose record
    parses and checksums.  (Task records carry no blob bytes, so there is
    no part file to cross-check — blob visibility is the blob protocol's
    job.)"""
    directory = Path(directory)
    best: Optional[dict] = None
    best_attempt = ""
    for p in directory.glob(f"{kind}-{task_id}.*"):
        attempt = p.name.rpartition(".")[2]
        try:
            rec = decode_record(p.read_bytes())
        except OSError:
            continue
        if not rec or rec.get("kind") != kind or rec.get("task_id") != task_id:
            continue
        if best is None or attempt < best_attempt:
            best, best_attempt = rec, attempt
    return best


# ----------------------------------------------------------------- fault
class FaultStore:
    """Deterministic crash injection around any Store.

    ``hooks`` maps CrashPoint -> callable(ctx).  ctx is the logical blob
    name (puts) or "<kind>-<task_id>" (task commits); the hook raises
    (typically WorkerKilled) to simulate the worker dying at that exact
    instruction, or returns to let the call proceed — so a hook can target
    one phase ("mr-out-*") or one task and fire once.  Exception:
    TORN_COMMIT_RECORD hooks RETURN TRUTHY to inject — FaultStore then
    writes a half-length task commit record and raises WorkerKilled
    itself (the tear and the death are the same event).
    """

    def __init__(self, base: Store, hooks: dict[str, Callable]):
        self.base = base
        self.name = base.name
        self.hooks = dict(hooks)
        unknown = set(self.hooks) - set(CrashPoint.ALL)
        if unknown:
            raise ValueError(f"unknown crash points: {sorted(unknown)}")

    def _fire(self, point: str, ctx: str) -> None:
        hook = self.hooks.get(point)
        if hook:
            hook(ctx)

    # --- blob writes: stage, maybe die, publish -------------------------
    # (both stores expose the same two-phase _stage_put* / _publish_put
    # internals, so injection is store-agnostic)
    def put(self, path: Path, data: bytes) -> None:
        staged = self.base._stage_put(path, data)
        self._fire(CrashPoint.AFTER_TEMP_WRITE, Path(path).name)
        self.base._publish_put(path, staged)

    def put_from_file(self, path: Path, src: Path,
                      chunk_bytes: int = 1 << 20,
                      consume: bool = False) -> None:
        # consume ignored: fault injection needs the two-phase internals
        staged = self.base._stage_put_from_file(path, src, chunk_bytes)
        self._fire(CrashPoint.AFTER_TEMP_WRITE, Path(path).name)
        self.base._publish_put(path, staged)

    def put_from_stream(self, path: Path, stream, length: int,
                        chunk_bytes: int = 1 << 20) -> None:
        staged = self.base._stage_put_from_stream(path, stream, length, chunk_bytes)
        self._fire(CrashPoint.AFTER_TEMP_WRITE, Path(path).name)
        self.base._publish_put(path, staged)

    # --- task commit: the three protocol-interrupting points ------------
    def commit_task(self, directory: Path, kind: str, task_id: int,
                    attempt: str, payload: dict) -> None:
        ctx = f"{kind}-{task_id}"
        self._fire(CrashPoint.BEFORE_COMMIT_RECORD, ctx)
        torn = self.hooks.get(CrashPoint.TORN_COMMIT_RECORD)
        if torn is not None and torn(ctx):
            directory = Path(directory)
            directory.mkdir(parents=True, exist_ok=True)
            rec = encode_record(
                dict(payload, kind=kind, task_id=task_id, attempt=attempt)
            )
            with open(task_commit_path(directory, kind, task_id, attempt),
                      "wb") as f:
                f.write(rec[: len(rec) // 2])
                _fsync_file(f)
            from distributed_grep_tpu.runtime.worker import WorkerKilled

            raise WorkerKilled(f"torn commit record for {ctx}")
        self.base.commit_task(directory, kind, task_id, attempt, payload)
        self._fire(CrashPoint.AFTER_COMMIT_BEFORE_ACK, ctx)

    # --- reads delegate: a dead worker reads nothing --------------------
    def get(self, path: Path) -> bytes:
        return self.base.get(path)

    def exists(self, path: Path) -> bool:
        return self.base.exists(path)

    def resolve(self, path: Path) -> Optional[Path]:
        return self.base.resolve(path)

    def list_committed(self, directory: Path, pattern: str) -> list[Path]:
        return self.base.list_committed(directory, pattern)

    def resolve_task_commit(self, directory: Path, kind: str,
                            task_id: int) -> Optional[dict]:
        return self.base.resolve_task_commit(directory, kind, task_id)


# --------------------------------------------------------------- factory
STORES = {"posix": PosixStore, "nonatomic": NonAtomicStore}


def make_store(name: str, durable: bool = True) -> Store:
    """Store factory for JobConfig.store ("posix" | "nonatomic").

    ``durable=False`` (JobConfig.durable — ephemeral temp work dirs only)
    reaches stores that support waiving fsync (PosixStore); stores whose
    COMMIT protocol depends on fsync ordering (NonAtomicStore's marker
    records) ignore it and stay fully durable."""
    try:
        cls = STORES[name]
    except KeyError:
        raise ValueError(
            f"unknown store {name!r} (choose from {sorted(STORES)})"
        ) from None
    store = cls()
    if not durable and isinstance(store, PosixStore):
        store.durable = False
    return store


def _unlink_quiet(path) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass

"""Durable task-commit journal — coordinator checkpoint/resume.

The reference has no job-level checkpointing; its implicit checkpoint is the
committed mr-* files on disk plus the file->task dedup map (coordinator.go:29,
:53-58) — a coordinator crash loses the job (SURVEY.md §5).  This journal
makes the same rename-commit philosophy durable: every task completion is
appended as one JSON line, fsync'd, and a restarted coordinator replays it
to skip finished work.  Entries carry ``has_record`` when the completion was
committed via a per-task commit record (runtime/store.py) — replay then
re-resolves the record as the unit of truth instead of trusting the journal
line alone (scheduler._replay).

A coordinator crash mid-append can tear the tail line.  Replay reports the
torn tail (warning + byte offset) and excludes it; reopening for append
truncates the file back to the last complete line first, so the next append
starts clean instead of gluing onto half a record.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("journal")


def _scan_valid_prefix(path: Path) -> tuple[list[dict], int, int | None]:
    """(entries, valid_byte_length, torn_offset_or_None) of a journal file.

    A line counts only if it is newline-terminated AND parses as JSON — a
    torn tail that coincidentally parses (e.g. ``{"task_id": 12}`` torn to
    ``{"task_id": 1}``) must not be trusted, and record() always terminates
    lines, so an unterminated tail is torn by definition.  The first bad
    line is the torn point; everything after it is excluded."""
    entries: list[dict] = []
    valid = 0
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    n = len(data)
    while pos < n:
        nl = data.find(b"\n", pos)
        if nl < 0:
            return entries, valid, pos  # unterminated tail: torn
        line = data[pos:nl].strip()
        if line:
            try:
                entries.append(json.loads(line))
            except (json.JSONDecodeError, UnicodeDecodeError):
                return entries, valid, pos  # torn/corrupt line
        pos = nl + 1
        valid = pos
    return entries, valid, None


class TaskJournal:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._truncate_torn_tail()
        self._f = open(self.path, "a", encoding="utf-8")

    def _truncate_torn_tail(self) -> None:
        """Drop a torn tail line before appending — without this, the next
        record() would glue onto the half-written line and corrupt BOTH."""
        if not self.path.exists():
            return
        size = self.path.stat().st_size
        _, valid, torn_at = _scan_valid_prefix(self.path)
        if torn_at is None:
            return
        log.warning(
            "journal %s has a torn tail at byte %d (%d bytes dropped); "
            "truncating so the next append starts on a clean line",
            self.path, torn_at, size - valid,
        )
        with open(self.path, "rb+") as f:
            f.truncate(valid)
            f.flush()
            os.fsync(f.fileno())

    def record(self, entry: dict) -> None:
        self._f.write(json.dumps(entry, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def map_completed(self, task_id: int, file: str, produced_parts: list[int],
                      has_record: bool = False,
                      files: list[str] | None = None) -> None:
        entry = {"kind": "map_done", "task_id": task_id, "file": file,
                 "parts": produced_parts}
        if has_record:
            entry["has_record"] = True
        if files:
            # batched multi-file split: replay must match the member list,
            # not just the display label (scheduler._replay)
            entry["files"] = list(files)
        self.record(entry)

    def reduce_completed(self, task_id: int, has_record: bool = False) -> None:
        entry = {"kind": "reduce_done", "task_id": task_id}
        if has_record:
            entry["has_record"] = True
        self.record(entry)

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def replay(path: str | Path) -> list[dict]:
        p = Path(path)
        if not p.exists():
            return []
        entries, _valid, torn_at = _scan_valid_prefix(p)
        if torn_at is not None:
            # torn tail write from a crash: report it (with the offset a
            # operator needs to inspect the file) and exclude it — the
            # uncommitted task simply re-runs.
            log.warning(
                "journal %s: torn tail at byte %d ignored during replay "
                "(%d complete entries)", p, torn_at, len(entries),
            )
        return entries

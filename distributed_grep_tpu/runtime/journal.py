"""Durable task-commit journal — coordinator checkpoint/resume.

The reference has no job-level checkpointing; its implicit checkpoint is the
committed mr-* files on disk plus the file->task dedup map (coordinator.go:29,
:53-58) — a coordinator crash loses the job (SURVEY.md §5).  This journal
makes the same rename-commit philosophy durable: every task completion is
appended as one JSON line, fsync'd, and a restarted coordinator replays it
to skip finished work (the committed intermediate/output files are still on
disk, so replay is sound).
"""

from __future__ import annotations

import json
import os
from pathlib import Path


class TaskJournal:
    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")

    def record(self, entry: dict) -> None:
        self._f.write(json.dumps(entry, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def map_completed(self, task_id: int, file: str, produced_parts: list[int]) -> None:
        self.record(
            {"kind": "map_done", "task_id": task_id, "file": file, "parts": produced_parts}
        )

    def reduce_completed(self, task_id: int) -> None:
        self.record({"kind": "reduce_done", "task_id": task_id})

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def replay(path: str | Path) -> list[dict]:
        p = Path(path)
        if not p.exists():
            return []
        entries = []
        with open(p, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    entries.append(json.loads(line))
                except json.JSONDecodeError:
                    break  # torn tail write from a crash; ignore the rest
        return entries

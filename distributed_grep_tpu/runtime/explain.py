"""Query-routing reports: why a grep was fast (or slow), from telemetry
already on disk.

The span pipeline persists everything an operator needs to answer "which
kernel family ran, was it pruned/fused/cache-warm, where did the time go"
— but only as a raw ``events.jsonl`` an operator had to replay in
Perfetto.  ``assemble()`` folds one job's event log (plus the job
record's planning tallies) into ONE JSON document: engine modes with
bytes/seconds/matches, host-vs-device routing, index prune counts, fused
attempts, model/corpus cache verdicts, per-stage walls, and task/attempt
accounting.  Served as ``GET /jobs/<id>/explain`` by the service daemon
and rendered by ``dgrep explain`` (and ``dgrep submit --explain``).

Pure Python, no ops imports — the daemon control plane assembles reports
without touching the jax stack (the runtime/fusion.py rule).

Every event name this module matches on is declared in
``analysis/events.py EVENTS`` — the authoritative telemetry vocabulary
(``analyze --events`` renders it).  The ``event-registry`` rule audits
both sides: an emit of an undeclared name and a consumer match here that
no emitter produces are both violations, so emitters and this view
cannot drift apart silently.
"""

from __future__ import annotations

from typing import Any

# Engine modes that run on the host by construction; everything else is a
# device kernel family (shift_and / nfa / fdr / pairset / approx / ...).
_HOST_MODES = ("re", "native")

# Instant-event names folded into the routing verdicts.
_CACHE_INSTANTS = {
    "cache:hit": ("model_cache", "hits"),
    "cache:miss": ("model_cache", "misses"),
    "cache:off": ("model_cache", "bypassed"),
    "corpus:hit": ("corpus_cache", "hits"),
    "corpus:miss": ("corpus_cache", "misses"),
}


def _query_view(app_options: dict) -> dict:
    """The query half of the app options — what was asked, not how."""
    out: dict = {}
    if app_options.get("pattern") is not None:
        out["pattern"] = app_options["pattern"]
    pats = app_options.get("patterns")
    if pats:
        out["patterns"] = len(pats)
    for k in ("ignore_case", "invert", "word_regexp", "line_regexp",
              "max_errors", "count_only", "presence_only", "backend"):
        v = app_options.get(k)
        if v:
            out[k] = v
    return out


def summarize_events(events: list[dict]) -> dict:
    """Aggregate one job's span/event records into routing + stage
    views.  Unknown record shapes are skipped — the report must survive
    event logs written by newer/older peers."""
    modes: dict[str, dict] = {}
    stages: dict[str, dict] = {}
    routing: dict[str, dict] = {}
    fusion = {"fused_plans": 0, "fused_attempts": 0, "max_queries": 0}
    index = {"prunes": 0, "bytes_skipped": 0, "maybes": 0}
    result = {"hits": 0, "partial_hits": 0, "misses": 0,
              "splits_reused": 0, "bytes_unscanned": 0, "revalidations": 0}
    shuffle = {"peer_fetches": 0, "peer_bytes": 0, "relay_fetches": 0,
               "relay_fallbacks": 0, "lost_outputs": 0}
    tasks = {"map_assigns": 0, "reduce_assigns": 0, "timeouts": 0,
             "map_commits": 0, "reduce_commits": 0}
    follow = {"solo_wakes": 0, "fused_wakes": 0, "records": 0}
    device_fallbacks = 0
    degrades = 0
    for r in events:
        name = r.get("name", "")
        t = r.get("t")
        if t == "span":
            args = r.get("args") or {}
            if name.startswith("scan:"):
                row = modes.setdefault(
                    name[len("scan:"):],
                    {"scans": 0, "bytes": 0, "seconds": 0.0, "matches": 0},
                )
                row["scans"] += 1
                row["bytes"] += int(args.get("bytes", 0))
                row["seconds"] += float(r.get("dur", 0.0))
                row["matches"] += int(args.get("matches", 0))
                if args.get("device_fallback"):
                    device_fallbacks += 1
            else:
                row = stages.setdefault(name, {"count": 0, "seconds": 0.0})
                row["count"] += 1
                row["seconds"] += float(r.get("dur", 0.0))
        elif t == "instant":
            hit = _CACHE_INSTANTS.get(name)
            if hit is not None:
                group, key = hit
                routing.setdefault(group, {})[key] = (
                    routing.get(group, {}).get(key, 0) + 1
                )
            elif name == "index:prune":
                index["prunes"] += 1
                index["bytes_skipped"] += int(
                    (r.get("args") or {}).get("bytes", 0)
                )
            elif name == "index:maybe":
                index["maybes"] += 1
            elif name in ("result:hit", "result:partial"):
                args = r.get("args") or {}
                key = "hits" if name == "result:hit" else "partial_hits"
                result[key] += 1
                result["splits_reused"] += int(args.get("splits_reused", 0))
                result["bytes_unscanned"] += int(
                    args.get("bytes_unscanned", 0)
                )
            elif name == "result:miss":
                result["misses"] += 1
            elif name == "result:revalidate":
                result["revalidations"] += 1
            elif name == "fuse:plan":
                fusion["fused_plans"] += 1
                fusion["max_queries"] = max(
                    fusion["max_queries"],
                    int((r.get("args") or {}).get("queries", 0)),
                )
            elif name == "fuse:split":
                fusion["fused_attempts"] += 1
            elif name in ("follow:wake", "fuse:wake"):
                # streaming tier: which wake loop served this standing
                # query — its own solo runner or a fused group (round 21)
                key = "solo_wakes" if name == "follow:wake" else "fused_wakes"
                follow[key] += 1
                follow["records"] += int(
                    (r.get("args") or {}).get("records", 0)
                )
            elif name == "shuffle:peer":
                shuffle["peer_fetches"] += 1
                shuffle["peer_bytes"] += int(
                    (r.get("args") or {}).get("bytes", 0)
                )
            elif name == "shuffle:relay":
                if (r.get("args") or {}).get("fallback"):
                    shuffle["relay_fallbacks"] += 1
                else:
                    shuffle["relay_fetches"] += 1
            elif name == "map_lost_output":
                shuffle["lost_outputs"] += 1
            elif name in ("device_demoted", "device_recovered"):
                degrades += 1
            elif name == "assign_map":
                tasks["map_assigns"] += 1
            elif name == "assign_reduce":
                tasks["reduce_assigns"] += 1
            elif name == "task_timeout":
                tasks["timeouts"] += 1
            elif name == "map_committed":
                tasks["map_commits"] += 1
            elif name == "reduce_committed":
                tasks["reduce_commits"] += 1
    for row in modes.values():
        row["seconds"] = round(row["seconds"], 6)
    for row in stages.values():
        row["seconds"] = round(row["seconds"], 6)
    out: dict = {"modes": modes, "stages": stages, "tasks": tasks}
    out.update(routing)  # model_cache / corpus_cache, present when seen
    if any(fusion.values()):
        out["fusion"] = fusion
    if any(index.values()):
        out["index"] = index
    if any(result.values()):
        # query-result cache (round 20): was this job answered from
        # stored results, wholly or incrementally?  Nonzero-only — a
        # cache-free job's report keeps its pre-round-20 shape.
        out["result_cache"] = result
    if follow["solo_wakes"] or follow["fused_wakes"]:
        # standing-query route verdict: fused means every wake came from
        # a group's shared scan; mixed marks a catch-up/demotion mid-run
        follow["route"] = (
            "fused" if follow["fused_wakes"] and not follow["solo_wakes"]
            else "solo" if follow["solo_wakes"] and not follow["fused_wakes"]
            else "mixed"
        )
        out["follow"] = follow
    if any(shuffle.values()):
        # shuffle route verdict (peer-to-peer shuffle, round 16): which
        # data plane the job's reduce fetches actually rode
        peer_n = shuffle["peer_fetches"]
        relay_n = shuffle["relay_fetches"] + shuffle["relay_fallbacks"]
        shuffle["route"] = (
            "peer" if peer_n and not relay_n
            else "relay" if relay_n and not peer_n
            else "mixed"
        )
        out["shuffle"] = shuffle
    if device_fallbacks:
        out["device_fallbacks"] = device_fallbacks
    if degrades:
        out["device_transitions"] = degrades
    return out


def disruptions_view(daemon_events: list[dict], job_id: str,
                     submitted_at: float | None = None,
                     finished_at: float | None = None) -> dict:
    """Daemon-scope disruptions overlapping one job's lifetime, from the
    fleet timeline (runtime/daemon_log.py): quarantine episodes, this
    job's lost-output revocations, daemon restarts and failovers that
    happened while the job was live.  Nonzero-only — an undisturbed
    job's report keeps its pre-round-19 shape."""
    if not daemon_events:
        return {}
    lo = submitted_at or 0.0
    hi = finished_at if finished_at else float("inf")
    out = {"quarantines": 0, "lost_outputs": 0, "daemon_restarts": 0,
           "failovers": 0}
    max_failover = 0.0
    for r in daemon_events:
        kind = r.get("kind")
        payload = r.get("payload") or {}
        ts = float(r.get("ts", 0.0))
        if kind == "map_lost_output":
            # job-tagged: the revocation names its tenant directly
            if payload.get("job") == job_id:
                out["lost_outputs"] += 1
        elif kind == "quarantine":
            if lo <= ts <= hi:
                out["quarantines"] += 1
        elif kind in ("start", "resume"):
            # strictly after submit: the boot that ADMITTED the job is
            # not a disruption, a restart mid-job is
            if lo < ts <= hi:
                out["daemon_restarts"] += 1
        elif kind == "promoted":
            if lo < ts <= hi:
                out["failovers"] += 1
                max_failover = max(max_failover,
                                   float(payload.get("failover_s", 0.0)))
    view = {k: v for k, v in out.items() if v}
    if max_failover:
        view["max_failover_s"] = round(max_failover, 6)
    return view


def _route_verdict(modes: dict[str, dict], device_fallbacks: int) -> str:
    """host / device / mixed / degraded / unknown — the one-word answer.
    ``scan:batch`` rows are EXCLUDED: a packed flush emits one batch span
    AND the inner engine's own ``scan:<mode>`` span, so the batch row is
    an envelope, not a route — counting it would report a pure-device
    batched job as "mixed"."""
    scored = {name: m for name, m in modes.items()
              if not name.startswith("batch")}
    if not scored:
        return "unknown"
    host = sum(m["scans"] for name, m in scored.items()
               if name in _HOST_MODES)
    device = sum(m["scans"] for name, m in scored.items()
                 if name not in _HOST_MODES)
    if device_fallbacks:
        return "degraded"
    if host and device:
        return "mixed"
    return "device" if device else "host"


def assemble(
    job_id: str,
    config: Any,
    state: str,
    submitted_at: float | None,
    started_at: float | None,
    finished_at: float | None,
    metrics_counters: dict,
    events: list[dict],
    index_shards_pruned: int = 0,
    index_bytes_skipped: int = 0,
    result_splits_reused: int = 0,
    result_bytes_unscanned: int = 0,
    result_revalidations: int = 0,
    daemon_events: list[dict] | None = None,
) -> dict:
    """One job's routing report.  ``config`` is the JobConfig (only the
    application spec and app options are read); ``metrics_counters`` the
    job Metrics piggyback snapshot; planner-side index tallies come from
    the JobRecord (they fire at submit, before any worker span);
    ``daemon_events`` (the fleet timeline, when the daemon log is on)
    feeds the nonzero-only ``disruptions`` section."""
    agg = summarize_events(events)
    modes = agg.pop("modes")
    stages = agg.pop("stages")
    tasks = agg.pop("tasks")
    timing: dict = {}
    if submitted_at and started_at:
        timing["queue_wait_s"] = round(started_at - submitted_at, 6)
    if started_at and finished_at:
        timing["run_s"] = round(finished_at - started_at, 6)
    if submitted_at and finished_at:
        timing["e2e_s"] = round(finished_at - submitted_at, 6)

    routing: dict = {
        "route": _route_verdict(modes, agg.get("device_fallbacks", 0)),
        "engine_modes": modes,
        **agg,  # model_cache/corpus_cache/fusion/index/device_* when seen
    }
    # planner-side prune tallies (fire before any worker span exists);
    # merge over the event view, which only sees engine-side prunes
    if index_shards_pruned:
        idx = routing.setdefault("index", {})
        idx["planner_shards_pruned"] = index_shards_pruned
        idx["planner_bytes_skipped"] = index_bytes_skipped
    # result-cache planner tallies (JobRecord fields — spans-off jobs
    # still report them; with spans on they merge over the instant view)
    if result_splits_reused or result_revalidations:
        res = routing.setdefault("result_cache", {})
        if result_splits_reused:
            res["planner_splits_reused"] = result_splits_reused
            res["planner_bytes_unscanned"] = result_bytes_unscanned
        if result_revalidations:
            res["planner_revalidations"] = result_revalidations

    counters = {
        k: v for k, v in sorted((metrics_counters or {}).items()) if v
    }
    disruptions = disruptions_view(
        daemon_events or [], job_id,
        submitted_at=submitted_at, finished_at=finished_at,
    )
    return {
        "job_id": job_id,
        "state": state,
        "application": getattr(config, "application", ""),
        "query": _query_view(getattr(config, "app_options", {}) or {}),
        "timing": timing,
        "routing": routing,
        "stages": stages,
        "tasks": tasks,
        "metrics": counters,
        # daemon-scope disruptions that overlapped this job (quarantine,
        # lost outputs, restarts/failovers) — nonzero-only, so a quiet
        # job's report keeps its pre-round-19 shape
        **({"disruptions": disruptions} if disruptions else {}),
        # spans off = a skeleton report; say so instead of reading empty
        "spans": bool(events),
    }

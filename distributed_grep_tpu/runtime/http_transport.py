"""Worker-side HTTP transport: long-poll control plane + HTTP data plane.

The client half of http_coordinator.py — implements the Transport protocol
(runtime/transport.py) over urllib, replacing the reference's per-call TCP
dials to a hardcoded coordinator IP (worker.go:220-233) and its SFTP file
pushes.  Unlike the reference worker, which dies via log.Fatal when the
coordinator disappears (worker.go:223), this transport retries transient
errors with backoff and raises CoordinatorGone only after the retry budget,
letting the worker loop exit cleanly (the coordinator vanishing after job
completion is the normal shutdown signal, as in the reference).
"""

from __future__ import annotations

import http.client
import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path

from distributed_grep_tpu.runtime import rpc
from distributed_grep_tpu.utils.config import JobConfig
from distributed_grep_tpu.utils.logging import get_logger

log = get_logger("http_transport")

# Bounded jittered retry policy for transient transport errors — shared by
# every client-side HTTP path (worker RPCs, data-plane GET/PUT, and the
# CLI's client_call).  DGREP_RPC_RETRIES transient failures are retried
# with exponential backoff (base DGREP_RPC_BACKOFF_S, doubling, capped at
# _RETRY_SLEEP_CAP_S) and +/-50% jitter: a daemon restart makes EVERY
# attached worker's in-flight RPC fail at the same instant, and unjittered
# synchronized retries would hammer the recovering daemon in lockstep.
# Retrying is SAFE by construction: task effects commit via idempotent
# per-task commit records (runtime/store.py), completions absorb
# duplicates (scheduler), and span batches dedup on (worker, seq) — a
# replayed request can change nothing a first delivery didn't.
DEFAULT_RPC_RETRIES = 6
DEFAULT_RPC_BACKOFF_S = 0.5
_RETRY_SLEEP_CAP_S = 5.0


def env_rpc_retries(default: int = DEFAULT_RPC_RETRIES) -> int:
    """Transient-error retry count — the ONE parser of DGREP_RPC_RETRIES
    (0 disables retries: first failure raises; malformed or negative
    keeps the default)."""
    raw = os.environ.get("DGREP_RPC_RETRIES")
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        return default
    return v if v >= 0 else default


def env_rpc_backoff_s(default: float = DEFAULT_RPC_BACKOFF_S) -> float:
    """Base retry backoff in seconds — the ONE parser of
    DGREP_RPC_BACKOFF_S (malformed or <= 0 keeps the default)."""
    raw = os.environ.get("DGREP_RPC_BACKOFF_S")
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def retry_delays():
    """The per-call schedule of jittered backoff sleeps (a fresh iterator
    per request): env_rpc_retries() entries, exponential from
    env_rpc_backoff_s(), capped, each scaled by a 0.5-1.5 jitter draw."""
    import random

    base = env_rpc_backoff_s()
    for i in range(env_rpc_retries()):
        yield min(_RETRY_SLEEP_CAP_S, base * (2 ** i)) * random.uniform(0.5, 1.5)


# Exceptions that mean "the peer may be gone / the connection broke" —
# retried under the policy above.  OSError covers URLError, timeouts and
# ConnectionError; HTTPException covers IncompleteRead/BadStatusLine
# (peer died mid-body / mid-status).  HTTPError is deliberately handled
# BEFORE this tuple at every site: the server answered, so liveness is
# fine and a retry would just repeat the rejection.
TRANSIENT_ERRORS = (OSError, http.client.HTTPException)


class CoordinatorGone(OSError):
    """The coordinator stopped answering (the retry schedule ran dry) —
    treat as job over (worker exits).  An OSError subclass: callers
    handling generic connectivity failure (the CLI clients) catch it
    without naming the transport layer."""


def split_addrs(addr: str) -> list[str]:
    """Parse a comma-separated daemon address list ("hostA:port,hostB:port")
    into its members — the ONE place address lists are split (the
    ``net-retry`` analyze rule flags stray copies): failover rotation
    lives inside the shared retry loop below, so every client path —
    worker RPCs, data plane, CLI — inherits it without growing a second
    rotation loop to drift."""
    return [a.strip() for a in str(addr).split(",") if a.strip()]


def _normalize_bases(addr: str) -> list[str]:
    bases = [
        a if a.startswith("http") else f"http://{a}"
        for a in split_addrs(addr)
    ]
    if not bases:
        raise ValueError(f"no address in {addr!r}")
    return [b.rstrip("/") for b in bases]


def _open_with_retries(build_request, timeout: float, desc: str,
                       on_retry=None, deadline: float | None = None,
                       delays=None, rotate_on_503: bool = False) -> bytes:
    """The ONE transient-retry loop every JSON-over-HTTP client call
    shares (worker `_request` and the CLI's `client_call` — the net-retry
    analyze rule exists so no third copy grows): urlopen the freshly
    built request, retry TRANSIENT_ERRORS on the jittered schedule,
    raise CoordinatorGone when it runs dry.  HTTPError passes through
    untouched (the server ANSWERED — disposition is the caller's).
    ``on_retry`` (optional) is called once per retry — the transport
    counts them for the rpc_retries telemetry.

    ``rotate_on_503`` (multi-address callers only): a 503 is the
    StandbyServer's park answer — the one status the real daemon never
    sends (its rejections are 400/404/409/429) — and the standby
    registered NOTHING, so re-sending the same request to the NEXT
    listed address is safe for any method.  It steps through the same
    schedule as a transient failure (on_retry rotates, the backoff
    bounds the both-sides-parked promotion window); a dry schedule
    re-raises the HTTPError so callers' 503 handling still sees the
    code.  Single-address callers keep the strict
    HTTPError-never-retries contract byte-for-byte.

    ``deadline`` (monotonic) bounds the WHOLE call, retries included:
    CLI clients pass their --timeout as a wall-clock promise, and
    against a black-holed host each attempt would otherwise consume the
    full socket timeout — x(retries+1), plus backoff, a one-shot
    `dgrep status --timeout 5` blocking for ~50 s.  Worker transports
    pass None: their budget IS the retry schedule.  ``delays`` overrides
    the schedule (client_call's single-shot mode passes an EMPTY one —
    one loop, one transient classification, no second copy to drift)."""
    if delays is None:
        delays = retry_delays()
    while True:
        attempt_timeout = timeout
        if deadline is not None:
            attempt_timeout = max(0.5, min(timeout,
                                           deadline - time.monotonic()))
        try:
            with urllib.request.urlopen(build_request(),
                                        timeout=attempt_timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if not (rotate_on_503 and e.code == 503):
                raise
            delay = next(delays, None)
            if delay is None or (
                deadline is not None
                and time.monotonic() + delay >= deadline
            ):
                raise
            if on_retry is not None:
                on_retry()
            time.sleep(delay)
        except TRANSIENT_ERRORS as e:
            delay = next(delays, None)
            if delay is None or (
                deadline is not None
                and time.monotonic() + delay >= deadline
            ):
                raise CoordinatorGone(f"{desc}: {e}") from e
            if on_retry is not None:
                on_retry()
            time.sleep(delay)


def fetch_peer_data(endpoint: str, job_id: str, name: str,
                    timeout: float = 30.0, on_retry=None) -> bytes:
    """Fetch one peer-held shuffle file (``GET <endpoint>/shuffle/<job>/
    <name>`` against a worker's PeerDataServer, runtime/peer.py) through
    the SAME bounded-jittered retry loop every client call rides.
    Raises CoordinatorGone when the schedule runs dry (the peer is gone)
    and RuntimeError on an HTTP error status (the peer ANSWERED — a 404
    means the spool entry is gone, not the worker) — both are the
    reducer's declared relay-fallback/lost-output failures, never
    retried harder."""
    base = endpoint if endpoint.startswith("http") else f"http://{endpoint}"
    url = (
        f"{base.rstrip('/')}/shuffle/"
        f"{urllib.parse.quote(job_id or '_', safe='')}/"
        f"{urllib.parse.quote(name, safe='')}"
    )

    def build():
        return urllib.request.Request(url)

    try:
        return _open_with_retries(build, timeout, f"GET {url}", on_retry)
    except urllib.error.HTTPError as e:
        raise RuntimeError(f"GET {url} -> {e.code}") from e


class HttpTransport:
    def __init__(self, addr: str, rpc_timeout_s: float = 60.0):
        # addr: "host:port" or full "http://host:port" — or a COMMA-SEPARATED
        # list of them (active/standby failover, round 18): every retry
        # rotates to the next address, so a worker parked against a dead
        # active finds the promoted standby inside its existing retry
        # budget.  rpc_timeout_s is the client socket timeout; the
        # coordinator derives its long-poll window as half of this (bounded
        # to 30s, http_coordinator.long_poll_window_s) so a healthy idle
        # long-poll always returns before the socket times out.  Pass the
        # job's JobConfig.rpc_timeout_s.
        self._bases = _normalize_bases(addr)
        self._base_i = 0
        self.rpc_timeout_s = rpc_timeout_s
        # Transient retries performed so far, process-lifetime (telemetry:
        # the worker piggybacks it as ``rpc_retries`` so /status shows
        # which workers are fighting their network).  Plain int increments
        # under the GIL — a counter, not a synchronization primitive.
        self.retry_count = 0

    @property
    def base(self) -> str:
        """The address currently in rotation.  Every request builder reads
        it PER ATTEMPT (the retry loop calls build_request each try), so a
        rotation performed by _count_retry lands on the very next attempt."""
        return self._bases[self._base_i]

    # ------------------------------------------------------------- plumbing
    def _count_retry(self) -> None:
        self.retry_count += 1
        if len(self._bases) > 1:
            # failover rotation rides the retry hook: fires BEFORE the
            # backoff sleep, so the next attempt dials the next address.
            # HTTPError never reaches here (the server ANSWERED) — only
            # connectivity failures rotate.
            self._base_i = (self._base_i + 1) % len(self._bases)

    def _sleep_or_give_up(self, delays, desc: str, err: Exception) -> None:
        """One step of the bounded-jittered retry policy: sleep the next
        backoff, or raise CoordinatorGone when the schedule is exhausted.
        (The streaming data-plane paths keep their own loops — spool
        resume / reopen-per-attempt semantics — and step through here.)"""
        delay = next(delays, None)
        if delay is None:
            raise CoordinatorGone(f"{desc}: {err}") from err
        self._count_retry()
        time.sleep(delay)

    def _request(self, method: str, path: str, body: bytes | None = None) -> bytes:
        def build():
            # URL built per attempt: self.base rotates across the address
            # list on every counted retry (failover)
            url = f"{self.base}{path}"
            req = urllib.request.Request(url, data=body, method=method)
            if body is not None:
                req.add_header("Content-Type", "application/json")
            return req

        try:
            return _open_with_retries(build, self.rpc_timeout_s,
                                      f"{method} {path}", self._count_retry,
                                      rotate_on_503=len(self._bases) > 1)
        except urllib.error.HTTPError as e:
            # Server answered: 4xx/5xx are not liveness failures.
            raise RuntimeError(
                f"{method} {path} -> {e.code}: {e.read()[:200]!r}"
            ) from e

    def _rpc(self, verb: str, payload: dict) -> dict:
        data = self._request("POST", f"/rpc/{verb}", json.dumps(payload).encode("utf-8"))
        return json.loads(data)

    # ------------------------------------------------------- control plane
    def assign_task(self, args: rpc.AssignTaskArgs) -> rpc.AssignTaskReply:
        return rpc.AssignTaskReply(**self._rpc(rpc.Verb.ASSIGN_TASK, rpc.to_dict(args)))

    def map_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return rpc.TaskFinishedReply(**self._rpc(rpc.Verb.MAP_FINISHED, rpc.to_dict(args)))

    def reduce_finished(self, args: rpc.TaskFinishedArgs) -> rpc.TaskFinishedReply:
        return rpc.TaskFinishedReply(**self._rpc(rpc.Verb.REDUCE_FINISHED, rpc.to_dict(args)))

    def reduce_next_file(self, args: rpc.ReduceNextFileArgs) -> rpc.ReduceNextFileReply:
        return rpc.ReduceNextFileReply(
            **self._rpc(rpc.Verb.REDUCE_NEXT_FILE, rpc.to_dict(args))
        )

    def heartbeat(self, args: rpc.HeartbeatArgs) -> float | None:
        """Advisory stamp; never raises — transport failure surfaces
        through the task's own RPCs.  Plain stamps are single-shot (a
        missed one costs at most one sweep window, and a retry budget
        inside the progress callback would stall the very work being
        stamped); GRACE stamps get a short bounded retry, because a lost
        grace declaration costs the whole silent phase it covers — the
        caller is about to block on a compile anyway, so a few seconds of
        retry cannot stall anything the compile wasn't already stalling.

        Returns the measured round trip of the successful POST (seconds) —
        retry sleeps excluded, so it is the clean RTT sample the span
        pipeline's clock sync wants — or None when every attempt failed."""
        attempts = 3 if args.grace_s > 0 else 1
        for i in range(attempts):
            if args.sent_at > 0:
                # re-stamp per attempt: a retry shipping the FIRST
                # attempt's sent_at would feed the clock sync a timestamp
                # stale by the failed attempt's timeout, skewing the
                # worker's offset estimate by seconds (spans_seq is
                # unchanged, so the span batch still dedups)
                args.sent_at = time.time()
            body = json.dumps(rpc.to_dict(args)).encode("utf-8")
            try:
                req = urllib.request.Request(
                    f"{self.base}/rpc/{rpc.Verb.HEARTBEAT}", data=body,
                    method="POST",
                )
                req.add_header("Content-Type", "application/json")
                t0 = time.monotonic()
                with urllib.request.urlopen(req, timeout=5.0):
                    return time.monotonic() - t0
            except Exception:  # noqa: BLE001 — advisory by contract
                if i + 1 < attempts:
                    time.sleep(0.5)
        return None

    # ---------------------------------------------------------- data plane
    def _data_path(self, kind: str, name: str) -> str:
        """URL path of one data-plane object.  The service transport
        (ServiceHttpTransport) overrides this with a job-scoped prefix —
        every data-plane method routes through here so the two can never
        diverge on an endpoint."""
        return f"/data/{kind}/{urllib.parse.quote(name, safe='')}"

    def read_input(self, filename: str) -> bytes:
        return self._request("GET", self._data_path("input", filename))

    def read_input_path(self, filename: str):
        """(local_path, is_temp): stream the split to a spool file so the
        worker never holds the whole input in memory (streaming apps then
        scan it in bounded chunks).  Same liveness retry policy as
        _request (incl. IncompleteRead: coordinator died mid-body); a
        partial download is discarded and restarted.  Spool dir: the
        DGREP_SPOOL_DIR env var, else the system temp dir — point it at a
        disk-backed path on hosts where /tmp is RAM-backed tmpfs, or the
        spool itself would consume the RAM the streaming path protects."""
        import errno
        import shutil
        import tempfile

        spool_dir = os.environ.get("DGREP_SPOOL_DIR") or None
        delays = retry_delays()
        tmp = tempfile.NamedTemporaryFile(
            prefix="dgrep-in-", dir=spool_dir, delete=False
        )
        try:
            while True:
                # per-attempt URL: base rotates on counted retries; every
                # address of an HA pair serves the same input split, so a
                # Range resume across the rotation stays exact
                url = f"{self.base}{self._data_path('input', filename)}"
                try:
                    req = urllib.request.Request(url)
                    got = tmp.tell()
                    if got:
                        # resume after a mid-body death: the coordinator
                        # serves 'bytes=N-' prefix ranges (206); a 200 means
                        # no range support — start the spool over
                        req.add_header("Range", f"bytes={got}-")
                    with urllib.request.urlopen(req, timeout=self.rpc_timeout_s) as resp:
                        if got and resp.status != 206:
                            tmp.seek(0)
                            tmp.truncate()
                        shutil.copyfileobj(resp, tmp, length=1 << 20)
                    tmp.close()
                    return Path(tmp.name), True
                except urllib.error.HTTPError as e:
                    raise RuntimeError(f"GET {url} -> {e.code}") from e
                except TRANSIENT_ERRORS as e:
                    # Local disk problems are NOT liveness failures — retrying
                    # the download cannot fix a full spool disk; surface them.
                    if isinstance(e, OSError) and e.errno in (
                        errno.ENOSPC, errno.EDQUOT, errno.EROFS,
                    ):
                        raise
                    self._sleep_or_give_up(delays, f"GET {url}", e)
        except BaseException:
            tmp.close()
            os.unlink(tmp.name)
            raise

    def write_intermediate(self, name: str, data: bytes) -> None:
        self._request("PUT", self._data_path("intermediate", name), data)

    def read_intermediate(self, name: str) -> bytes:
        return self._request("GET", self._data_path("intermediate", name))

    def fetch_peer(self, endpoint: str, job_id: str, name: str) -> bytes:
        """Peer-to-peer shuffle fetch (runtime/peer.py) — a transport
        METHOD (not just the module helper) so the chaos tier's
        FaultTransport can inject drops/delays on exactly this leg."""
        return fetch_peer_data(endpoint, job_id, name,
                               timeout=self.rpc_timeout_s,
                               on_retry=self._count_retry)

    def write_output(self, name: str, data: bytes) -> None:
        self._request("PUT", self._data_path("out", name), data)

    def publish_task_commit(self, kind: str, task_id: int, attempt: str,
                            payload: dict) -> None:
        """Publish the per-task commit record (runtime/store.py) on the
        coordinator's store — the durable commit the scheduler registers
        from, sent BEFORE the finished RPC."""
        name = f"{kind}-{task_id}.{attempt}"
        self._request(
            "PUT", self._data_path("commit", name),
            json.dumps(payload).encode("utf-8"),
        )

    def write_output_from_file(self, name: str, path: str) -> None:
        """Streaming PUT: the body is a file object sent in blocks with an
        explicit Content-Length (http.client streams ~8 KB at a time), so a
        reduce output larger than worker RAM commits without ever being
        held whole.  Same liveness/retry policy as _request; each retry
        reopens the file from the start."""
        size = os.path.getsize(path)
        delays = retry_delays()
        while True:
            url = f"{self.base}{self._data_path('out', name)}"
            try:
                with open(path, "rb") as f:
                    req = urllib.request.Request(url, data=f, method="PUT")
                    req.add_header("Content-Length", str(size))
                    with urllib.request.urlopen(req, timeout=self.rpc_timeout_s):
                        return
            except urllib.error.HTTPError as e:
                raise RuntimeError(
                    f"PUT {url} -> {e.code}: {e.read()[:200]!r}"
                ) from e
            except TRANSIENT_ERRORS as e:
                self._sleep_or_give_up(delays, f"PUT {url}", e)

    # ------------------------------------------------------------ bootstrap
    def fetch_config(self) -> JobConfig:
        return JobConfig(**json.loads(self._request("GET", "/config")))

    def fetch_status(self) -> dict:
        return json.loads(self._request("GET", "/status"))


def client_call(addr: str, method: str, path: str,
                body: bytes | None = None, timeout: float = 30.0,
                retry: bool = True) -> dict:
    """One JSON-over-HTTP client call with the transport's bounded
    jittered retry policy — the helper the CLI's control-plane clients
    (``dgrep submit`` polls, ``dgrep status``) route through instead of
    raw urlopen (analyze rule ``net-retry``).  A transient connection
    reset mid-poll retries instead of killing the client; exhausting the
    schedule raises CoordinatorGone (the caller's daemon-death fallback
    fires); an HTTP error status re-raises immediately as HTTPError (the
    server ANSWERED — submit's 429/400 handling needs the code).

    ``retry=False`` makes the call SINGLE-SHOT (first transient failure
    raises CoordinatorGone): for NON-idempotent requests — job submission
    above all, where a reply lost after the daemon durably registered the
    job would mint a duplicate job on the re-POST.  Only retry what a
    duplicate delivery cannot change.

    ``addr`` may be a comma-separated list (active/standby failover):
    each retry rotates to the next address, so a CLI client pointed at
    both daemons follows a promotion inside its retry budget.  HTTPError
    never rotates — except a 503 (the standby's park answer, which
    registered nothing): rotating past a parked standby to the active
    is exactly what the address list is for."""
    bases = _normalize_bases(addr)
    state = {"i": 0}

    def build():
        url = f"{bases[state['i']]}{path}"
        req = urllib.request.Request(url, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        return req

    def rotate():
        state["i"] = (state["i"] + 1) % len(bases)

    desc = f"{method} {addr}{path}"
    if retry:
        # timeout is the caller's overall wall-clock promise — pass it as
        # the retry loop's deadline too, not just the per-attempt socket
        # timeout (see _open_with_retries)
        return json.loads(
            _open_with_retries(build, timeout, desc, on_retry=rotate,
                               deadline=time.monotonic() + timeout,
                               rotate_on_503=len(bases) > 1)
        )
    # single-shot: the SAME loop with an empty schedule (first transient
    # failure raises CoordinatorGone) — never a second transient-error
    # classification to drift from the retried path
    return json.loads(
        _open_with_retries(build, timeout, desc, delays=iter(()))
    )


def client_text(addr: str, path: str, timeout: float = 30.0) -> str:
    """client_call's text-body sibling for non-JSON GET surfaces —
    ``/metrics`` Prometheus exposition above all (``dgrep top`` scrapes
    it).  Same bounded-jittered retry loop, same address-list rotation
    (transient failures AND the standby's 503 park answer), utf-8
    decoded body returned verbatim."""
    bases = _normalize_bases(addr)
    state = {"i": 0}

    def build():
        return urllib.request.Request(
            f"{bases[state['i']]}{path}", method="GET"
        )

    def rotate():
        state["i"] = (state["i"] + 1) % len(bases)

    return _open_with_retries(
        build, timeout, f"GET {addr}{path}", on_retry=rotate,
        deadline=time.monotonic() + timeout,
        rotate_on_503=len(bases) > 1,
    ).decode("utf-8", "replace")


class ServiceHttpTransport(HttpTransport):
    """HttpTransport against the service daemon (runtime/service.py): the
    control plane is identical, but the data plane is scoped per job —
    ``/data/<job>/<kind>/<name>`` — and follows the worker's current
    assignment via bind_job (runtime/worker._bind_assignment).  A worker
    attached this way serves a STREAM of jobs through one connection."""

    def __init__(self, addr: str, rpc_timeout_s: float = 60.0):
        super().__init__(addr, rpc_timeout_s=rpc_timeout_s)
        self._job = ""

    def bind_job(self, job_id: str) -> None:
        self._job = job_id

    def _data_path(self, kind: str, name: str) -> str:
        if not self._job:
            return super()._data_path(kind, name)
        return (
            f"/data/{urllib.parse.quote(self._job, safe='')}"
            f"/{kind}/{urllib.parse.quote(name, safe='')}"
        )


def run_http_worker(addr: str, n_parallel: int = 1) -> None:
    """CLI worker entry: fetch config, load the application, run task loops.

    The reference worker gets its application as a .so path on argv
    (worker_launch.go:11-19) and everything else from hardcoded constants;
    here the coordinator's /config endpoint supplies both the application
    module spec and the job options.  n_parallel > 1 runs several task loops
    sharing one process — the slot analogue of multiple chips per host.
    """
    import threading

    from distributed_grep_tpu.apps.loader import load_application
    from distributed_grep_tpu.runtime.worker import WorkerLoop

    # Multi-host pod slices: when the standard JAX env vars are present
    # (JAX_COORDINATOR_ADDRESS / _NUM_PROCESSES / _PROCESS_ID), wire
    # jax.distributed before any backend touch so this worker's chips join
    # the global mesh (parallel/multihost.py); single-host runs skip it.
    from distributed_grep_tpu.parallel.multihost import init_distributed

    init_distributed()

    # HA park-and-poll (round 18, runtime/lease.py): probe each address's
    # /status single-shot.  An ACTIVE daemon (no "role" key, or anything
    # but "standby") wins and is moved to the FRONT of the rotation; when
    # only standbys answer, the worker parks and re-polls instead of
    # erroring — the standby will promote within the lease TTL and the
    # same poll finds it.  When NOTHING answers, fall through to the
    # historical path: fetch_config burns the normal retry budget
    # (rotating through the list) and exits via CoordinatorGone.
    bases = split_addrs(addr)
    daemon_status: dict = {}
    while True:
        active = None
        saw_standby = False
        for b in bases:
            try:
                st = client_call(b, "GET", "/status", timeout=5.0,
                                 retry=False)
            except OSError:
                continue
            if st.get("role") == "standby":
                saw_standby = True
                continue
            active = b
            daemon_status = st
            break
        if active is not None:
            addr = ",".join([active] + [b for b in bases if b != active])
            break
        if not saw_standby:
            break
        log.info("all of %s answer standby; parking until one promotes",
                 addr)
        time.sleep(2.0)

    transport = HttpTransport(addr)
    try:
        config = transport.fetch_config()
    except CoordinatorGone:
        log.error("no coordinator at %s", addr)
        raise SystemExit(1)
    # Service daemon detection (runtime/service.py): its /status answers
    # {"service": true}; such workers scope their data plane per job and
    # resolve the application per assignment instead of from /config.
    if not daemon_status:
        try:
            daemon_status = transport.fetch_status()
        except Exception:  # noqa: BLE001 — plain coordinator, no /status
            pass
    is_service = bool(daemon_status.get("service"))
    app = load_application(config.application, **config.app_options)
    transport_cls = ServiceHttpTransport if is_service else HttpTransport
    if is_service:
        log.info("attached to a service daemon at %s", addr)

    from distributed_grep_tpu.utils import spans as spans_mod

    # Peer-to-peer shuffle (round 16, runtime/peer.py): service-attached
    # workers start ONE data server per process (all slots share it) and
    # keep map output on their local spool — the daemon then moves
    # shuffle METADATA only.  Default on for the service, not applicable
    # to one-shot coordinators; DGREP_PEER_SHUFFLE=0 is a true no-op
    # (no server, no spool, pre-peer wire payloads).  Gated on the
    # daemon's /status "peer" capability key: a pre-peer daemon parses
    # AssignTaskArgs with cls(**payload) and would 500 every poll on the
    # unknown peer_endpoint key — with the knob default-ON the worker
    # must not assume support.  A server that cannot bind degrades to
    # the relay data plane instead of refusing to work.
    peer = None
    if is_service and daemon_status.get("peer"):
        from distributed_grep_tpu.runtime.peer import (
            PeerDataServer,
            env_peer_shuffle,
        )

        if env_peer_shuffle():
            try:
                peer = PeerDataServer().start()
            except OSError:
                log.exception(
                    "peer data server failed to start; relay shuffle")
                peer = None

    def run_loop(slot: int) -> None:
        loop = WorkerLoop(
            transport_cls(addr, rpc_timeout_s=config.rpc_timeout_s),
            app,
            reduce_memory_bytes=config.reduce_memory_bytes,
            # config.spill_dir is a coordinator-host path; HTTP workers only
            # honor it when explicitly set (operators ensure it exists)
            spill_dir=config.spill_dir,
            # span pipeline: the coordinator's /config decides (its side
            # persists events.jsonl; a worker shipping spans nobody stores
            # would be pure payload), DGREP_SPANS forces on for debugging
            spans_enabled=spans_mod.enabled(config.spans),
            job_id=config.effective_job_id(),
            peer=peer,
        )
        try:
            loop.run()
        except CoordinatorGone:
            # Coordinator exited (job presumably done) — clean worker exit,
            # unlike the reference's log.Fatal (worker.go:223).
            log.info("slot %d: coordinator gone, exiting", slot)

    threads = [
        threading.Thread(target=run_loop, args=(i,), name=f"slot-{i}") for i in range(n_parallel)
    ]
    for t in threads:
        t.start()
    try:
        for t in threads:
            t.join()
    finally:
        if peer is not None:
            peer.close()
